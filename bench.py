"""End-to-end serving benchmark: continuous-batching decode throughput.

Prints ONE JSON line: {"metric","value","unit","vs_baseline"}.

Runs the full native engine (scheduler + paged KV + fused jitted step) on
the available accelerator with a flagship-shaped Llama (random weights —
throughput is weight-agnostic). ``vs_baseline`` is measured throughput as
a fraction of the single-chip HBM roofline (weights + KV traffic at ~819
GB/s for v5e): 1.0 would mean perfectly bandwidth-bound decode, so higher
is better and the number is comparable across rounds.

Env knobs: DYN_BENCH_PLATFORM=cpu for a tiny smoke run; DYN_BENCH_BATCH,
DYN_BENCH_ISL, DYN_BENCH_OSL to override the workload;
DYN_BENCH_DECODE_STEPS (default 32) fuses that many decode steps per
device dispatch (dispatch latency over the remote-chip tunnel otherwise
dominates the measurement); DYN_BENCH_QUANT=int8|none (default int8 on
TPU: weight-only per-channel int8, which is also what lets the REAL
8B flagship shape fit one 16 GB chip — bf16 does not);
DYN_BENCH_MODEL=8b|3.8b (default 8b: R1-Distill-Llama-8B geometry,
BASELINE.md config 1); DYN_BENCH_KV_DTYPE=bfloat16|int8|float8_e4m3fn
(default int8 — the Pallas decode kernel dequantizes int8 pages
in-register, so the halved KV bytes are pure roofline headroom;
``--kv-dtype`` below records the bf16-vs-int8 delta);
DYN_MATMUL_IMPL=auto|reference|pallas selects the quantized-matmul
path (models/llama.py — auto is the fused dequant Pallas kernels on a
single TPU chip) and the headline JSON records the resolved impl.

The HEADLINE runs overlapped speculative decoding by default
(DYN_BENCH_SPEC=1: spec + the decode pipeline composed at
decode_steps=1 over the int8 KV cache — docs/speculative_decoding.md's
pipelined section; its JSON carries a ``spec`` stanza with drafter,
spec_tokens, accept_rate and draft_hidden_frac). DYN_BENCH_SPEC=0 is
the escape hatch back to the fused-window headline
(DYN_BENCH_DECODE_STEPS windows, no speculation).

``--spec`` switches to the speculative-decoding A/B mode: the same
workload runs once without and once with speculation (both at
decode_steps=1 — speculation replaces fused windows), and the JSON line
reports accept rate, proposed/accepted draft tokens, and out-tok/s for
both sides (vs_baseline = spec/plain throughput ratio). Knobs:
DYN_BENCH_SPEC_DRAFTER (default "ngram"), DYN_BENCH_SPEC_TOKENS
(default 4). Repetitive prompts (the self-drafting sweet spot) via
DYN_BENCH_SPEC_REPEAT=1 — the default keeps the standard random-prompt
workload, where the reported accept rate is an honest floor.

``--spec-overlap`` is the three-way composition A/B at decode_steps=1:
serial spec (overlap off) vs pipelined spec (the composition) vs plain
overlap (spec off) on the identical workload; vs_baseline =
pipelined-spec / serial-spec throughput, with draft_hidden_frac (how
much host draft wall time the pipeline hid under device execution) and
both sides' device_idle_frac reported so the win is measured, not
asserted.

``--matmul`` is the reference-vs-Pallas quantized-matmul A/B at the
headline config: the same workload runs once with
DYN_MATMUL_IMPL=reference (XLA mixed int8×bf16 dot) and once with
=pallas (ops/qmatmul.py fused dequant kernels); vs_baseline =
pallas/reference throughput. ``--kv-dtype`` is the bf16-vs-int8 KV
cache A/B (vs_baseline = int8/bf16). ``--phases`` augments the
headline JSON with a per-phase device-time + HBM-bytes breakdown
(attention / MLP / LM-head / sampling, docs/performance.md): each
phase microbenches the real step computation at the headline geometry
and reports its ideal HBM bytes and the bandwidth its measured time
implies — the roofline gap decomposed instead of guessed at.

``--sentinel`` is the bench regression gate: the headline workload runs
once and its tok/s + per-bucket attribution compare against the
committed ``BENCH_BASELINE.json`` (explicit noise bands; override with
``--baseline PATH`` / ``DYN_BENCH_BASELINE``). Exit 1 on regression,
with the attribution delta naming the bucket that ate the loss; exit 2
when the profile has no baseline (seed with ``--update-baseline``).
``--quick`` shrinks the workload for the CI CPU-interpret smoke tier;
``DYN_SENTINEL_REPORT=path`` writes the report JSON as an artifact.

``--guided`` is the guided-decoding A/B (docs/guided_decoding.md): the
same workload at decode_steps=1 runs once unconstrained and once under
a canned bounded JSON schema whose [B, V] allow-mask rides every
sampling step; vs_baseline = guided/plain throughput — the mask's
hot-path cost as a measured number. A guided-under-spec stanza reports
the accept rate with masks on (proposals filter through the automaton,
the verify step applies identical per-position masks);
DYN_BENCH_GUIDED_SPEC=0 skips it, DYN_BENCH_GUIDED_TOKENIZER points the
mask compiler at a different vocabulary.

``--fanout`` is the frontend host-plane ceiling (no accelerator, no
jax): the real HttpService over a synthetic chat engine, driven with a
non-stream RPS concurrency ladder and a concurrent-SSE stream ladder;
reports the requests/sec ceiling and stream fan-out ceiling with the
server loop's lag p99 per rung and the host-cost ledger's per-stream
breakdown, as ``frontend_fanout_rps`` / ``frontend_fanout_streams``
JSON lines gated against the committed ``cpu-fanout-*`` baseline
profile (exit 1 regression / exit 2 missing profile; ``--quick``,
``--update-baseline``, DYN_SENTINEL_REPORT as with ``--sentinel``).
docs/observability.md "Host data plane" is the reading guide.

``--kvfleet`` is the fleet KV fabric A/B (docs/kvbm.md "Fleet
fabric"; no accelerator, no jax): the canned diurnal trace with
Zipf-popular shared prefix families replays through the fleet
simulator twice — fabric off (every prompt reprefills its shared head)
and fabric on (catalog hits fetch it from a peer's host tier or the
shared bucket) — and reports the fleet prefix hit rate plus the
fraction of the recompute bill avoided, as ``kvfleet_hit_rate`` /
``kvfleet_reprefill_avoided`` JSON lines gated against the committed
``cpu-kvfleet-*`` baseline profile (exit 1 regression / exit 2 missing
profile; ``--quick``, ``--update-baseline``, DYN_SENTINEL_REPORT as
with ``--sentinel``). Knobs: DYN_BENCH_KVFLEET_DURATION /
DYN_BENCH_KVFLEET_SEED.

``--overlap`` is the serial-vs-overlap A/B (docs/performance.md): the
same workload at decode_steps=1 runs once with --no-overlap (fully
serial plan -> dispatch -> sync -> emit) and once with the overlapped
decode pipeline; vs_baseline = overlap/serial throughput, and both
sides report device_idle_frac so the attribution is measured, not
asserted. The headline run also emits device_idle_frac + per-step
overlap stats in its config; DYN_BENCH_OVERLAP=0 forces the serial
loop there (the escape hatch A/B at the headline decode_steps).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The roofline/byte-budget math lives in telemetry/roofline.py now —
# ONE formula shared with the engine's live attribution ledger
# (dynamo_roofline_frac), so the bench artifact and the serving gauges
# can never disagree about the denominator.
from dynamo_tpu.telemetry.roofline import (  # noqa: E402
    HBM_BW_BYTES,
    kv_bytes_per_token as _roofline_kv_bytes_per_token,
    param_bytes as _roofline_param_bytes,
    phase_ideal_bytes as _roofline_phase_ideal_bytes,
)


def _build_config(cpu_mode: bool):
    from dynamo_tpu.models.config import ModelConfig

    if cpu_mode:
        model = ModelConfig(
            vocab_size=2048, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=2048,
        )
        workload = dict(batch=4, isl=32, osl=16, num_blocks=256, block_size=16,
                        quant=os.environ.get("DYN_BENCH_QUANT", "none"),
                        model_name="tiny")
    else:
        quant = os.environ.get("DYN_BENCH_QUANT", "int8")
        bench_model = os.environ.get("DYN_BENCH_MODEL", "8b")
        if bench_model == "8b":
            # the REAL flagship geometry: DeepSeek-R1-Distill-Llama-8B
            # (BASELINE.md config 1). int8 weights ≈ 8 GB -> fits one
            # 16 GB v5e chip WITH a useful KV cache; bf16 (16 GB) does not.
            model = ModelConfig(
                vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                num_hidden_layers=32, num_attention_heads=32,
                num_key_value_heads=8, max_position_embeddings=8192,
            )
        else:
            # ~3.8B shape: the round-1 bf16 reference point
            model = ModelConfig(
                vocab_size=32768, hidden_size=4096, intermediate_size=14336,
                num_hidden_layers=16, num_attention_heads=32,
                num_key_value_heads=8, max_position_embeddings=8192,
            )
        # num_blocks None = auto-size from free HBM after weights load;
        # the fused multi-step scan needs transient headroom, hence the
        # conservative utilization below. block_size 128 = the TPU
        # serving default (MXU-width kernel dots; +20% measured over
        # 16-token pages)
        # batch 64 default: the cohort-admission fix (scheduler.py
        # plan() cohort gate) made wide closed batches pay — windows
        # are weights-bound, so doubling rows nearly doubles tokens
        # per window (measured ladder on-chip: B=32 1514, B=64 2181,
        # B=128 2464 tok/s at p50 TTFT 577/1048/1710 ms; B=64 is the
        # default as the throughput/TTFT balance, DYN_BENCH_BATCH
        # overrides)
        workload = dict(batch=64, isl=128, osl=128, num_blocks=None,
                        block_size=128, quant=quant, model_name=bench_model)
    workload["batch"] = int(os.environ.get("DYN_BENCH_BATCH", workload["batch"]))
    workload["isl"] = int(os.environ.get("DYN_BENCH_ISL", workload["isl"]))
    workload["osl"] = int(os.environ.get("DYN_BENCH_OSL", workload["osl"]))
    workload["block_size"] = int(
        os.environ.get("DYN_BENCH_BLOCK_SIZE", workload["block_size"])
    )
    return model, workload


def _param_bytes(mc, quant: str) -> int:
    return _roofline_param_bytes(mc, quant)


def _bench_kv_dtype() -> str:
    # int8 headline default: the decode kernel reads int8 pages with
    # in-register dequant, so halved KV bytes are pure roofline headroom
    # (the bf16-vs-int8 delta is recorded by --kv-dtype)
    return os.environ.get("DYN_BENCH_KV_DTYPE", "int8")


def _kv_bytes_per_token(mc, kv_dtype: str = None) -> float:
    return _roofline_kv_bytes_per_token(mc, kv_dtype or _bench_kv_dtype())


async def _run(
    model_cfg, wl, spec: bool = False, decode_steps=None, slo=None,
    overlap: bool = True, kv_dtype: str = None, guided: dict = None,
) -> dict:
    """``slo`` = (ttft_ms, itl_ms) targets; when set, the result dict
    gains slo_attainment / goodput_tokens / requests_met from the
    engine's SloTracker (the --chaos mode's scoreboard).

    ``overlap=False`` runs the fully serial step loop (the --no-overlap
    escape hatch) — the A/B baseline for _main_overlap_ab. Every run
    reports ``device_idle_frac``: the OverlapTracker's idle-gap growth
    over the measured window divided by wall time (0.0 = the device
    always had a dispatched step to chew on; the serial loop's value is
    exactly the host plan+unpack+emit share the pipeline removes).

    ``guided`` (a GuidedOptions-shaped dict) runs every request under
    that constraint (docs/guided_decoding.md): the engine loads the
    DYN_BENCH_GUIDED_TOKENIZER vocabulary (default: the tiny test
    tokenizer — mask COST is shape-dependent, not content-dependent),
    prewarms the masked variants, and each request decodes through the
    allow-mask on the serial step path (guided's divert discipline)."""
    if os.environ.get("DYN_STEP_TRACE"):
        # step-trace forensics print via logging.INFO; the bench is a
        # bare script, so wire a handler or the trace silently drops
        import logging

        logging.basicConfig(
            level=logging.INFO, stream=sys.stderr,
            format="%(asctime)s %(name)s: %(message)s",
        )
    import numpy as np

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.protocols.common import (
        GuidedOptions,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    kv_dtype = kv_dtype or _bench_kv_dtype()
    # guided runs need a real tokenizer vocabulary to compile the mask
    # against; the synthetic bench model has none, so the tiny test
    # tokenizer stands in (mask hot-path cost depends on [B, V] shape,
    # not on which ids are allowed)
    guided_tok = os.environ.get(
        "DYN_BENCH_GUIDED_TOKENIZER",
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tests", "data", "tiny_llama_model",
        ),
    )
    cfg = EngineConfig(
        model_path=guided_tok if guided else "",
        model_name="bench", random_weights=True,
        prewarm_guided=bool(guided),
        quantization="int8" if wl["quant"] == "int8" else None,
        kv_cache_dtype=kv_dtype,
        num_blocks=wl["num_blocks"], block_size=wl["block_size"],
        max_batch_size=wl["batch"],
        prefill_chunk_size=int(os.environ.get("DYN_BENCH_PREFILL_CHUNK", "1024")),
        max_model_len=wl["isl"] + wl["osl"] + 8,
        # K=64 windows both raise throughput AND lower p50 TTFT at this
        # closed-batch shape (r4 measured: 1490-1521 tok/s @ ~560 ms vs
        # 1389-1450 @ ~640-780 ms at K=32) — per-window fixed costs
        # amortize over twice the tokens. Serving configs tune their own
        # decode_steps (the sweeps run 32).
        decode_steps=(
            decode_steps
            if decode_steps is not None
            else int(os.environ.get("DYN_BENCH_DECODE_STEPS", "64"))
        ),
        spec_decode=(
            os.environ.get("DYN_BENCH_SPEC_DRAFTER", "ngram") if spec else ""
        ),
        spec_tokens=int(os.environ.get("DYN_BENCH_SPEC_TOKENS", "4")),
        overlap=overlap,
        hbm_utilization=0.7,
        slo_ttft_ms=(slo[0] if slo else None),
        slo_itl_ms=(slo[1] if slo else None),
    )
    # static serving shapes (EngineConfig.static_shapes, default on)
    # pin the decode batch, table width, and prefill buckets so the only
    # reachable step shapes are the ones warmup exercises — compiles
    # are minutes over the chip tunnel.
    print(f"# engine launching (compile ~minutes on first run)", file=sys.stderr, flush=True)
    engine = await JaxEngine.launch(cfg, model_config=model_cfg)
    print("# engine up", file=sys.stderr, flush=True)

    rng = np.random.default_rng(0)
    adapter = engine.as_async_engine()

    repeat_prompts = os.environ.get("DYN_BENCH_SPEC_REPEAT") == "1"

    async def one_request(i: int) -> tuple[float, float, int, list]:
        if repeat_prompts:
            # self-similar prompt (doc-repetition workload): the n-gram
            # drafter's sweet spot — accept rates here show the ceiling
            period = max(8, wl["isl"] // 8)
            unit = rng.integers(
                1, model_cfg.vocab_size, size=period
            ).tolist()
            prompt = (unit * (wl["isl"] // period + 1))[: wl["isl"]]
        else:
            prompt = rng.integers(
                1, model_cfg.vocab_size, size=wl["isl"]
            ).tolist()
        # unique head: avoid total prefix collapse (mod: warmup ids
        # 9000+ must stay inside the CPU smoke model's tiny vocab)
        prompt[0] = (7 + i) % (model_cfg.vocab_size - 1) + 1
        req = PreprocessedRequest(
            request_id=f"bench-{i}",
            token_ids=prompt,
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=wl["osl"], ignore_eos=True),
            guided=GuidedOptions(**guided) if guided else None,
        )
        t_start = time.monotonic()
        t_first = None
        n = 0
        # chunk arrival log (t, tokens_in_chunk): fused windows deliver
        # tokens in bursts, so per-token ITL is each gap amortized over
        # the chunk it delivered
        arrivals: list[tuple[float, int]] = []
        async for item in adapter.generate(req, Context()):
            if item.token_ids:
                now = time.monotonic()
                if t_first is None:
                    t_first = now
                arrivals.append((now, len(item.token_ids)))
            n += len(item.token_ids)
        return t_start, t_first or time.monotonic(), n, arrivals

    # warmup at FULL batch: the measurement's shapes (batched prefill at
    # B=batch, decode at the batch bucket) must compile now, not inside
    # the timed run
    await asyncio.gather(*[one_request(9000 + i) for i in range(wl["batch"])])
    print("# warmup done; measuring", file=sys.stderr, flush=True)

    idle0 = engine.overlap.stats()
    t0 = time.monotonic()
    results = await asyncio.gather(*[one_request(i) for i in range(wl["batch"])])
    t1 = time.monotonic()
    idle1 = engine.overlap.stats()
    total_tokens = sum(r[2] for r in results)
    ttfts = [r[1] - r[0] for r in results]
    # per-token ITL samples across all requests: each inter-chunk gap
    # contributes one sample per token it delivered (tail percentiles
    # are what BENCH_* files exist to capture — p50 hides the stalls)
    itls: list[float] = []
    for _, _, _, arrivals in results:
        for (t_prev, _), (t_cur, k) in zip(arrivals, arrivals[1:]):
            if k > 0:
                itls.extend([(t_cur - t_prev) / k] * k)
    wall = t1 - t0
    tput = total_tokens / wall

    # roofline: per decode step, read all weights once + each seq's KV
    avg_ctx = wl["isl"] + wl["osl"] / 2
    step_bytes = _param_bytes(model_cfg, wl["quant"]) + wl["batch"] * avg_ctx * _kv_bytes_per_token(model_cfg, kv_dtype)
    roofline_tput = wl["batch"] / (step_bytes / HBM_BW_BYTES)

    # device-idle attribution over the MEASURED window only (warmup
    # compiles would otherwise swamp the number): the fraction of wall
    # time the device provably sat without a dispatched step while the
    # host did serial work (telemetry/overlap.py — a host-observable
    # lower bound; exact for the serial loop)
    idle_s = idle1["idle_gap_s_total"] - idle0["idle_gap_s_total"]
    steps = idle1["steps_dispatched"] - idle0["steps_dispatched"]
    overlap_stats = {
        "device_idle_frac": round(max(0.0, idle_s) / max(wall, 1e-9), 4),
        "idle_gap_s_total": round(max(0.0, idle_s), 4),
        "steps_dispatched": steps,
        "idle_gap_ms_per_step": round(
            max(0.0, idle_s) * 1e3 / max(steps, 1), 3
        ),
        # the tracker's max is lifetime-wide: report it only when it
        # GREW during the window (the new max happened in-measurement);
        # 0.0 otherwise, so a warmup-era gap never masquerades as the
        # measured run's worst step
        "max_idle_gap_ms": (
            idle1["max_idle_gap_ms"]
            if idle1["max_idle_gap_ms"] > idle0["max_idle_gap_ms"]
            else 0.0
        ),
        "overlap_enabled": overlap,
    }
    spec_proposed = engine.spec_proposed_total
    spec_accepted = engine.spec_accepted_total
    # overlapped spec pipeline accounting (docs/speculative_decoding.md):
    # fraction of host draft wall time hidden under device execution
    hid = engine.spec_draft_hidden_s_total
    exp = engine.spec_draft_exposed_s_total
    spec_hidden_frac = round(hid / (hid + exp), 4) if (hid + exp) > 0 else 0.0
    slo_stats = engine.slo.stats()
    # live perf attribution (telemetry/attribution.py): the ledger's
    # rolling window over the run — loss-bucket fractions plus the
    # live roofline_frac computed from the SAME formula as the
    # "roofline" denominator below (telemetry/roofline.py)
    attribution = engine.attribution.window_summary()
    # resolve the matmul impl WHILE the engine's mesh is registered:
    # shutdown clears it, after which auto would misreport "reference"
    # on multi-device hosts for a run that used the Pallas kernels
    matmul_impl = _resolved_matmul_impl()
    await engine.shutdown()
    return {
        "slo": slo_stats,
        "attribution": attribution,
        "overlap": overlap_stats,
        "kv_dtype": kv_dtype,
        "matmul_impl": matmul_impl,
        "tput": tput,
        "p50_ttft_s": _percentile(ttfts, 50),
        "p90_ttft_s": _percentile(ttfts, 90),
        "p99_ttft_s": _percentile(ttfts, 99),
        "p50_itl_s": _percentile(itls, 50),
        "p90_itl_s": _percentile(itls, 90),
        "p99_itl_s": _percentile(itls, 99),
        "total_tokens": total_tokens,
        "wall_s": wall,
        "roofline": roofline_tput,
        "spec_proposed": spec_proposed,
        "spec_accepted": spec_accepted,
        "spec_draft_hidden_frac": spec_hidden_frac,
    }


def _percentile(samples: list, p: float) -> float:
    """Nearest-rank percentile (0.0 on an empty sample set)."""
    if not samples:
        return 0.0
    import math

    s = sorted(samples)
    # true ceil — round() is round-half-to-even, which overshoots the
    # rank (to the max) whenever p*N/100 lands on an integer
    k = min(len(s) - 1, max(0, math.ceil(p / 100.0 * len(s)) - 1))
    return s[k]


def _main_spec_ab(model_cfg, wl) -> None:
    """--spec: A/B the same workload with and without speculation (both
    at decode_steps=1) and report accept rate + both throughputs."""
    base = asyncio.run(_run(model_cfg, wl, spec=False, decode_steps=1))
    spec = asyncio.run(_run(model_cfg, wl, spec=True, decode_steps=1))
    proposed, accepted = spec["spec_proposed"], spec["spec_accepted"]
    out = {
        "metric": "engine_spec_decode_ab_1chip",
        "value": round(spec["tput"], 2),
        "unit": "tokens/sec",
        # spec vs plain decode on the identical workload: > 1.0 means
        # speculation converted spare decode FLOPs into tokens/step
        "vs_baseline": round(spec["tput"] / max(base["tput"], 1e-9), 4),
        "config": {
            "model": wl["model_name"],
            "batch": wl["batch"],
            "isl": wl["isl"],
            "osl": wl["osl"],
            "drafter": os.environ.get("DYN_BENCH_SPEC_DRAFTER", "ngram"),
            "spec_tokens": int(os.environ.get("DYN_BENCH_SPEC_TOKENS", "4")),
            "repeat_prompts": os.environ.get("DYN_BENCH_SPEC_REPEAT") == "1",
            "plain_tok_s": round(base["tput"], 2),
            "spec_tok_s": round(spec["tput"], 2),
            "proposed_tokens": proposed,
            "accepted_tokens": accepted,
            "accept_rate": round(accepted / proposed, 4) if proposed else 0.0,
            "p50_ttft_ms_plain": round(base["p50_ttft_s"] * 1000, 1),
            "p50_ttft_ms_spec": round(spec["p50_ttft_s"] * 1000, 1),
            "p99_ttft_ms_plain": round(base["p99_ttft_s"] * 1000, 1),
            "p99_ttft_ms_spec": round(spec["p99_ttft_s"] * 1000, 1),
            "p99_itl_ms_plain": round(base["p99_itl_s"] * 1000, 2),
            "p99_itl_ms_spec": round(spec["p99_itl_s"] * 1000, 2),
        },
    }
    print(json.dumps(out))
    print(
        f"# spec A/B: plain={base['tput']:.1f} spec={spec['tput']:.1f} tok/s "
        f"accept={out['config']['accept_rate']:.2%} "
        f"({accepted}/{proposed} drafts)",
        file=sys.stderr,
    )


def _main_spec_overlap_ab(model_cfg, wl) -> None:
    """--spec-overlap: the composition A/B (docs/speculative_decoding.md
    pipelined section). Three runs of the identical workload at
    decode_steps=1: serial spec (drafting fully exposed as device
    idle), pipelined spec (drafting hidden under the in-flight verify),
    and plain overlap (no speculation — the floor the composition must
    beat for spec to earn its verify rectangle). vs_baseline =
    pipelined-spec / serial-spec throughput; draft_hidden_frac is the
    measured fraction of draft wall time the pipeline hid."""
    serial = asyncio.run(
        _run(model_cfg, wl, spec=True, decode_steps=1, overlap=False)
    )
    piped = asyncio.run(
        _run(model_cfg, wl, spec=True, decode_steps=1, overlap=True)
    )
    plain = asyncio.run(
        _run(model_cfg, wl, spec=False, decode_steps=1, overlap=True)
    )
    prop, acc = piped["spec_proposed"], piped["spec_accepted"]
    out = {
        "metric": "engine_spec_overlap_ab_1chip",
        "value": round(piped["tput"], 2),
        "unit": "tokens/sec",
        # pipelined vs serial spec on the identical workload: > 1.0
        # means the double-buffered schedule converted exposed host
        # draft time into device work
        "vs_baseline": round(piped["tput"] / max(serial["tput"], 1e-9), 4),
        "config": {
            "model": wl["model_name"],
            "batch": wl["batch"],
            "isl": wl["isl"],
            "osl": wl["osl"],
            "drafter": os.environ.get("DYN_BENCH_SPEC_DRAFTER", "ngram"),
            "spec_tokens": int(os.environ.get("DYN_BENCH_SPEC_TOKENS", "4")),
            "repeat_prompts": os.environ.get("DYN_BENCH_SPEC_REPEAT") == "1",
            "serial_spec_tok_s": round(serial["tput"], 2),
            "pipelined_spec_tok_s": round(piped["tput"], 2),
            "plain_overlap_tok_s": round(plain["tput"], 2),
            "accept_rate": round(acc / prop, 4) if prop else 0.0,
            "proposed_tokens": prop,
            "accepted_tokens": acc,
            "draft_hidden_frac": piped["spec_draft_hidden_frac"],
            "serial_device_idle_frac":
                serial["overlap"]["device_idle_frac"],
            "pipelined_device_idle_frac":
                piped["overlap"]["device_idle_frac"],
            "p99_itl_ms_serial_spec": round(serial["p99_itl_s"] * 1000, 2),
            "p99_itl_ms_pipelined_spec": round(piped["p99_itl_s"] * 1000, 2),
            "p99_itl_ms_plain_overlap": round(plain["p99_itl_s"] * 1000, 2),
        },
    }
    print(json.dumps(out))
    print(
        f"# spec-overlap A/B: serial-spec={serial['tput']:.1f} "
        f"pipelined-spec={piped['tput']:.1f} "
        f"plain-overlap={plain['tput']:.1f} tok/s, "
        f"accept={out['config']['accept_rate']:.2%}, "
        f"draft_hidden={piped['spec_draft_hidden_frac']:.2%}",
        file=sys.stderr,
    )


# canned bench schema: bounded everywhere (strings capped, enum moods,
# boolean) so a random-weights model always terminates the document —
# what the A/B measures is the mask's hot-path cost, not schema luck
GUIDED_BENCH_SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"type": "string", "maxLength": 8},
        "ok": {"type": "boolean"},
        "mood": {"enum": ["happy", "sad", "neutral"]},
        "score": {"type": "string", "pattern": "[0-9]{1,3}"},
    },
    "required": ["name", "ok", "mood", "score"],
}


def _main_guided_ab(model_cfg, wl) -> None:
    """--guided: unconstrained vs schema-masked A/B at decode_steps=1
    (docs/guided_decoding.md) — the mask's hot-path cost as a measured
    number: per step the engine builds a [B, V] bool mask on host,
    ships it with the batch, and the jitted step drops disallowed
    logits to -inf before sampling. vs_baseline = guided/plain
    throughput on the identical workload (< 1.0 by the mask's cost;
    the gap IS the number). A guided-under-spec stanza reports the
    accept rate with masks on (drafts filter through the automaton
    before the verify step applies identical per-position masks);
    DYN_BENCH_GUIDED_SPEC=0 skips it."""
    guided_spec = {"kind": "json_schema", "json_schema": GUIDED_BENCH_SCHEMA}
    plain = asyncio.run(_run(model_cfg, wl, decode_steps=1))
    guided = asyncio.run(
        _run(model_cfg, wl, decode_steps=1, guided=guided_spec)
    )
    cfg = {
        "model": wl["model_name"],
        "batch": wl["batch"],
        "isl": wl["isl"],
        "osl": wl["osl"],
        "schema": "bench-canned-v1",
        "plain_tok_s": round(plain["tput"], 2),
        "guided_tok_s": round(guided["tput"], 2),
        "p99_itl_ms_plain": round(plain["p99_itl_s"] * 1000, 2),
        "p99_itl_ms_guided": round(guided["p99_itl_s"] * 1000, 2),
        "guided_device_idle_frac": guided["overlap"]["device_idle_frac"],
    }
    if os.environ.get("DYN_BENCH_GUIDED_SPEC", "1") != "0":
        gspec = asyncio.run(
            _run(model_cfg, wl, spec=True, decode_steps=1, guided=guided_spec)
        )
        prop, acc = gspec["spec_proposed"], gspec["spec_accepted"]
        cfg["spec"] = {
            "guided_spec_tok_s": round(gspec["tput"], 2),
            "proposed_tokens": prop,
            "accepted_tokens": acc,
            "accept_rate": round(acc / prop, 4) if prop else 0.0,
            "drafter": os.environ.get("DYN_BENCH_SPEC_DRAFTER", "ngram"),
            "spec_tokens": int(os.environ.get("DYN_BENCH_SPEC_TOKENS", "4")),
        }
    out = {
        "metric": "engine_guided_ab_1chip",
        "value": round(guided["tput"], 2),
        "unit": "tokens/sec",
        "vs_baseline": round(guided["tput"] / max(plain["tput"], 1e-9), 4),
        "config": cfg,
    }
    print(json.dumps(out))
    spec_note = (
        f" spec-accept={cfg['spec']['accept_rate']:.2%}"
        if "spec" in cfg else ""
    )
    print(
        f"# guided A/B: plain={plain['tput']:.1f} "
        f"guided={guided['tput']:.1f} tok/s "
        f"(x{out['vs_baseline']:.3f}){spec_note}",
        file=sys.stderr,
    )


def _main_overlap_ab(model_cfg, wl) -> None:
    """--overlap: serial-vs-overlap A/B at decode_steps=1 — the shape
    where the host's per-step plan+unpack+emit time is fully exposed,
    so the pipeline's contribution is attributable. vs_baseline is
    overlap/serial throughput on the identical workload; both sides
    report device_idle_frac (the serial side's value IS the host share
    the pipeline exists to hide — if it were ~0 there would be nothing
    to win and the A/B honestly reports that)."""
    serial = asyncio.run(
        _run(model_cfg, wl, decode_steps=1, overlap=False)
    )
    over = asyncio.run(_run(model_cfg, wl, decode_steps=1, overlap=True))
    out = {
        "metric": "engine_overlap_decode_ab_1chip",
        "value": round(over["tput"], 2),
        "unit": "tokens/sec",
        # overlapped pipeline vs the serial loop on the identical
        # workload: > 1.0 means the double-buffered host schedule
        # converted device idle gaps into tokens
        "vs_baseline": round(over["tput"] / max(serial["tput"], 1e-9), 4),
        "config": {
            "model": wl["model_name"],
            "batch": wl["batch"],
            "isl": wl["isl"],
            "osl": wl["osl"],
            "serial_tok_s": round(serial["tput"], 2),
            "overlap_tok_s": round(over["tput"], 2),
            "serial_device_idle_frac":
                serial["overlap"]["device_idle_frac"],
            "overlap_device_idle_frac":
                over["overlap"]["device_idle_frac"],
            "serial_idle_gap_ms_per_step":
                serial["overlap"]["idle_gap_ms_per_step"],
            "overlap_idle_gap_ms_per_step":
                over["overlap"]["idle_gap_ms_per_step"],
            "p50_itl_ms_serial": round(serial["p50_itl_s"] * 1000, 2),
            "p50_itl_ms_overlap": round(over["p50_itl_s"] * 1000, 2),
            "p99_itl_ms_serial": round(serial["p99_itl_s"] * 1000, 2),
            "p99_itl_ms_overlap": round(over["p99_itl_s"] * 1000, 2),
        },
    }
    print(json.dumps(out))
    print(
        f"# overlap A/B: serial={serial['tput']:.1f} "
        f"overlap={over['tput']:.1f} tok/s, device_idle_frac "
        f"{serial['overlap']['device_idle_frac']:.3f} -> "
        f"{over['overlap']['device_idle_frac']:.3f}",
        file=sys.stderr,
    )


def _resolved_matmul_impl() -> str:
    from dynamo_tpu.models.llama import matmul_impl

    return matmul_impl()


def _main_matmul_ab(model_cfg, wl) -> None:
    """--matmul: reference-vs-Pallas quantized-matmul A/B at the
    headline config (same workload, same decode_steps). vs_baseline =
    pallas/reference throughput — > 1.0 means the in-register dequant
    kernels converted int8 weight bytes into tokens the XLA mixed-dtype
    dot could not. Off-TPU the Pallas side runs interpreted (a
    correctness smoke, not a speed number — the JSON records the
    backend so nobody reads a CPU ratio as a win)."""
    os.environ["DYN_MATMUL_IMPL"] = "reference"
    ref = asyncio.run(_run(model_cfg, wl))
    os.environ["DYN_MATMUL_IMPL"] = "pallas"
    try:
        pal = asyncio.run(_run(model_cfg, wl))
    finally:
        os.environ.pop("DYN_MATMUL_IMPL", None)
    import jax

    out = {
        "metric": "engine_matmul_ab_1chip",
        "value": round(pal["tput"], 2),
        "unit": "tokens/sec",
        "vs_baseline": round(pal["tput"] / max(ref["tput"], 1e-9), 4),
        "config": {
            "model": wl["model_name"],
            "batch": wl["batch"],
            "isl": wl["isl"],
            "osl": wl["osl"],
            "quant": wl["quant"],
            "kv_dtype": ref["kv_dtype"],
            "backend": jax.default_backend(),
            "reference_tok_s": round(ref["tput"], 2),
            "pallas_tok_s": round(pal["tput"], 2),
            "p50_itl_ms_reference": round(ref["p50_itl_s"] * 1000, 2),
            "p50_itl_ms_pallas": round(pal["p50_itl_s"] * 1000, 2),
            "p99_itl_ms_reference": round(ref["p99_itl_s"] * 1000, 2),
            "p99_itl_ms_pallas": round(pal["p99_itl_s"] * 1000, 2),
        },
    }
    print(json.dumps(out))
    print(
        f"# matmul A/B: reference={ref['tput']:.1f} "
        f"pallas={pal['tput']:.1f} tok/s "
        f"(x{out['vs_baseline']:.3f})",
        file=sys.stderr,
    )


def _main_kv_dtype_ab(model_cfg, wl) -> None:
    """--kv-dtype: bf16-vs-int8 KV cache A/B at the headline config.
    vs_baseline = int8/bf16 throughput — the record of what flipping
    the headline default to the quantized cache actually bought (the
    decode kernel reads int8 pages + scales either way; only the cache
    bytes change)."""
    bf16 = asyncio.run(_run(model_cfg, wl, kv_dtype="bfloat16"))
    int8 = asyncio.run(_run(model_cfg, wl, kv_dtype="int8"))
    avg_ctx = wl["isl"] + wl["osl"] / 2
    out = {
        "metric": "engine_kv_dtype_ab_1chip",
        "value": round(int8["tput"], 2),
        "unit": "tokens/sec",
        "vs_baseline": round(int8["tput"] / max(bf16["tput"], 1e-9), 4),
        "config": {
            "model": wl["model_name"],
            "batch": wl["batch"],
            "isl": wl["isl"],
            "osl": wl["osl"],
            "quant": wl["quant"],
            "matmul_impl": int8["matmul_impl"],
            "bf16_tok_s": round(bf16["tput"], 2),
            "int8_tok_s": round(int8["tput"], 2),
            # the byte story behind the ratio: per-step KV traffic at
            # the workload's average context, both dtypes
            "kv_bytes_per_step_bf16": int(
                wl["batch"] * avg_ctx
                * _kv_bytes_per_token(model_cfg, "bfloat16")
            ),
            "kv_bytes_per_step_int8": int(
                wl["batch"] * avg_ctx
                * _kv_bytes_per_token(model_cfg, "int8")
            ),
            "p50_itl_ms_bf16": round(bf16["p50_itl_s"] * 1000, 2),
            "p50_itl_ms_int8": round(int8["p50_itl_s"] * 1000, 2),
            "p99_itl_ms_bf16": round(bf16["p99_itl_s"] * 1000, 2),
            "p99_itl_ms_int8": round(int8["p99_itl_s"] * 1000, 2),
        },
    }
    print(json.dumps(out))
    print(
        f"# kv-dtype A/B: bf16={bf16['tput']:.1f} int8={int8['tput']:.1f} "
        f"tok/s (x{out['vs_baseline']:.3f})",
        file=sys.stderr,
    )


def _phase_breakdown(model_cfg, wl, kv_dtype: str) -> dict:
    """Decompose one decode step's device time into attention / MLP /
    LM-head / sampling by microbenching each phase's REAL computation
    (the serving params and cache geometry, the serving kernels) at the
    headline shape. Per phase: measured device ms, the ideal HBM bytes
    that phase must move, and the bandwidth the measured time implies —
    achieved-vs-ideal, so the roofline gap names its owner instead of
    being guessed at. ``step_ms_sum`` vs the engine-measured step time
    shows how much of a real step the decomposition accounts for."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.quant import init_params_quantized

    mc = model_cfg
    B = wl["batch"]
    bs = wl["block_size"]
    avg_ctx = int(wl["isl"] + wl["osl"] / 2)
    L, D, F, V = (
        mc.num_hidden_layers, mc.hidden_size, mc.intermediate_size,
        mc.vocab_size,
    )
    H, Hk, Dh = mc.num_attention_heads, mc.num_key_value_heads, mc.head_dim
    quant = wl["quant"] == "int8"
    params = (
        init_params_quantized(mc, seed=0) if quant
        else llama.init_params(mc, seed=0)
    )
    # register a size-1 mesh exactly like the single-chip engine does,
    # so matmul_impl/pallas_matmul_active resolve HERE the same way
    # they did inside the headline run (the engine cleared the mesh at
    # shutdown; without this, multi-device hosts would microbench the
    # reference path while the headline ran the fused kernels)
    from jax.sharding import Mesh

    prev_mesh = llama.get_attention_mesh()
    llama.set_attention_mesh(
        Mesh(
            np.asarray(jax.devices()[:1]).reshape(1, 1, 1, 1),
            ("dp", "pp", "tp", "ep"),
        )
    )

    blocks_per_seq = -(-avg_ctx // bs)
    num_blocks = B * blocks_per_seq + 1
    cache_dt = {"int8": jnp.int8, "bfloat16": jnp.bfloat16}.get(
        kv_dtype, jnp.bfloat16
    )
    k_cache, v_cache = llama.init_cache(mc, num_blocks, bs, dtype=cache_dt)
    tables = np.asarray(
        1 + np.arange(B * blocks_per_seq).reshape(B, blocks_per_seq),
        np.int32,
    )
    ctx = np.full((B,), avg_ctx, np.int32)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, Dh)), jnp.bfloat16)
    x_dec = jnp.asarray(rng.standard_normal((B, 1, D)), jnp.bfloat16)
    x_last = x_dec[:, 0]
    logits = jnp.asarray(rng.standard_normal((B, V)), jnp.float32)

    interpret = jax.default_backend() != "tpu"
    lp = {
        k: params[k][0] if params[k].shape[0] == L else params[k]
        for k in llama.layer_param_names(params)
    }

    def attn_layer(q, kc, vc, t, c):
        from dynamo_tpu.ops.paged_attention import (
            paged_attention_decode_stacked,
        )

        ksc = vsc = None
        if llama.kv_cache_is_quantized(kc):
            (kc, ksc), (vc, vsc) = kc, vc
        return paged_attention_decode_stacked(
            q, kc, vc, jnp.int32(0), t, c, block_size=bs,
            interpret=interpret, k_scale=ksc, v_scale=vsc,
        )

    def mlp_full(x):
        """One layer's complete matmul set at the decode shape: the
        qkv projections feed wq's output through the SHARED
        post-attention chain (llama.post_attn_mlp — the exact served
        composition, fused Pallas epilogues and all; attention itself
        is the phase above). k/v are returned so DCE cannot drop their
        weight reads from the measurement."""
        h = llama.rmsnorm(x, lp["attn_norm"], mc.rms_norm_eps)
        a = llama.mm(lp, "wq", h)
        k = llama.mm(lp, "wk", h)
        v = llama.mm(lp, "wv", h)
        return llama.post_attn_mlp(mc, lp, x, a), k, v

    def lm_head_fn(x):
        return llama.lm_head(params, x)

    def sample_fn(lg):
        lse = jax.nn.logsumexp(lg, axis=-1)
        tok = jnp.argmax(lg, axis=-1)
        return tok, jnp.take_along_axis(lg, tok[:, None], 1)[:, 0] - lse

    def timed(fn, *args, reps: int = 5) -> float:
        f = jax.jit(fn)
        out = f(*args)
        jax.block_until_ready(out)  # compile outside the clock
        best = float("inf")
        for _ in range(reps):
            t0 = _time.monotonic()
            out = f(*args)
            jax.block_until_ready(out)
            best = min(best, _time.monotonic() - t0)
        return best

    try:
        t_attn1 = timed(
            attn_layer, q, k_cache, v_cache, jnp.asarray(tables),
            jnp.asarray(ctx),
        )
        t_mlp1 = timed(mlp_full, x_dec)
        t_lm = timed(lm_head_fn, x_last)
        t_sample = timed(sample_fn, logits)
    finally:
        llama.set_attention_mesh(prev_mesh)

    # per-phase byte budget from the SHARED roofline model
    # (telemetry/roofline.py) — the same prior the serving-side
    # attribution ledger splits device time with, so --phases and
    # /debug/attribution decompose against identical denominators
    ideal = _roofline_phase_ideal_bytes(
        mc, B, avg_ctx, "int8" if quant else None, kv_dtype
    )
    phases = {
        "attention": {
            "device_ms": round(t_attn1 * L * 1e3, 3),
            "ideal_bytes": ideal["attention"],
        },
        "mlp": {
            "device_ms": round(t_mlp1 * L * 1e3, 3),
            "ideal_bytes": ideal["mlp"],
        },
        "lm_head": {
            "device_ms": round(t_lm * 1e3, 3),
            "ideal_bytes": ideal["lm_head"],
        },
        "sampling": {
            "device_ms": round(t_sample * 1e3, 3),
            "ideal_bytes": ideal["sampling"],
        },
    }
    for ph in phases.values():
        dt = ph["device_ms"] / 1e3
        ph["implied_gbs"] = round(ph["ideal_bytes"] / max(dt, 1e-9) / 1e9, 2)
        ph["bw_frac"] = round(
            ph["ideal_bytes"] / max(dt, 1e-9) / HBM_BW_BYTES, 4
        )
    phases["step_ms_sum"] = round(
        sum(p["device_ms"] for p in phases.values() if isinstance(p, dict)),
        3,
    )
    return phases


def _migration_sim_ab() -> dict:
    """Goodput retained under a mid-burst worker kill with mid-stream
    migration on vs off (the live routers' default vs the PR-5 abort
    behavior), replayed on the PR-6 discrete-event fleet — no
    accelerator needed, deterministic at a fixed seed. Rides along with
    --chaos so the kill-recovery policy is benched next to the
    step-fault goodput number (docs/robustness.md)."""
    from dynamo_tpu.faults.plan import parse_plan
    from dynamo_tpu.sim import FleetSim, SimConfig, bursty_trace

    trace = bursty_trace(
        600.0, seed=2026, calm_rps=30.0, burst_rps=60.0,
        mean_calm_s=90.0, mean_burst_s=30.0,
    )
    kill = "seed=42;worker.liveness:kill@after=240"

    def run(migration, plan_spec=None):
        plan = parse_plan(plan_spec) if plan_spec else None
        return FleetSim(
            trace, SimConfig(initial_decode=3, migration=migration),
            plan=plan,
        ).run()

    base = run(True)  # fault-free reference
    on = run(True, kill)
    off = run(False, kill)
    g = max(1, base["goodput_tokens"])
    return {
        "sim_kill_plan": kill,
        "sim_goodput_retained_migration_on": round(
            on["goodput_tokens"] / g, 4
        ),
        "sim_goodput_retained_migration_off": round(
            off["goodput_tokens"] / g, 4
        ),
        "sim_resumed": on["resumed"],
        "sim_lost_migration_off": off["lost_inflight"],
    }


def _drain_sim_ab() -> dict:
    """Kill-vs-drain A/B on the discrete-event fleet: the same worker
    goes down at the same instant under the same seed — reactively
    (worker.liveness:kill — streams resume after full re-prefill) vs
    gracefully (worker.drain — proactive handoff, onboard-rate
    resumes, zero lost tokens). The headline is the SLO-attainment
    dip: the drain's must be strictly shallower
    (docs/robustness.md "Graceful drain & rolling restarts")."""
    from dynamo_tpu.faults.plan import parse_plan
    from dynamo_tpu.sim import FleetSim, SimConfig, bursty_trace

    trace = bursty_trace(
        600.0, seed=2026, calm_rps=30.0, burst_rps=60.0,
        mean_calm_s=90.0, mean_burst_s=30.0,
    )

    def run(point):
        plan = parse_plan(f"seed=42;{point}:kill@after=240")
        # kill_detect_s models the reactive path's death-detection gap
        # (stream error + failover backoff) — only kills pay it; the
        # drain's handoff latency is the config default
        return FleetSim(
            trace, SimConfig(initial_decode=3, kill_detect_s=2.0),
            plan=plan,
        ).run()

    def dip(res):
        att = [s["slo_attainment_mean"] for s in res["timeline"]]
        return 1.0 - min(att) if att else 0.0

    kill = run("worker.liveness")
    drain = run("worker.drain")
    return {
        "sim_fault_at_s": 240,
        "sim_attainment_dip_kill": round(dip(kill), 4),
        "sim_attainment_dip_drain": round(dip(drain), 4),
        "sim_streams_migrated_drain": drain["drained_inflight"],
        "sim_streams_hit_kill": kill["killed_inflight"],
        "sim_goodput_kill": kill["goodput_tokens"],
        "sim_goodput_drain": drain["goodput_tokens"],
    }


def _main_chaos_ab(model_cfg, wl) -> None:
    """--chaos: goodput/SLO attainment under a canned, fixed-seed fault
    plan vs the identical fault-free workload (docs/robustness.md).

    The plan (override with DYN_FAULTS) delays a fraction of engine
    steps and injects two transient step errors — the quarantine/retry
    machinery must absorb them. SLO targets default to 3x the fault-free
    run's p50s (env DYN_BENCH_SLO_TTFT_MS / DYN_BENCH_SLO_ITL_MS pin
    absolute targets instead)."""
    from dynamo_tpu import faults

    env_ttft = float(os.environ.get("DYN_BENCH_SLO_TTFT_MS", 0))
    env_itl = float(os.environ.get("DYN_BENCH_SLO_ITL_MS", 0))
    if env_ttft and env_itl:
        # both targets pinned: the probe run would be discarded — skip it
        ttft_ms, itl_ms = env_ttft, env_itl
    else:
        probe = asyncio.run(_run(model_cfg, wl))
        ttft_ms = env_ttft or max(50.0, probe["p50_ttft_s"] * 3e3)
        itl_ms = env_itl or max(5.0, probe["p50_itl_s"] * 3e3)
    slo = (round(ttft_ms, 2), round(itl_ms, 2))
    base = asyncio.run(_run(model_cfg, wl, slo=slo))

    plan_spec = os.environ.get("DYN_FAULTS") or (
        f"seed={os.environ.get('DYN_BENCH_CHAOS_SEED', '42')};"
        f"engine.step:delay={os.environ.get('DYN_BENCH_CHAOS_DELAY', '0.005')}"
        f"@p=0.2;engine.step:error@after=50@max=2"
    )
    injector = faults.activate(faults.parse_plan(plan_spec))
    try:
        chaos = asyncio.run(_run(model_cfg, wl, slo=slo))
        fired = injector.stats()["fired_total"]
    finally:
        faults.deactivate()

    base_goodput = base["slo"]["goodput_tokens_total"]
    chaos_goodput = chaos["slo"]["goodput_tokens_total"]
    out = {
        "metric": "engine_chaos_goodput_1chip",
        "value": round(chaos_goodput / max(chaos["wall_s"], 1e-9), 2),
        "unit": "goodput_tokens/sec",
        # goodput retained under the canned fault plan, relative to the
        # fault-free run at the same SLO targets (1.0 = chaos-immune)
        "vs_baseline": round(chaos_goodput / max(base_goodput, 1), 4),
        "config": {
            "model": wl["model_name"],
            "batch": wl["batch"],
            "isl": wl["isl"],
            "osl": wl["osl"],
            "fault_plan": plan_spec,
            "faults_fired": fired,
            "slo_ttft_ms": slo[0],
            "slo_itl_ms": slo[1],
            "base_tok_s": round(base["tput"], 2),
            "chaos_tok_s": round(chaos["tput"], 2),
            "base_slo_attainment": round(base["slo"]["attainment"], 4),
            "chaos_slo_attainment": round(chaos["slo"]["attainment"], 4),
            "base_goodput_tokens": base_goodput,
            "chaos_goodput_tokens": chaos_goodput,
            "p99_ttft_ms_base": round(base["p99_ttft_s"] * 1000, 1),
            "p99_ttft_ms_chaos": round(chaos["p99_ttft_s"] * 1000, 1),
            "p99_itl_ms_base": round(base["p99_itl_s"] * 1000, 2),
            "p99_itl_ms_chaos": round(chaos["p99_itl_s"] * 1000, 2),
        },
    }
    # mid-stream migration A/B (sim-based; DYN_BENCH_CHAOS_MIGRATION=0
    # skips it): goodput retained through a worker kill, migration
    # on vs off
    if os.environ.get("DYN_BENCH_CHAOS_MIGRATION", "1") != "0":
        out["config"]["migration"] = mig = _migration_sim_ab()
        print(
            f"# migration A/B (sim kill): goodput retained "
            f"{mig['sim_goodput_retained_migration_off']:.4f} (off) -> "
            f"{mig['sim_goodput_retained_migration_on']:.4f} (on), "
            f"{mig['sim_resumed']} stream(s) resumed",
            file=sys.stderr,
        )
    # graceful-drain A/B (sim-based; DYN_BENCH_CHAOS_DRAIN=0 skips it):
    # the same departure as a kill vs as a planned drain — the drain's
    # attainment dip must be the shallower one
    if os.environ.get("DYN_BENCH_CHAOS_DRAIN", "1") != "0":
        out["config"]["drain"] = dr = _drain_sim_ab()
        print(
            f"# drain A/B (sim): attainment dip "
            f"{dr['sim_attainment_dip_kill']:.4f} (kill) -> "
            f"{dr['sim_attainment_dip_drain']:.4f} (drain), "
            f"{dr['sim_streams_migrated_drain']} stream(s) handed off",
            file=sys.stderr,
        )
    print(json.dumps(out))
    print(
        f"# chaos A/B: base={base['tput']:.1f} chaos={chaos['tput']:.1f} "
        f"tok/s, attainment {base['slo']['attainment']:.2%} -> "
        f"{chaos['slo']['attainment']:.2%}, {fired} fault(s) fired",
        file=sys.stderr,
    )


def _main_sim() -> None:
    """--sim: scaling-policy regression watch, no accelerator at all.

    Replays a canned diurnal+burst trace (fixed seed 2026) through the
    discrete-event fleet simulator at three static fleet sizes and once
    with the autoscaling planner, and reports SLO attainment + goodput
    per configuration as two JSON lines (planner_sim_slo_attainment /
    planner_sim_goodput). The headline attainment is of OFFERED load —
    shed and killed requests count as misses — so a policy cannot look
    healthy by rejecting traffic; per-row `slo_attainment` (of admitted
    work) is kept alongside. Policy regressions — watermark changes,
    admission defaults, degradation ladder — move these numbers while
    the chip benches stay flat. Knobs: DYN_BENCH_SIM_DURATION (sim
    seconds, default 1800), DYN_BENCH_SIM_SEED."""
    from dynamo_tpu.planner import PlannerConfig
    from dynamo_tpu.sim import (
        FleetSim,
        SimConfig,
        bursty_trace,
        diurnal_trace,
        merge_traces,
    )

    seed = int(os.environ.get("DYN_BENCH_SIM_SEED", "2026"))
    duration = float(os.environ.get("DYN_BENCH_SIM_DURATION", "1800"))
    trace = merge_traces(
        diurnal_trace(duration, seed, base_rps=12.0, peak_rps=45.0,
                      period_s=duration),
        bursty_trace(duration, seed + 1, calm_rps=4.0, burst_rps=60.0,
                     mean_calm_s=240.0, mean_burst_s=25.0),
    )
    fleet_sizes = (2, 4, 8)
    rows: dict[str, dict] = {}

    def run_one(decode: int, autoscale: bool) -> dict:
        cfg = SimConfig(initial_decode=decode, initial_prefill=1,
                        max_queue_depth=150, slo_ttft_ms=3000.0,
                        slo_itl_ms=60.0)
        fleet = FleetSim(trace, cfg)
        if autoscale:
            fleet.attach_planner(PlannerConfig(
                adjustment_interval_s=20.0, grace_cycles=2,
                reconcile_cycles=2, slo_target=0.95,
                min_decode=1, max_decode=max(fleet_sizes),
                min_prefill=1, max_prefill=4,
            ))
        res = fleet.run()
        # worker-seconds actually provisioned (resource cost) — the
        # timeline integral for EVERY row, so static and autoscaled
        # runs are costed over the same horizon (trace + drain)
        worker_ticks = sum(
            s["decode_workers_reporting"] for s in res["timeline"]
        ) * cfg.metric_interval_s
        return {
            "slo_attainment": round(res["slo_attainment"], 4),
            "slo_attainment_offered": round(
                res["slo_attainment_offered"], 4
            ),
            "goodput_tok_s": round(res["goodput_tok_s"], 2),
            "shed": res["shed"],
            "requests": res["requests"],
            "worker_seconds": round(worker_ticks, 1),
        }

    for n in fleet_sizes:
        rows[f"static-{n}"] = run_one(n, autoscale=False)
    rows["planner"] = run_one(2, autoscale=True)

    config = {
        "seed": seed,
        "duration_s": duration,
        "trace_requests": len(trace),
        "fleet_sizes": list(fleet_sizes),
        **rows,
    }
    peak = rows[f"static-{max(fleet_sizes)}"]
    dyn = rows["planner"]
    print(json.dumps({
        "metric": "planner_sim_slo_attainment",
        "value": dyn["slo_attainment_offered"],
        "unit": "fraction",
        # autoscaled offered-load attainment relative to the capacity-
        # planned static peak fleet (1.0 = planner matches peak
        # provisioning without peak cost)
        "vs_baseline": round(
            dyn["slo_attainment_offered"]
            / max(1e-9, peak["slo_attainment_offered"]), 4
        ),
        "config": config,
    }))
    print(json.dumps({
        "metric": "planner_sim_goodput",
        "value": dyn["goodput_tok_s"],
        "unit": "goodput_tokens/sec",
        "vs_baseline": round(
            dyn["goodput_tok_s"] / max(1e-9, peak["goodput_tok_s"]), 4
        ),
        "config": {
            "planner_worker_seconds": dyn["worker_seconds"],
            "static_peak_worker_seconds": peak["worker_seconds"],
        },
    }))
    print(
        "# sim: " + " ".join(
            f"{k}={v['slo_attainment_offered']:.3f}"
            f"@{v['goodput_tok_s']:.0f}tok/s"
            for k, v in rows.items()
        ),
        file=sys.stderr,
    )


def _kvfleet_compare(measured: dict, base: dict) -> dict:
    """Pure comparison for the kvfleet sentinel (unit-tested without a
    sim run): measured ``{"hit_rate", "avoided_frac"}`` vs a baseline
    entry with an explicit ``noise_frac``. Either headline falling
    below its floor is a regression; a zero hit rate or a recompute
    bill that did NOT shrink with the fabric on is an unconditional
    regression — the A/B invariant holds regardless of how wide the
    noise band is."""
    noise = float(base.get("noise_frac", 0.25))
    hit_floor = base["hit_rate"] * (1.0 - noise)
    avoided_floor = base["avoided_frac"] * (1.0 - noise)
    return {
        "regressed": (
            measured["hit_rate"] <= 0.0
            or measured["avoided_frac"] <= 0.0
            or measured["hit_rate"] < hit_floor
            or measured["avoided_frac"] < avoided_floor
        ),
        "hit_rate": round(measured["hit_rate"], 4),
        "baseline_hit_rate": base["hit_rate"],
        "floor_hit_rate": round(hit_floor, 4),
        "avoided_frac": round(measured["avoided_frac"], 4),
        "baseline_avoided_frac": base["avoided_frac"],
        "floor_avoided_frac": round(avoided_floor, 4),
        "noise_frac": noise,
    }


def _main_kvfleet() -> None:
    """--kvfleet: the fleet KV fabric A/B, pure host-side discrete-event
    run — no jax, no chip (docs/kvbm.md "Fleet fabric").

    The canned diurnal trace with Zipf-popular shared prefix families
    (sim/traces.py PrefixModel: a few giant system prompts dominate)
    replays through FleetSim twice: fabric off, where every request
    reprefills its shared head, and fabric on, where catalog hits fetch
    it at peer/bucket rate instead. Headlines:

    - ``kvfleet_hit_rate`` — fleet prefix hit rate over requests that
      carry a shared prefix;
    - ``kvfleet_reprefill_avoided`` — the fraction of the fabric-off
      recompute bill (prefilled tokens) the fabric removed.

    Both gate against the committed ``cpu-kvfleet-quick``/``-full``
    profile in BENCH_BASELINE.json (exit 1 regression / exit 2 missing
    profile; ``--update-baseline`` seeds; DYN_SENTINEL_REPORT writes
    the CI artifact). The determinism of the sim makes the noise band
    narrow by construction — the band absorbs deliberate model
    retuning, not run-to-run jitter."""
    from dynamo_tpu.sim import FleetSim, SimConfig, diurnal_trace
    from dynamo_tpu.sim.traces import PrefixModel

    argv = sys.argv[1:]
    quick = "--quick" in argv
    seed = int(os.environ.get("DYN_BENCH_KVFLEET_SEED", "7"))
    duration = float(os.environ.get(
        "DYN_BENCH_KVFLEET_DURATION", "300" if quick else "1200"
    ))
    trace = diurnal_trace(
        duration, seed, base_rps=8.0, peak_rps=24.0, period_s=duration,
        prefixes=PrefixModel(),
    )

    def run_one(fabric: bool) -> dict:
        cfg = SimConfig(
            initial_decode=4, initial_prefill=1, max_queue_depth=200,
            fabric=fabric,
        )
        return FleetSim(trace, cfg).run()["fabric"]

    off = run_one(fabric=False)
    on = run_one(fabric=True)
    hit_rate = on["fleet_hit_rate"]
    avoided = on["reprefill_tokens_avoided"]
    avoided_frac = avoided / max(1, off["prefilled_tokens"])
    measured = {"hit_rate": hit_rate, "avoided_frac": avoided_frac}

    # -- sentinel gate (same discipline as --sentinel / --fanout) ---------
    path = _sentinel_baseline_path()
    if "--baseline" in argv:
        i = argv.index("--baseline") + 1
        if i >= len(argv) or argv[i].startswith("--"):
            raise SystemExit("--baseline requires a path argument")
        path = argv[i]
    key = f"cpu-kvfleet-{'quick' if quick else 'full'}"
    baselines: dict = {"profiles": {}}
    if os.path.exists(path):
        with open(path) as f:
            baselines = json.load(f)
    if "--update-baseline" in argv:
        baselines.setdefault("profiles", {})[key] = {
            "hit_rate": round(hit_rate, 4),
            "avoided_frac": round(avoided_frac, 4),
            # the sim is deterministic; the band exists for deliberate
            # trace/model retuning, not machine noise
            "noise_frac": 0.25,
        }
        with open(path, "w") as f:
            json.dump(baselines, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"# kvfleet: baseline profile {key!r} written to {path}",
              file=sys.stderr)
    base = (baselines.get("profiles") or {}).get(key)
    config = {
        "profile": key,
        "baseline_path": path,
        "seed": seed,
        "duration_s": duration,
        "trace_requests": len(trace),
        "prefix_requests": on["prefix_requests"],
        "fleet_hits_host": on["fleet_hits_host"],
        "fleet_hits_bucket": on["fleet_hits_bucket"],
        "publishes": on["publishes"],
        "demoted_bucket": on["demoted_bucket"],
        "demoted_dropped": on["demoted_dropped"],
        "prefilled_tokens_off": off["prefilled_tokens"],
        "prefilled_tokens_on": on["prefilled_tokens"],
        "reprefill_tokens_avoided": avoided,
    }
    if base is None:
        print(json.dumps({
            "metric": "kvfleet_hit_rate", "value": round(hit_rate, 4),
            "unit": "fraction", "vs_baseline": 0.0,
            "config": {"error": f"no baseline profile {key!r} in {path}",
                       "hint": "run with --update-baseline and commit"},
        }))
        print(json.dumps({
            "metric": "kvfleet_reprefill_avoided",
            "value": round(avoided_frac, 4),
            "unit": "fraction_of_prefill_bill", "vs_baseline": 0.0,
            "config": {"error": f"no baseline profile {key!r} in {path}"},
        }))
        sys.exit(2)
    verdict = _kvfleet_compare(measured, base)
    out_hits = {
        "metric": "kvfleet_hit_rate",
        "value": round(hit_rate, 4),
        "unit": "fraction",
        "vs_baseline": round(hit_rate / max(base["hit_rate"], 1e-9), 4),
        "config": {**config, **verdict},
    }
    out_avoided = {
        "metric": "kvfleet_reprefill_avoided",
        "value": round(avoided_frac, 4),
        "unit": "fraction_of_prefill_bill",
        "vs_baseline": round(
            avoided_frac / max(base["avoided_frac"], 1e-9), 4
        ),
        "config": {"profile": key, **verdict},
    }
    print(json.dumps(out_hits))
    print(json.dumps(out_avoided))
    report_path = os.environ.get("DYN_SENTINEL_REPORT")
    if report_path:
        with open(report_path, "w") as f:
            json.dump(
                {"hit_rate": out_hits, "avoided": out_avoided},
                f, indent=2,
            )
            f.write("\n")
    if verdict["regressed"]:
        print(
            f"# KVFLEET REGRESSION: hit_rate {verdict['hit_rate']} "
            f"(floor {verdict['floor_hit_rate']}) avoided_frac "
            f"{verdict['avoided_frac']} (floor "
            f"{verdict['floor_avoided_frac']}) vs baseline "
            f"hit_rate={base['hit_rate']} "
            f"avoided_frac={base['avoided_frac']} "
            f"-{verdict['noise_frac']:.0%}",
            file=sys.stderr,
        )
        sys.exit(1)
    print(
        f"# kvfleet OK: hit_rate {hit_rate:.3f}, "
        f"{avoided} reprefill tokens avoided "
        f"({avoided_frac:.1%} of the bill, {key})",
        file=sys.stderr,
    )


def _fanout_compare(measured: dict, base: dict) -> dict:
    """Pure comparison for the fan-out sentinel (unit-tested without a
    server): measured ``{"rps", "streams"}`` vs a baseline entry with an
    explicit ``noise_frac``. Either headline falling below its floor is
    a regression — host-plane throughput gates exactly like decode."""
    noise = float(base.get("noise_frac", 0.5))
    rps_floor = base["rps"] * (1.0 - noise)
    streams_floor = base["streams"] * (1.0 - noise)
    return {
        "regressed": (
            measured["rps"] < rps_floor
            or measured["streams"] < streams_floor
        ),
        "rps": round(measured["rps"], 1),
        "baseline_rps": base["rps"],
        "floor_rps": round(rps_floor, 1),
        "streams": measured["streams"],
        "baseline_streams": base["streams"],
        "floor_streams": int(streams_floor),
        "noise_frac": noise,
    }


def _main_fanout() -> None:
    """--fanout: the frontend host-plane ceiling — no accelerator, no
    jax (docs/observability.md "Host data plane").

    Boots the REAL HttpService (port 0, dedicated server thread/loop)
    over a synthetic chat engine, then drives it from a client loop:

    - a non-stream RPS ladder at rising concurrency (instant engine:
      every microsecond measured is host work — parse, admission,
      dispatch, aggregate, serialize), headline = best rung's req/s;
    - a concurrent-SSE stream ladder (paced engine holds every rung's
      streams open simultaneously), headline = the largest rung whose
      streams ALL completed; each rung reports the server loop's lag
      p99 over just that rung (LoopLagMonitor.reset_window between
      rungs) and the ledger's per-stream host cost.

    Emits TWO JSON lines — ``frontend_fanout_rps`` and
    ``frontend_fanout_streams`` — gated against the committed
    ``cpu-fanout-quick``/``cpu-fanout-full`` profile in
    BENCH_BASELINE.json exactly like the decode sentinel (exit 1
    regression / exit 2 missing profile; ``--update-baseline`` seeds;
    DYN_SENTINEL_REPORT writes the CI artifact). ``--quick`` shrinks
    both ladders for the CI tier. Knobs: DYN_BENCH_FANOUT_CHUNKS /
    DYN_BENCH_FANOUT_INTERVAL_S shape the synthetic stream."""
    import resource
    import threading

    import aiohttp

    from dynamo_tpu.http.service import HttpService, ModelManager
    from dynamo_tpu.protocols.openai import ChatDeltaGenerator
    from dynamo_tpu.telemetry.hostplane import LoopLagMonitor

    argv = sys.argv[1:]
    quick = "--quick" in argv
    chunks = int(os.environ.get("DYN_BENCH_FANOUT_CHUNKS", "4"))
    interval_s = float(os.environ.get("DYN_BENCH_FANOUT_INTERVAL_S", "0.05"))

    class _SyntheticEngine:
        """Chat engine of pure host cost: real ChatCompletionChunk
        objects (serialize cost is the production pydantic dump), zero
        chip work. ``interval_s`` > 0 paces chunks so N in-flight
        streams are N OPEN streams, not N sequential sprints."""

        def __init__(self, pace_s: float):
            self.pace_s = pace_s

        def generate(self, req, ctx):
            return self._gen(req, ctx)

        async def _gen(self, req, ctx):
            gen = ChatDeltaGenerator(model=req.model or "fanout")
            yield gen.role_chunk()
            for _ in range(chunks):
                if self.pace_s > 0:
                    await asyncio.sleep(self.pace_s)
                else:
                    await asyncio.sleep(0)
                yield gen.text_chunk("synthetic delta text ")
            yield gen.finish_chunk("stop")

    # both the client and server sockets of every stream live in THIS
    # process: 2 fds per open stream, so the ladder's top rung is
    # bounded by the nofile limit (recorded in the config stanza)
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
        soft = hard
    fd_budget = max(64, (soft - 1000) // 2)
    if os.environ.get("DYN_BENCH_FANOUT_SMOKE") == "1":
        # tests/test_hostplane.py: the smallest honest run — one rung
        # per ladder, enough traffic to populate every surface
        rps_rungs = (2,)
        rps_reqs_per_rung = 20
        stream_rungs = (8,)
    elif quick:
        rps_rungs = (4, 16)
        rps_reqs_per_rung = 300
        stream_rungs = tuple(n for n in (64, 256) if n <= fd_budget)
    else:
        rps_rungs = (4, 16, 64, 256)
        rps_reqs_per_rung = 1500
        stream_rungs = tuple(
            n for n in (512, 2048, 8192) if n <= fd_budget
        )

    # -- server side: real HttpService on its own thread + loop ----------
    mm = ModelManager()
    mm.add_chat_model("fanout", _SyntheticEngine(pace_s=0.0))
    mm.add_chat_model("fanout-paced", _SyntheticEngine(pace_s=interval_s))
    # fine-grained heartbeat (20 ms) so a few-second rung still yields a
    # real p99; no blackbox — under deliberate overload the stall
    # counter is the signal, a dump per rung would be noise
    monitor = LoopLagMonitor(interval_s=0.02, window=4096)
    svc = HttpService(mm, host="127.0.0.1", port=0, lag_monitor=monitor)
    server_loop = asyncio.new_event_loop()
    started = threading.Event()

    def _serve() -> None:
        asyncio.set_event_loop(server_loop)
        server_loop.run_until_complete(svc.start())
        started.set()
        server_loop.run_forever()

    server = threading.Thread(target=_serve, name="fanout-server", daemon=True)
    server.start()
    if not started.wait(timeout=30):
        raise SystemExit("fanout: server failed to start")
    base_url = f"http://127.0.0.1:{svc.port}"

    def _reset_lag() -> None:
        server_loop.call_soon_threadsafe(monitor.reset_window)

    # -- client side ------------------------------------------------------
    async def _drive() -> dict:
        timeout = aiohttp.ClientTimeout(
            total=None, sock_connect=60, sock_read=120
        )
        conn = aiohttp.TCPConnector(limit=0)
        results: dict = {"rps_rungs": [], "stream_rungs": []}
        async with aiohttp.ClientSession(
            timeout=timeout, connector=conn
        ) as session:

            async def lag_now() -> dict:
                async with session.get(f"{base_url}/debug/hostplane") as r:
                    snap = await r.json()
                fe = snap.get("frontend", {})
                return {
                    "lag": fe.get("loop", {}).get("lag", {}),
                    "stalls": fe.get("loop", {}).get("stalls", 0),
                    "ledger": fe.get("ledger", {}),
                }

            body = {
                "model": "fanout",
                "messages": [{"role": "user", "content": "ping"}],
                "stream": False,
            }
            for conc in rps_rungs:
                _reset_lag()
                left = rps_reqs_per_rung
                errors = 0

                async def worker():
                    nonlocal left, errors
                    url = f"{base_url}/v1/chat/completions"
                    while left > 0:
                        left -= 1
                        async with session.post(url, json=body) as r:
                            await r.read()
                            if r.status != 200:
                                errors += 1

                t0 = time.monotonic()
                await asyncio.gather(*(worker() for _ in range(conc)))
                dt = time.monotonic() - t0
                probe = await lag_now()
                results["rps_rungs"].append({
                    "concurrency": conc,
                    "requests": rps_reqs_per_rung,
                    "errors": errors,
                    "rps": round(rps_reqs_per_rung / max(dt, 1e-9), 1),
                    "lag_p99_ms": probe["lag"].get("p99_ms", 0.0),
                    "lag_max_ms": probe["lag"].get("max_ms", 0.0),
                })

            sbody = dict(body, model="fanout-paced", stream=True)
            for n in stream_rungs:
                _reset_lag()
                failures = 0

                async def one_stream():
                    nonlocal failures
                    url = f"{base_url}/v1/chat/completions"
                    try:
                        async with session.post(url, json=sbody) as r:
                            ok = r.status == 200
                            async for _ in r.content:
                                pass
                            if not ok:
                                failures += 1
                    except (aiohttp.ClientError, OSError,
                            asyncio.TimeoutError):
                        failures += 1

                t0 = time.monotonic()
                tasks = []
                for i in range(n):
                    tasks.append(asyncio.ensure_future(one_stream()))
                    if i % 256 == 255:
                        # stagger socket bring-up so the listen backlog
                        # measures streaming fan-out, not SYN flooding
                        await asyncio.sleep(0)
                await asyncio.gather(*tasks)
                dt = time.monotonic() - t0
                probe = await lag_now()
                ledger = probe["ledger"]
                results["stream_rungs"].append({
                    "streams": n,
                    "failures": failures,
                    "wall_s": round(dt, 3),
                    "lag_p99_ms": probe["lag"].get("p99_ms", 0.0),
                    "lag_max_ms": probe["lag"].get("max_ms", 0.0),
                    "stalls_total": probe["stalls"],
                    "sse_write_ema_us": ledger.get("sse_write_ema_us"),
                    "host_stage_ms_mean": (
                        ledger.get("window", {}).get("stage_ms_mean", {})
                    ),
                })
        return results

    try:
        results = asyncio.run(_drive())
    finally:
        asyncio.run_coroutine_threadsafe(svc.stop(), server_loop).result(30)
        server_loop.call_soon_threadsafe(server_loop.stop)
        server.join(timeout=30)

    clean_rps = [r for r in results["rps_rungs"] if r["errors"] == 0]
    rps_ceiling = max((r["rps"] for r in clean_rps), default=0.0)
    clean_streams = [
        r for r in results["stream_rungs"] if r["failures"] == 0
    ]
    stream_ceiling = max((r["streams"] for r in clean_streams), default=0)

    # -- sentinel gate (same discipline as --sentinel) --------------------
    path = _sentinel_baseline_path()
    if "--baseline" in argv:
        i = argv.index("--baseline") + 1
        if i >= len(argv) or argv[i].startswith("--"):
            raise SystemExit("--baseline requires a path argument")
        path = argv[i]
    key = f"cpu-fanout-{'quick' if quick else 'full'}"
    measured = {"rps": rps_ceiling, "streams": stream_ceiling}
    baselines: dict = {"profiles": {}}
    if os.path.exists(path):
        with open(path) as f:
            baselines = json.load(f)
    if "--update-baseline" in argv:
        baselines.setdefault("profiles", {})[key] = {
            "rps": round(rps_ceiling, 1),
            "streams": stream_ceiling,
            # single-core CI runners swing hard on pure host-throughput
            # numbers — wide explicit band, tighten per-fleet on purpose
            "noise_frac": 0.5,
        }
        with open(path, "w") as f:
            json.dump(baselines, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"# fanout: baseline profile {key!r} written to {path}",
              file=sys.stderr)
    base = (baselines.get("profiles") or {}).get(key)
    config = {
        "profile": key,
        "baseline_path": path,
        "chunks_per_stream": chunks,
        "chunk_interval_s": interval_s,
        "fd_budget_streams": fd_budget,
        "rps_rungs": results["rps_rungs"],
        "stream_rungs": results["stream_rungs"],
    }
    if base is None:
        print(json.dumps({
            "metric": "frontend_fanout_rps", "value": rps_ceiling,
            "unit": "requests/sec", "vs_baseline": 0.0,
            "config": {"error": f"no baseline profile {key!r} in {path}",
                       "hint": "run with --update-baseline and commit"},
        }))
        print(json.dumps({
            "metric": "frontend_fanout_streams", "value": stream_ceiling,
            "unit": "concurrent_streams", "vs_baseline": 0.0,
            "config": {"error": f"no baseline profile {key!r} in {path}"},
        }))
        sys.exit(2)
    verdict = _fanout_compare(measured, base)
    out_rps = {
        "metric": "frontend_fanout_rps",
        "value": rps_ceiling,
        "unit": "requests/sec",
        "vs_baseline": round(rps_ceiling / max(base["rps"], 1e-9), 4),
        "config": {**config, **verdict},
    }
    out_streams = {
        "metric": "frontend_fanout_streams",
        "value": stream_ceiling,
        "unit": "concurrent_streams",
        "vs_baseline": round(
            stream_ceiling / max(base["streams"], 1e-9), 4
        ),
        "config": {"profile": key, **verdict},
    }
    print(json.dumps(out_rps))
    print(json.dumps(out_streams))
    report_path = os.environ.get("DYN_SENTINEL_REPORT")
    if report_path:
        with open(report_path, "w") as f:
            json.dump({"rps": out_rps, "streams": out_streams}, f, indent=2)
            f.write("\n")
    if verdict["regressed"]:
        print(
            f"# FANOUT REGRESSION: rps {verdict['rps']} (floor "
            f"{verdict['floor_rps']}) streams {verdict['streams']} "
            f"(floor {verdict['floor_streams']}) vs baseline "
            f"rps={base['rps']} streams={base['streams']} "
            f"-{verdict['noise_frac']:.0%}",
            file=sys.stderr,
        )
        sys.exit(1)
    print(
        f"# fanout OK: {rps_ceiling:.0f} req/s, {stream_ceiling} "
        f"concurrent streams ({key})",
        file=sys.stderr,
    )


def _sentinel_profile_key(
    cpu_mode: bool, wl: dict, quick: bool, spec: bool = True
) -> str:
    """Baseline entries key on platform + model + quick/full so a CPU
    CI run never compares against a TPU headline number. The default
    (spec+overlap) headline keeps the bare key; the DYN_BENCH_SPEC=0
    escape hatch gets its own ``-nospec`` profile — the two modes run
    entirely different step programs (fused windows vs the spec
    pipeline at decode_steps=1), so comparing across them would make
    the gate vacuous in one direction and a false alarm in the other."""
    return (
        f"{'cpu' if cpu_mode else 'tpu'}-{wl['model_name']}-"
        f"{'quick' if quick else 'full'}"
        + ("" if spec else "-nospec")
    )


def _sentinel_compare(measured: dict, base: dict) -> dict:
    """Pure comparison logic (unit-tested without an engine): measured
    ``{"tok_s", "roofline_frac", "step_time_frac"}`` vs a baseline
    entry with EXPLICIT noise bands. Returns the verdict dict printed
    as the sentinel report:

    - ``regressed`` — tok/s fell below ``base.tok_s × (1 − noise_frac)``
      (the gate; roofline_frac rides along informationally since it
      moves with tok/s by construction);
    - ``bucket_deltas`` — measured − baseline per attribution bucket;
    - ``losing_bucket`` — the bucket whose time share GREW most beyond
      the per-bucket noise band (``bucket_noise_abs``): the named owner
      of the lost tokens.
    """
    noise = float(base.get("noise_frac", 0.15))
    floor = base["tok_s"] * (1.0 - noise)
    regressed = measured["tok_s"] < floor
    bucket_noise = float(base.get("bucket_noise_abs", 0.05))
    deltas: dict[str, float] = {}
    losing, losing_delta = "", 0.0
    for bucket, base_frac in (base.get("step_time_frac") or {}).items():
        cur = (measured.get("step_time_frac") or {}).get(bucket, 0.0)
        d = round(cur - float(base_frac), 4)
        deltas[bucket] = d
        if d > losing_delta and d > bucket_noise:
            losing, losing_delta = bucket, d
    if regressed and not losing and deltas:
        # nothing beat the bucket band but the headline fell: name the
        # largest POSITIVE mover, or call the slowdown uniform — naming
        # a bucket that shrank would send the reader chasing the one
        # place the time did NOT go
        grew = {k: v for k, v in deltas.items() if v > 0}
        losing = max(grew, key=grew.get) if grew else "uniform"
    return {
        "regressed": regressed,
        "tok_s": round(measured["tok_s"], 2),
        "baseline_tok_s": base["tok_s"],
        "noise_frac": noise,
        "floor_tok_s": round(floor, 2),
        "roofline_frac": measured.get("roofline_frac"),
        "baseline_roofline_frac": base.get("roofline_frac"),
        "bucket_deltas": deltas,
        "bucket_noise_abs": bucket_noise,
        "losing_bucket": losing,
    }


def _sentinel_baseline_path() -> str:
    return os.environ.get("DYN_BENCH_BASELINE") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json"
    )


def _main_sentinel(model_cfg, wl, cpu_mode: bool) -> None:
    """--sentinel: the bench regression gate (docs/observability.md
    "Perf attribution"). Runs the headline workload, compares tok/s and
    the attribution breakdown against the committed BENCH_BASELINE.json
    (override: --baseline PATH / DYN_BENCH_BASELINE), prints the
    attribution delta naming the bucket that ate the loss, and exits
    nonzero on regression. ``--quick`` shrinks the workload for the CI
    CPU-interpret smoke tier; ``--update-baseline`` rewrites this
    profile's entry from the measured run (commit the diff
    deliberately). DYN_SENTINEL_REPORT=path additionally writes the
    report JSON there (the CI artifact)."""
    argv = sys.argv[1:]
    quick = "--quick" in argv
    if quick:
        # small enough for a CI CPU run, big enough for a steady decode
        # window (the attribution fractions need some steps)
        wl = dict(wl, batch=min(wl["batch"], 2), isl=min(wl["isl"], 16),
                  osl=min(wl["osl"], 16))
    # the sentinel gates the HEADLINE configuration, which defaults to
    # overlapped speculative decoding at decode_steps=1 (DYN_BENCH_SPEC
    # escape hatch mirrors the headline's)
    headline_spec = os.environ.get("DYN_BENCH_SPEC", "1") != "0"
    decode_steps = 1 if headline_spec else (4 if quick else None)
    path = _sentinel_baseline_path()
    if "--baseline" in argv:
        i = argv.index("--baseline") + 1
        if i >= len(argv) or argv[i].startswith("--"):
            raise SystemExit("--baseline requires a path argument")
        path = argv[i]
    key = _sentinel_profile_key(cpu_mode, wl, quick, spec=headline_spec)
    r = asyncio.run(_run(
        model_cfg, wl, spec=headline_spec, decode_steps=decode_steps
    ))
    attr = r["attribution"]
    measured = {
        "tok_s": r["tput"],
        "roofline_frac": (
            attr["roofline_frac"]
            if attr["roofline_frac"] is not None
            else round(r["tput"] / r["roofline"], 6)
        ),
        "step_time_frac": attr["frac"],
    }
    baselines: dict = {"profiles": {}}
    if os.path.exists(path):
        with open(path) as f:
            baselines = json.load(f)
    if "--update-baseline" in argv:
        baselines.setdefault("profiles", {})[key] = {
            "tok_s": round(measured["tok_s"], 2),
            "roofline_frac": round(measured["roofline_frac"], 6),
            "step_time_frac": {
                k: round(v, 4)
                for k, v in measured["step_time_frac"].items()
            },
            # explicit noise bands: CPU-interpret timings swing with
            # runner hardware, so the quick tier gets a wide gate —
            # tighten deliberately, per profile, when the fleet is known
            "noise_frac": 0.15 if not cpu_mode else 0.5,
            "bucket_noise_abs": 0.05 if not cpu_mode else 0.2,
        }
        with open(path, "w") as f:
            json.dump(baselines, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"# sentinel: baseline profile {key!r} written to {path}",
              file=sys.stderr)
    base = (baselines.get("profiles") or {}).get(key)
    if base is None:
        print(json.dumps({
            "metric": "bench_sentinel", "value": round(r["tput"], 2),
            "unit": "tokens/sec", "vs_baseline": 0.0,
            "config": {"error": f"no baseline profile {key!r} in {path}",
                       "hint": "run with --update-baseline and commit"},
        }))
        sys.exit(2)
    verdict = _sentinel_compare(measured, base)
    out = {
        "metric": "bench_sentinel",
        "value": round(r["tput"], 2),
        "unit": "tokens/sec",
        "vs_baseline": round(r["tput"] / max(base["tok_s"], 1e-9), 4),
        "config": {"profile": key, "baseline_path": path, **verdict},
    }
    print(json.dumps(out))
    report_path = os.environ.get("DYN_SENTINEL_REPORT")
    if report_path:
        with open(report_path, "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    if verdict["regressed"]:
        delta = verdict["bucket_deltas"].get(verdict["losing_bucket"], 0.0)
        print(
            f"# SENTINEL REGRESSION: {verdict['tok_s']} tok/s < floor "
            f"{verdict['floor_tok_s']} (baseline {base['tok_s']} "
            f"-{verdict['noise_frac']:.0%}); losing bucket: "
            f"{verdict['losing_bucket'] or 'unknown'} "
            f"({delta:+.4f} of step time)",
            file=sys.stderr,
        )
        sys.exit(1)
    print(
        f"# sentinel OK: {verdict['tok_s']} tok/s >= floor "
        f"{verdict['floor_tok_s']} ({key})",
        file=sys.stderr,
    )


def main() -> None:
    if "--sim" in sys.argv[1:]:
        _main_sim()  # pure host-side discrete-event run: no jax, no chip
        return
    if "--fanout" in sys.argv[1:]:
        _main_fanout()  # frontend host-plane ceiling: no jax, no chip
        return
    if "--kvfleet" in sys.argv[1:]:
        _main_kvfleet()  # fleet KV fabric A/B: no jax, no chip
        return
    cpu_mode = os.environ.get("DYN_BENCH_PLATFORM") == "cpu"
    if cpu_mode:
        from dynamo_tpu.utils.jaxtools import force_platform

        force_platform("cpu")
    model_cfg, wl = _build_config(cpu_mode)
    if "--sentinel" in sys.argv[1:]:
        _main_sentinel(model_cfg, wl, cpu_mode)
        return
    if "--spec" in sys.argv[1:]:
        _main_spec_ab(model_cfg, wl)
        return
    if "--chaos" in sys.argv[1:]:
        _main_chaos_ab(model_cfg, wl)
        return
    if "--spec-overlap" in sys.argv[1:]:
        _main_spec_overlap_ab(model_cfg, wl)
        return
    if "--overlap" in sys.argv[1:]:
        _main_overlap_ab(model_cfg, wl)
        return
    if "--guided" in sys.argv[1:]:
        _main_guided_ab(model_cfg, wl)
        return
    if "--matmul" in sys.argv[1:]:
        _main_matmul_ab(model_cfg, wl)
        return
    if "--kv-dtype" in sys.argv[1:]:
        _main_kv_dtype_ab(model_cfg, wl)
        return
    headline_overlap = os.environ.get("DYN_BENCH_OVERLAP", "1") != "0"
    # headline default: overlapped speculative decoding over int8 KV —
    # spec (accepted drafts multiply tokens/step) composed with the
    # decode pipeline (drafting hidden under the in-flight verify), at
    # decode_steps=1 (speculation replaces fused windows).
    # DYN_BENCH_SPEC=0 is the escape hatch back to the window headline.
    headline_spec = os.environ.get("DYN_BENCH_SPEC", "1") != "0"
    r = asyncio.run(_run(
        model_cfg, wl, overlap=headline_overlap, spec=headline_spec,
        decode_steps=1 if headline_spec else None,
    ))
    phases = (
        _phase_breakdown(model_cfg, wl, r["kv_dtype"])
        if "--phases" in sys.argv[1:]
        else None
    )
    out = {
        "metric": "engine_decode_throughput_1chip",
        "value": round(r["tput"], 2),
        "unit": "tokens/sec",
        "vs_baseline": round(r["tput"] / r["roofline"], 4),
        # auditability: the exact workload behind the number
        "config": {
            "model": wl["model_name"],
            "layers": model_cfg.num_hidden_layers,
            "hidden": model_cfg.hidden_size,
            "vocab": model_cfg.vocab_size,
            "quant": wl["quant"],
            "kv_dtype": r["kv_dtype"],
            # resolved quantized-matmul impl (ops/qmatmul.py kernels vs
            # XLA mixed dot) — headline movement must name its lever
            "matmul_impl": r["matmul_impl"],
            "batch": wl["batch"],
            "isl": wl["isl"],
            "osl": wl["osl"],
            "decode_steps": (
                1 if headline_spec
                else int(os.environ.get("DYN_BENCH_DECODE_STEPS", "64"))
            ),
            # speculative decoding stanza (docs/speculative_decoding.md):
            # the headline's spec composition, or enabled=False under
            # the DYN_BENCH_SPEC=0 escape hatch
            "spec": (
                {
                    "enabled": True,
                    "drafter": os.environ.get(
                        "DYN_BENCH_SPEC_DRAFTER", "ngram"
                    ),
                    "spec_tokens": int(
                        os.environ.get("DYN_BENCH_SPEC_TOKENS", "4")
                    ),
                    "proposed_tokens": r["spec_proposed"],
                    "accepted_tokens": r["spec_accepted"],
                    "accept_rate": (
                        round(r["spec_accepted"] / r["spec_proposed"], 4)
                        if r["spec_proposed"] else 0.0
                    ),
                    "draft_hidden_frac": r["spec_draft_hidden_frac"],
                }
                if headline_spec
                else {"enabled": False}
            ),
            # overlapped-pipeline attribution (ISSUE 7): the device-idle
            # share of the measured wall plus per-step overlap stats —
            # movement in the headline number is attributable to the
            # pipeline only if this fraction moved with it
            "overlap": r["overlap"]["overlap_enabled"],
            # live attribution (telemetry/attribution.py): the serving-
            # side decomposition of this run's wall time; roofline_frac
            # here and vs_baseline above share one formula
            # (telemetry/roofline.py) so they must agree up to
            # windowing (the ledger's frac is decode-records-only and
            # skips engine-idle spans; vs_baseline divides by the whole
            # measured wall incl. prefill)
            "roofline_frac_live": r["attribution"]["roofline_frac"],
            "top_loss_bucket": r["attribution"]["top_loss_bucket"],
            "step_time_frac": {
                k: v for k, v in r["attribution"]["frac"].items() if v > 0
            },
            "device_idle_frac": r["overlap"]["device_idle_frac"],
            "idle_gap_ms_per_step": r["overlap"]["idle_gap_ms_per_step"],
            "max_idle_gap_ms": r["overlap"]["max_idle_gap_ms"],
            "steps_dispatched": r["overlap"]["steps_dispatched"],
            "p50_ttft_ms": round(r["p50_ttft_s"] * 1000, 1),
            # tails (ISSUE 4 satellite): the serving story lives in the
            # p90/p99, not the median — BENCH_* files must capture them
            "p90_ttft_ms": round(r["p90_ttft_s"] * 1000, 1),
            "p99_ttft_ms": round(r["p99_ttft_s"] * 1000, 1),
            "p50_itl_ms": round(r["p50_itl_s"] * 1000, 2),
            "p90_itl_ms": round(r["p90_itl_s"] * 1000, 2),
            "p99_itl_ms": round(r["p99_itl_s"] * 1000, 2),
        },
    }
    if phases is not None:
        # per-phase device-time + bytes breakdown (--phases): the
        # roofline gap decomposed in the artifact itself
        out["config"]["phases"] = phases
        step_ms_engine = round(
            wl["batch"] / max(r["tput"], 1e-9) * 1e3, 3
        )
        out["config"]["phases"]["step_ms_engine"] = step_ms_engine
    print(json.dumps(out))
    print(
        f"# detail: total_tokens={r['total_tokens']} wall={r['wall_s']:.2f}s "
        f"ttft p50/p90/p99={r['p50_ttft_s'] * 1000:.0f}/"
        f"{r['p90_ttft_s'] * 1000:.0f}/{r['p99_ttft_s'] * 1000:.0f}ms "
        f"itl p50/p99={r['p50_itl_s'] * 1000:.1f}/"
        f"{r['p99_itl_s'] * 1000:.1f}ms roofline={r['roofline']:.0f} tok/s "
        f"device_idle_frac={r['overlap']['device_idle_frac']:.3f}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
