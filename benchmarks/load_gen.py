"""HTTP load generator for serving benchmarks.

The native stand-in for the reference's benchmark harness (reference:
examples/llm/benchmarks/perf.sh drives genai-perf with fixed ISL/OSL and
a concurrency sweep; planner_benchmark/sin_synth.py generates a
sinusoidal request rate for autoscaler evaluation). Drives any
OpenAI-compatible endpoint (ours or not) and reports TTFT/ITL/E2E
percentiles plus token throughput as one JSON line.

Modes:
  --rate-mode constant --rate R            fixed R req/s (Poisson)
  --rate-mode sweep --concurrency 1,2,4    closed-loop concurrency sweep
  --rate-mode sin --rate-min 5 --rate-max 20 --period 150
                                           sinusoidal open-loop load
                                           (the planner benchmark shape)

Example:
  python benchmarks/load_gen.py --url http://127.0.0.1:8000 \
      --model echo --isl 128 --osl 64 --duration 60 --rate-mode constant --rate 4
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import random
import sys
import time

import aiohttp

PROMPT_WORD = "benchmark "


def _percentiles(xs: list[float], ps=(50, 90, 99)) -> dict[str, float]:
    if not xs:
        return {f"p{p}": 0.0 for p in ps}
    xs = sorted(xs)
    # nearest-rank: p99 of 100 samples is the 99th value, not the max
    return {
        f"p{p}": xs[max(0, math.ceil(len(xs) * p / 100) - 1)] for p in ps
    }


def ms(xs: list[float]) -> dict[str, float]:
    """TTFT/ITL/E2E percentiles in rounded milliseconds (the one
    reporting format, shared with serve_bench)."""
    return {k: round(v * 1000, 1) for k, v in _percentiles(xs).items()}


class Stats:
    def __init__(self) -> None:
        self.ttft: list[float] = []
        self.itl: list[float] = []
        self.e2e: list[float] = []
        self.tokens = 0
        self.errors = 0
        self.completed = 0
        self.elapsed = 0.0  # actual wall time incl. the drain window
        # multiturn mode: TTFT split by first vs returning turns
        self.ttft_first: list[float] = []
        self.ttft_later: list[float] = []


async def one_request(session: aiohttp.ClientSession, args, stats: Stats) -> None:
    # unique head defeats cross-request prefix caching; body sized to ~ISL
    prompt = f"req-{random.random():.9f} " + PROMPT_WORD * max(1, args.isl - 2)
    await _stream_completion(session, args, stats, prompt)


async def chat_turn(
    session: aiohttp.ClientSession, args, stats: Stats, prompt: str,
    first_turn: bool,
) -> str | None:
    """One conversation turn: send the full history as the prompt,
    collect the generated text (the next turn appends it). TTFT lands
    in stats.ttft_first / stats.ttft_later — later turns are where
    prefix reuse and KV offload show up."""
    return await _stream_completion(
        session, args, stats, prompt, first_turn=first_turn, collect=True
    )


async def _stream_completion(
    session: aiohttp.ClientSession, args, stats: Stats, prompt: str,
    first_turn: bool | None = None, collect: bool = False,
) -> str | None:
    """Stream one /v1/completions call, accounting TTFT/ITL/E2E/tokens
    into ``stats``. ``first_turn`` additionally buckets the TTFT into
    ttft_first/ttft_later (multiturn mode). Returns the generated text
    when ``collect`` (None on error)."""
    body = {
        "model": args.model,
        "prompt": prompt,
        "max_tokens": args.osl,
        "stream": True,
        "ignore_eos": True,
        # ask for exact token counts on the final chunk; servers that
        # don't support it fall back to a word-count estimate below
        "stream_options": {"include_usage": True},
    }
    t0 = time.monotonic()
    t_prev = None
    n_est = 0
    n_usage = None
    text_parts: list[str] = []
    try:
        async with session.post(
            f"{args.url}/v1/completions", json=body,
            timeout=aiohttp.ClientTimeout(total=args.request_timeout),
        ) as resp:
            if resp.status != 200:
                stats.errors += 1
                return None
            async for line in resp.content:
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                payload = line[5:].strip()
                if payload == b"[DONE]":
                    break
                now = time.monotonic()
                chunk = json.loads(payload)
                usage = chunk.get("usage") or {}
                if usage.get("completion_tokens"):
                    n_usage = int(usage["completion_tokens"])
                text = "".join(
                    c.get("text") or "" for c in chunk.get("choices", [])
                )
                if text:
                    # ITL here is inter-CHUNK latency: servers with fused
                    # multi-step decode stream several tokens per chunk
                    if t_prev is None:
                        ttft = now - t0
                        stats.ttft.append(ttft)
                        if first_turn is not None:
                            (stats.ttft_first if first_turn
                             else stats.ttft_later).append(ttft)
                    else:
                        stats.itl.append(now - t_prev)
                    t_prev = now
                    n_est += max(1, len(text.split()))
                    if collect:
                        text_parts.append(text)
        stats.e2e.append(time.monotonic() - t0)
        stats.tokens += n_usage if n_usage is not None else n_est
        stats.completed += 1
        return "".join(text_parts) if collect else ""
    except Exception:
        stats.errors += 1
        return None


async def run_multiturn(args, users: int, turns: int, think: float) -> Stats:
    """Multi-turn conversations: ``users`` concurrent users, each
    holding a growing chat history for ``turns`` sequential requests
    with ~``think`` seconds of think time between turns (reference
    recipe: the KV-offload benchmark's 'multi-turn conversations x
    users' workload, docs/architecture.md:91-96 — the system-memory KV
    tier is measured as TTFT on RETURNING turns whose prefix blocks
    were evicted from HBM in between)."""
    stats = Stats()

    async def user(u: int) -> None:
        # distinct head per conversation: users never share prefixes
        history = f"user-{u}-{random.random():.9f} "
        for t in range(turns):
            history += f" Q{t}: " + PROMPT_WORD * max(1, args.isl - 2)
            out = await chat_turn(
                session, args, stats, history, first_turn=(t == 0)
            )
            if out is None:
                return  # conversation aborted (error)
            history += " " + out
            if think > 0 and t < turns - 1:
                await asyncio.sleep(random.uniform(0.5 * think, 1.5 * think))

    t_start = time.monotonic()
    async with aiohttp.ClientSession() as session:
        await asyncio.gather(*[user(u) for u in range(users)])
    stats.elapsed = time.monotonic() - t_start
    return stats


async def run_open_loop(args, rate_fn) -> Stats:
    """Poisson arrivals at a (possibly time-varying) rate."""
    stats = Stats()
    tasks: set[asyncio.Task] = set()
    async with aiohttp.ClientSession() as session:
        t_start = time.monotonic()
        while time.monotonic() - t_start < args.duration:
            rate = max(0.01, rate_fn(time.monotonic() - t_start))
            await asyncio.sleep(random.expovariate(rate))
            task = asyncio.create_task(one_request(session, args, stats))
            tasks.add(task)
            task.add_done_callback(tasks.discard)
        if tasks:
            await asyncio.wait(tasks, timeout=args.request_timeout)
        # tokens from the drain window count, so the denominator must too
        stats.elapsed = time.monotonic() - t_start
    return stats


async def run_closed_loop(args, concurrency: int) -> Stats:
    """Fixed in-flight concurrency for the duration."""
    stats = Stats()
    t_start = time.monotonic()
    stop = t_start + args.duration

    async with aiohttp.ClientSession() as session:
        async def worker() -> None:
            while time.monotonic() < stop:
                await one_request(session, args, stats)

        await asyncio.gather(*[worker() for _ in range(concurrency)])
    stats.elapsed = time.monotonic() - t_start
    return stats


def report(tag: str, stats: Stats, duration: float) -> None:
    elapsed = stats.elapsed or duration
    out = {
        "tag": tag,
        "completed": stats.completed,
        "errors": stats.errors,
        "output_tok_per_s": round(stats.tokens / max(elapsed, 1e-9), 2),
        "ttft_ms": ms(stats.ttft),
        "inter_chunk_ms": ms(stats.itl),
        "e2e_ms": ms(stats.e2e),
    }
    print(json.dumps(out), flush=True)


async def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--url", default="http://127.0.0.1:8000")
    p.add_argument("--model", required=True)
    p.add_argument("--isl", type=int, default=128, help="approx input words")
    p.add_argument("--osl", type=int, default=64, help="max output tokens")
    p.add_argument("--duration", type=float, default=30.0)
    p.add_argument("--request-timeout", type=float, default=120.0)
    p.add_argument("--rate-mode", default="constant",
                   choices=["constant", "sweep", "sin", "multiturn"])
    p.add_argument("--users", type=int, default=8,
                   help="concurrent conversations for --rate-mode multiturn")
    p.add_argument("--turns", type=int, default=4,
                   help="turns per conversation for --rate-mode multiturn")
    p.add_argument("--think-time", type=float, default=0.0,
                   help="mean seconds between a user's turns")
    p.add_argument("--rate", type=float, default=2.0)
    p.add_argument("--concurrency", default="1,2,4,8",
                   help="comma list for --rate-mode sweep")
    p.add_argument("--rate-min", type=float, default=5.0)
    p.add_argument("--rate-max", type=float, default=20.0)
    p.add_argument("--period", type=float, default=150.0,
                   help="sin period seconds (planner benchmark: 150)")
    args = p.parse_args()

    if args.rate_mode == "multiturn":
        stats = await run_multiturn(
            args, args.users, args.turns, args.think_time
        )
        report(f"multiturn-{args.users}x{args.turns}", stats, args.duration)
        print(json.dumps({
            "ttft_first_ms": ms(stats.ttft_first),
            "ttft_later_ms": ms(stats.ttft_later),
        }), flush=True)
    elif args.rate_mode == "constant":
        stats = await run_open_loop(args, lambda t: args.rate)
        report(f"constant-{args.rate}", stats, args.duration)
    elif args.rate_mode == "sin":
        mid = (args.rate_min + args.rate_max) / 2
        amp = (args.rate_max - args.rate_min) / 2
        stats = await run_open_loop(
            args, lambda t: mid + amp * math.sin(2 * math.pi * t / args.period)
        )
        report(f"sin-{args.rate_min}-{args.rate_max}", stats, args.duration)
    else:
        for c in [int(x) for x in args.concurrency.split(",")]:
            stats = await run_closed_loop(args, c)
            report(f"concurrency-{c}", stats, args.duration)


if __name__ == "__main__":
    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        sys.exit(1)
