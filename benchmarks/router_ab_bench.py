"""KV-aware routing vs round-robin: the recorded serving A/B.

The reference's headline routing claim is 3x TTFT / 2x avg latency from
KV-aware routing on prefix-heavy workloads (reference:
docs/architecture.md:73-87). This bench measures OUR analogue on a real
multi-worker serving fleet: coordinator store + TWO jax workers
(publishing KV events) + an HTTP frontend, once with
``--router-mode kv`` and once with ``--router-mode round-robin``,
driven by the multi-turn conversation workload (each user's history
grows turn over turn, so a returning turn's prefix is cached ONLY on
the worker that served the previous turn — KV routing sends the user
back there; round-robin sprays turns across workers and re-prefills
~half the histories from scratch).

Reported per mode: returning-turn TTFT p50/p99 (where routing pays),
first-turn TTFT (sanity: should match across modes), and the
fleet-wide average prefix-hit rate scraped from the metrics service.
Committed results: benchmarks/results_router_ab.json +
benchmarks/RESULTS.md.

    python benchmarks/router_ab_bench.py            # full A/B (CPU)
    python benchmarks/router_ab_bench.py --users 4 --turns 3   # quicker
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

from load_gen import Stats, ms, run_multiturn  # noqa: E402

TINY_MODEL = os.path.join(REPO, "tests", "data", "tiny_llama_model")

# big enough that re-prefilling a multi-turn history is clearly
# distinguishable from serving it out of prefix cache on a CPU worker
CONFIG = dict(
    model_type="llama", vocab_size=2048, hidden_size=256,
    intermediate_size=512, num_hidden_layers=4, num_attention_heads=8,
    num_key_value_heads=4, max_position_embeddings=4096,
)
ENGINE = dict(
    random_weights=True, num_blocks=1024, block_size=16, max_batch_size=8,
    decode_steps=4, prefill_chunk_size=512, max_model_len=3072,
    enable_prefix_caching=True,
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Fleet:
    def __init__(self, tmp: str):
        self.tmp = tmp
        self.procs: list[tuple[subprocess.Popen, str]] = []

    def spawn(self, tag: str, *argv: str) -> subprocess.Popen:
        inherited = os.environ.get("PYTHONPATH", "")
        env = dict(
            os.environ,
            PYTHONPATH=REPO + (os.pathsep + inherited if inherited else ""),
            JAX_PLATFORMS="cpu",
        )
        log = os.path.join(self.tmp, f"{tag}.log")
        fh = open(log, "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.cli.main", *argv],
            env=env, stdout=fh, stderr=subprocess.STDOUT,
        )
        self.procs.append((proc, log))
        return proc

    def teardown(self) -> None:
        for proc, _ in self.procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc, log in self.procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
        self.procs.clear()


def wait_http(url: str, ready, timeout: float = 300.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                body = r.read()
                if ready(body):
                    return body
        except Exception as exc:
            last = exc
        time.sleep(0.5)
    raise RuntimeError(f"{url} never ready: {last}")


def scrape_metrics(port: int) -> dict[str, float]:
    out: dict[str, float] = {}
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as r:
        for line in r.read().decode().splitlines():
            if line and not line.startswith("#"):
                name = line.split("{")[0].split(" ")[0]
                try:
                    out[name] = float(line.rsplit(" ", 1)[1])
                except ValueError:
                    pass
    return out


def run_mode(mode: str, model_dir: str, engine_args: str,
             users: int, turns: int, think: float) -> dict:
    tmp = tempfile.mkdtemp(prefix=f"dyn_router_ab_{mode}_")
    fleet = Fleet(tmp)
    store_port = free_port()
    http_port = free_port()
    metrics_port = free_port()
    try:
        fleet.spawn("store", "store", "--host", "127.0.0.1",
                    "--port", str(store_port))
        time.sleep(2)
        common = ["--store-host", "127.0.0.1", "--store-port",
                  str(store_port)]
        for i in range(2):
            fleet.spawn(
                f"worker{i}", "run", "--in", "dyn://ab.backend.generate",
                "--out", "jax", "--model-path", model_dir,
                "--model-name", "bench",
                "--extra-engine-args", engine_args, *common,
            )
        fleet.spawn(
            "frontend", "run", "--in", "http",
            "--out", "dyn://ab.backend.generate",
            "--model-path", model_dir, "--model-name", "bench",
            "--http-host", "127.0.0.1", "--http-port", str(http_port),
            "--router-mode", mode, *common,
        )
        fleet.spawn(
            "metrics", "metrics", "--namespace", "ab", "--component",
            "backend", "--port", str(metrics_port), *common,
        )
        wait_http(
            f"http://127.0.0.1:{http_port}/v1/models",
            lambda b: json.loads(b).get("data"),
        )
        # BOTH workers must be routable or the A/B is vacuous
        wait_http(
            f"http://127.0.0.1:{metrics_port}/metrics",
            lambda b: b"llm_workers_reporting 2" in b.replace(b".0", b""),
        )

        class A:
            url = f"http://127.0.0.1:{http_port}"
            model = "bench"
            isl = 40  # words/turn; ~9 tok/word on the test tokenizer
            osl = 24
            request_timeout = 600.0

        stats: Stats = asyncio.run(run_multiturn(A, users, turns, think))
        metrics = scrape_metrics(metrics_port)
        row = {
            "mode": mode,
            "users": users,
            "turns": turns,
            "completed": stats.completed,
            "errors": stats.errors,
            "output_tok_per_s": round(
                stats.tokens / max(stats.elapsed, 1e-9), 2
            ),
            "ttft_first_ms": ms(stats.ttft_first),
            "ttft_later_ms": ms(stats.ttft_later),
            "avg_prefix_hit_rate": round(
                metrics.get("llm_kv_avg_hit_rate", 0.0), 4
            ),
        }
        print(json.dumps(row), flush=True)
        return row
    except Exception:
        for _, log in fleet.procs:
            try:
                with open(log) as f:
                    print(f"--- {log} tail ---\n{f.read()[-2000:]}",
                          file=sys.stderr)
            except OSError:
                pass
        raise
    finally:
        fleet.teardown()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--users", type=int, default=8)
    p.add_argument("--turns", type=int, default=5)
    p.add_argument("--think", type=float, default=1.0)
    p.add_argument("--out", default=os.path.join(
        HERE, "results_router_ab.json"))
    cli = p.parse_args()

    tmp = tempfile.mkdtemp(prefix="dyn_router_ab_model_")
    model_dir = os.path.join(tmp, "model")
    os.makedirs(model_dir, exist_ok=True)
    for f in ("tokenizer.json", "tokenizer_config.json"):
        shutil.copy(os.path.join(TINY_MODEL, f), os.path.join(model_dir, f))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump(CONFIG, f)
    engine_args = os.path.join(tmp, "engine.json")
    with open(engine_args, "w") as f:
        json.dump(ENGINE, f)

    try:
        rows = [
            run_mode("kv", model_dir, engine_args,
                     cli.users, cli.turns, cli.think),
            run_mode("round_robin", model_dir, engine_args,
                     cli.users, cli.turns, cli.think),
        ]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    with open(cli.out, "w") as f:
        json.dump({
            "workload": "multiturn",
            "workers": 2,
            "users": cli.users,
            "turns": cli.turns,
            "rows": rows,
        }, f, indent=1)
    kv, rr = rows
    print("\n| mode | later-turn TTFT p50 | p99 | first-turn p50 | "
          "prefix hit |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(
            f"| {r['mode']} | {r['ttft_later_ms']['p50']} ms "
            f"| {r['ttft_later_ms']['p99']} ms "
            f"| {r['ttft_first_ms']['p50']} ms "
            f"| {r['avg_prefix_hit_rate']} |"
        )
    speedup = (
        rr["ttft_later_ms"]["p50"] / max(1e-9, kv["ttft_later_ms"]["p50"])
    )
    print(f"\nreturning-turn TTFT p50 speedup (kv vs rr): {speedup:.2f}x")


if __name__ == "__main__":
    main()
