"""KV router micro-benchmark: event ingest rate + match latency at scale.

Quantifies the indexer implementations against the reference's scale
story (reference: kv_router/indexer.rs — the sharded indexer exists
because one tree saturates; lib/llm benches apply_event/find_matches):

    python benchmarks/router_bench.py --blocks 1000000 --workers 32

One JSON line per implementation:
  {"impl", "blocks", "events_per_s", "match_p50_us", "match_p99_us"}
"""

from __future__ import annotations

import argparse
import json
import random
import time

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.kv_router.protocols import KvCacheEvent, RouterEvent  # noqa: E402


def make_events(n_blocks: int, n_workers: int, seq_len: int, block: int,
                seed: int = 7):
    """Synthetic stored-events: chains of seq_len hashes per sequence,
    sequences assigned round-robin to workers, ~10% shared prefix reuse."""
    rng = random.Random(seed)
    events = []
    queries = []
    made = 0
    sid = 0
    shared_roots: list[list[int]] = []
    while made < n_blocks:
        wid = 2**32 + (sid % n_workers)
        if shared_roots and rng.random() < 0.3:
            root = rng.choice(shared_roots)
            tail = [rng.getrandbits(63) for _ in range(seq_len - len(root))]
            hashes = root + tail
        else:
            hashes = [rng.getrandbits(63) for _ in range(seq_len)]
            if rng.random() < 0.3:
                shared_roots.append(hashes[: seq_len // 2])
        events.append(RouterEvent(
            worker_id=wid, event_id=sid + 1,
            event=KvCacheEvent(op="stored", block_hashes=hashes,
                               token_block_size=block),
        ))
        if rng.random() < 0.02:
            queries.append(hashes[: rng.randrange(1, seq_len)] +
                           [rng.getrandbits(63)])
        made += seq_len
        sid += 1
    # some queries with no overlap at all
    queries += [[rng.getrandbits(63) for _ in range(seq_len)]
                for _ in range(20)]
    rng.shuffle(queries)
    return events, queries[:200]


def bench_impl(name: str, make, events, queries) -> dict:
    idx = make()
    t0 = time.monotonic()
    for ev in events:
        idx.apply_event(ev) if hasattr(idx, "apply_event") else idx.apply(ev)
    # sharded: wait for queues to drain
    if hasattr(idx, "close_threads"):
        while idx.applied_events < len(events):
            time.sleep(0.005)
    ingest_s = time.monotonic() - t0

    lat = []
    t0 = time.monotonic()
    for q in queries:
        s = time.monotonic()
        idx.find_matches(q)
        lat.append(time.monotonic() - s)
    lat.sort()
    out = {
        "impl": name,
        "blocks": idx.num_blocks,
        "events_per_s": round(len(events) / ingest_s, 1),
        "block_hashes_per_s": round(
            sum(len(e.event.block_hashes) for e in events) / ingest_s, 1
        ),
        "match_p50_us": round(lat[len(lat) // 2] * 1e6, 1),
        "match_p99_us": round(lat[int(len(lat) * 0.99) - 1] * 1e6, 1),
    }
    if hasattr(idx, "close_threads"):
        idx.close_threads()
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--blocks", type=int, default=100_000)
    p.add_argument("--workers", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=64,
                   help="blocks per stored sequence")
    p.add_argument("--shards", type=int, default=4)
    args = p.parse_args()

    events, queries = make_events(args.blocks, args.workers, args.seq_len, 16)
    print(f"# {len(events)} events, {args.blocks} blocks, "
          f"{len(queries)} queries", file=sys.stderr)

    from dynamo_tpu import native
    from dynamo_tpu.kv_router.indexer import (
        KvIndexerSharded,
        NativeRadixTree,
        RadixTree,
    )

    print(json.dumps(bench_impl("python", RadixTree, events, queries)),
          flush=True)
    if native.is_available():
        print(json.dumps(
            bench_impl("native", NativeRadixTree, events, queries)
        ), flush=True)
        print(json.dumps(bench_impl(
            f"sharded-{args.shards}",
            lambda: KvIndexerSharded(num_shards=args.shards),
            events, queries,
        )), flush=True)


if __name__ == "__main__":
    main()
