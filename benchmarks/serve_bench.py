"""HTTP-level serving benchmark: real frontend+worker, concurrency sweep.

The committed, reproducible version of the reference's benchmark
methodology (reference: examples/llm/benchmarks/README.md:28-100 —
genai-perf closed-loop concurrency sweep at fixed ISL/OSL, recording
output tok/s and p50 TTFT). Spawns the actual serving stack
(``dynamo-tpu run --in http --out jax --static``) as a subprocess,
drives it with benchmarks/load_gen.py's closed loop, and emits one JSON
line per concurrency plus a markdown table to stdout.

Modes:
  --mode cpu   tiny model, CPU backend: CI smoke / methodology check
  --mode tpu   flagship 8B geometry, int8 weights, real chip

Results land in benchmarks/results_<mode>.json (committed for the
record; see benchmarks/RESULTS.md).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

from load_gen import (  # noqa: E402
    Stats,
    ms,
    one_request,
    run_closed_loop,
    run_multiturn,
)

TINY_MODEL = os.path.join(REPO, "tests", "data", "tiny_llama_model")

SHAPES = {
    "cpu": dict(
        config=dict(
            model_type="llama", vocab_size=2048, hidden_size=128,
            intermediate_size=256, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=2048,
        ),
        engine=dict(random_weights=True, num_blocks=512, block_size=16,
                    max_batch_size=16, decode_steps=4,
                    prefill_chunk_size=256),
        isl=64, osl=32, duration=15.0, concurrency=[1, 2, 4, 8],
    ),
    "tpu": dict(
        # DeepSeek-R1-Distill-Llama-8B geometry (BASELINE.md config 1);
        # int8 weights fit the single 16 GB chip
        config=dict(
            model_type="llama", vocab_size=128256, hidden_size=4096,
            intermediate_size=14336, num_hidden_layers=32,
            num_attention_heads=32, num_key_value_heads=8,
            max_position_embeddings=8192,
        ),
        engine=dict(random_weights=True, quantization="int8",
                    # max_batch_size=64 is the r5 number of record: the
                    # wide engine serves c=64 at ~1.9k out tok/s and
                    # holds lower concurrencies at or above the old
                    # mb=32 engine (mid decode bucket; RESULTS.md)
                    block_size=128, max_batch_size=64, decode_steps=32,
                    hbm_utilization=0.7, prefill_chunk_size=1024,
                    max_model_len=320),
        # isl is in WORDS (load_gen builds text); the test tokenizer
        # expands ~9 tokens/word, so 14 words ≈ 130 prompt tokens —
        # matching bench.py's 128/128 token workload under
        # max_model_len=320
        isl=14, osl=128, duration=90.0, concurrency=[1, 4, 16, 32],
    ),
    # the REFERENCE methodology (examples/llm/benchmarks/README.md:28-100
    # + perf.sh): ISL 3000 tokens / OSL 150, concurrency 1 -> 256.
    # Real block-table widths, real HBM pressure: one 16 GB chip's KV
    # budget holds only a handful of 3.2k-token contexts resident, so
    # high concurrencies measure the scheduler's admission/queueing
    # behavior under pressure — exactly what the r3 sweep (130-token
    # prompts, max_model_len 320) never exercised.
    "tpu_ref": dict(
        config=dict(
            model_type="llama", vocab_size=128256, hidden_size=4096,
            intermediate_size=14336, num_hidden_layers=32,
            num_attention_heads=32, num_key_value_heads=8,
            max_position_embeddings=8192,
        ),
        # hbm_utilization stays 0.7: 0.8 measured +3.5% at saturation
        # (341 vs 298 blocks, c=64 138->143) BUT introduced a one-time
        # ~106 s mid-serve stall shortly after startup (memory
        # pressure; absent at 0.7 — see RESULTS.md negative result),
        # which lands inside interactive windows. 0.85 was flat:
        # residency stops binding near ~340 blocks at this shape.
        engine=dict(random_weights=True, quantization="int8",
                    # int8 KV: the r5 record (saturation 139 -> 172
                    # out tok/s with the mid-chunk sync skip; see
                    # RESULTS.md round-5 sections)
                    kv_cache_dtype="int8",
                    block_size=128, max_batch_size=32, decode_steps=32,
                    hbm_utilization=0.7, prefill_chunk_size=1024,
                    max_model_len=3328),
        # ~9 tokens/word with the test tokenizer: 334 words ≈ 3000
        # prompt tokens
        isl=334, osl=150, duration=120.0, concurrency=[1, 4, 16, 64, 256],
    ),
    # KV-offload A/B on the reference's multi-turn recipe
    # (docs/architecture.md:91-96: multi-turn conversations x users,
    # system-memory KV tier measured as TTFT on RETURNING turns vs
    # prefix-caching-only). G1 is deliberately constrained
    # (num_blocks) so conversations evict between turns; variant B's
    # G2 host tier restores their blocks instead of recomputing.
    "tpu_offload": dict(
        config=dict(
            model_type="llama", vocab_size=128256, hidden_size=4096,
            intermediate_size=14336, num_hidden_layers=32,
            num_attention_heads=32, num_key_value_heads=8,
            max_position_embeddings=8192,
        ),
        engine=dict(random_weights=True, quantization="int8",
                    block_size=128, max_batch_size=32, decode_steps=32,
                    prefill_chunk_size=1024, max_model_len=2304,
                    num_blocks=192),
        # overlay: the G2 tier, FORCED past the restore-vs-recompute
        # probe — this mode exists to measure the tier itself (the
        # gate would disable it on a slow tunnel link)
        engine_b=dict(host_kv_blocks=768, kv_offload_force=True),
        # ~30 words x ~9 tok/word = ~270 prompt tokens per turn + 64
        # generated: 6 turns end near 2000 tokens of history
        workload="multiturn",
        isl=30, osl=64, users=24, turns=6, think=8.0,
        duration=0.0, concurrency=[],
    ),
    # CI smoke of the same machinery on CPU (tiny model, no pressure
    # claims — just that both variants serve and the report emits)
    "cpu_offload": dict(
        config=dict(
            model_type="llama", vocab_size=2048, hidden_size=128,
            intermediate_size=256, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=2048,
        ),
        engine=dict(random_weights=True, num_blocks=64, block_size=16,
                    max_batch_size=8, decode_steps=4,
                    prefill_chunk_size=256, max_model_len=512),
        engine_b=dict(host_kv_blocks=256, kv_offload_force=True),
        workload="multiturn",
        isl=4, osl=8, users=4, turns=3, think=0.2,
        duration=0.0, concurrency=[],
    ),
}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def make_model_dir(tmp: str, shape: dict) -> str:
    """Model dir = tiny test tokenizer + the benchmark shape's config
    (random weights: throughput is weight-agnostic)."""
    d = os.path.join(tmp, "model")
    os.makedirs(d, exist_ok=True)
    for f in ("tokenizer.json", "tokenizer_config.json"):
        shutil.copy(os.path.join(TINY_MODEL, f), os.path.join(d, f))
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(shape["config"], f)
    return d


def wait_ready(url: str, timeout: float) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(f"{url}/v1/models", timeout=2) as r:
                if json.load(r).get("data"):
                    return
        except Exception:
            pass
        time.sleep(1.0)
    raise RuntimeError(f"server at {url} not ready after {timeout}s")


async def drive(args, shape: dict) -> list[dict]:
    import aiohttp

    results = []
    for c in shape["concurrency"]:
        # untimed warmup at this concurrency: compiles (minutes over the
        # chip tunnel) must not land inside the measured window
        warm = Stats()
        async with aiohttp.ClientSession() as session:
            await asyncio.gather(
                *[one_request(session, args, warm) for _ in range(c)]
            )
        stats = await run_closed_loop(args, c)
        if stats.completed and not stats.tokens:
            raise RuntimeError(
                f"concurrency {c}: {stats.completed} requests completed "
                "with ZERO output tokens — the server is rejecting the "
                "workload (prompt over max_model_len?); results would "
                "be garbage"
            )
        row = {
            "concurrency": c,
            "completed": stats.completed,
            "errors": stats.errors,
            "output_tok_per_s": round(stats.tokens / max(stats.elapsed, 1e-9), 2),
            "ttft_ms": ms(stats.ttft),
            "e2e_ms": ms(stats.e2e),
        }
        print(json.dumps(row), flush=True)
        results.append(row)
    return results


def launch_server(
    mode: str, engine: dict, model_dir: str, tmp: str, tag: str,
    ready_timeout: float,
):
    """Start the real serving stack for one engine config; returns
    (proc, url, log_fh). Raises with the log tail if it never comes up."""
    engine_args = os.path.join(tmp, f"engine_{tag}.json")
    with open(engine_args, "w") as f:
        json.dump(engine, f)
    port = free_port()
    # APPEND to PYTHONPATH: replacing it would drop the accelerator
    # plugin's sitecustomize dir (e.g. the axon tunnel registers its
    # backend at interpreter boot via a PYTHONPATH entry)
    inherited = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ,
        PYTHONPATH=REPO + (os.pathsep + inherited if inherited else ""),
    )
    if mode.startswith("cpu"):
        env["JAX_PLATFORMS"] = "cpu"
    server_log = os.path.join(tmp, f"server_{tag}.log")
    log_fh = open(server_log, "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dynamo_tpu.cli.main", "run",
            "--in", "http", "--out", "jax", "--static",
            "--model-path", model_dir, "--model-name", "bench",
            "--http-host", "127.0.0.1", "--http-port", str(port),
            "--extra-engine-args", engine_args,
        ],
        env=env,
        stdout=log_fh,
        stderr=subprocess.STDOUT,
    )
    url = f"http://127.0.0.1:{port}"
    try:
        wait_ready(url, ready_timeout)
    except RuntimeError:
        with open(server_log) as f:
            print("--- server log tail ---\n" + f.read()[-4000:],
                  file=sys.stderr)
        stop_server(proc, log_fh)
        raise
    return proc, url, log_fh


def stop_server(proc, log_fh) -> None:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
    log_fh.close()


def bench_args(url: str, shape: dict):
    class A:
        pass

    a = A()
    a.url = url
    a.model = "bench"
    a.isl = shape["isl"]
    a.osl = shape["osl"]
    a.duration = shape["duration"]
    a.request_timeout = 600.0
    return a


def drive_multiturn(cli, shape: dict, model_dir: str, tmp: str) -> list[dict]:
    """A/B the multi-turn conversation workload: variant 'prefix_only'
    (base engine) vs 'g2_host' (base + engine_b overlay, the host KV
    tier). Each variant gets its own server; the headline is the
    RETURNING-turn TTFT delta (reference: docs/architecture.md:91-96,
    +40% TTFT from the system-memory tier)."""
    variants = [
        ("prefix_only", dict(shape["engine"])),
        ("g2_host", dict(shape["engine"], **shape["engine_b"])),
    ]
    rows = []
    for tag, engine in variants:
        proc, url, log_fh = launch_server(
            cli.mode, engine, model_dir, tmp, tag, cli.ready_timeout
        )
        try:
            a = bench_args(url, shape)
            # warmup: one short conversation compiles every shape
            warm_stats = asyncio.run(
                run_multiturn(a, users=1, turns=2, think=0.0)
            )
            if warm_stats.errors:
                raise RuntimeError(f"{tag}: warmup conversation errored")
            stats = asyncio.run(
                run_multiturn(
                    a, users=shape["users"], turns=shape["turns"],
                    think=shape["think"],
                )
            )
            row = {
                "variant": tag,
                "users": shape["users"],
                "turns": shape["turns"],
                "completed": stats.completed,
                "errors": stats.errors,
                "output_tok_per_s": round(
                    stats.tokens / max(stats.elapsed, 1e-9), 2
                ),
                "ttft_first_ms": ms(stats.ttft_first),
                "ttft_later_ms": ms(stats.ttft_later),
                "e2e_ms": ms(stats.e2e),
            }
            print(json.dumps(row), flush=True)
            rows.append(row)
        finally:
            stop_server(proc, log_fh)
    return rows


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--mode",
        choices=["cpu", "tpu", "tpu_ref", "tpu_offload", "cpu_offload"],
        default="cpu",
    )
    p.add_argument("--duration", type=float, default=None)
    p.add_argument("--concurrency", default=None, help="comma list override")
    p.add_argument("--users", type=int, default=None)
    p.add_argument("--turns", type=int, default=None)
    p.add_argument("--keep-logs", default=None,
                   help="copy server logs to this directory instead of "
                        "deleting them with the tmp dir (stall forensics)")
    p.add_argument("--engine-override", default=None,
                   help="JSON dict merged over the shape's engine config "
                        "(e.g. '{\"mixed_wide_max_running\": 32}')")
    p.add_argument("--ready-timeout", type=float, default=1200.0)
    p.add_argument("--out", default=None, help="results JSON path")
    cli = p.parse_args()

    shape = SHAPES[cli.mode]
    if cli.duration:
        shape = dict(shape, duration=cli.duration)
    if cli.concurrency:
        shape = dict(
            shape, concurrency=[int(x) for x in cli.concurrency.split(",")]
        )
    if cli.users:
        shape = dict(shape, users=cli.users)
    if cli.turns:
        shape = dict(shape, turns=cli.turns)
    if cli.engine_override:
        shape = dict(
            shape,
            engine=dict(shape["engine"], **json.loads(cli.engine_override)),
        )

    tmp = tempfile.mkdtemp(prefix="dyn_serve_bench_")
    model_dir = make_model_dir(tmp, shape)
    try:
        if shape.get("workload") == "multiturn":
            rows = drive_multiturn(cli, shape, model_dir, tmp)
            out_path = cli.out or os.path.join(
                HERE, f"results_{cli.mode}.json"
            )
            with open(out_path, "w") as f:
                json.dump(
                    {
                        "mode": cli.mode,
                        "workload": "multiturn",
                        "isl": shape["isl"],
                        "osl": shape["osl"],
                        "users": shape["users"],
                        "turns": shape["turns"],
                        "think_s": shape["think"],
                        "engine": shape["engine"],
                        "engine_b": shape["engine_b"],
                        "model_geometry": shape["config"],
                        "rows": rows,
                    },
                    f,
                    indent=1,
                )
            print("\n| variant | out tok/s | turn-1 TTFT p50 | "
                  "returning-turn TTFT p50 | p99 |")
            print("|---|---|---|---|---|")
            for r in rows:
                print(
                    f"| {r['variant']} | {r['output_tok_per_s']} "
                    f"| {r['ttft_first_ms']['p50']} "
                    f"| {r['ttft_later_ms']['p50']} "
                    f"| {r['ttft_later_ms']['p99']} |"
                )
            return

        proc, url, log_fh = launch_server(
            cli.mode, shape["engine"], model_dir, tmp, "main",
            cli.ready_timeout,
        )
        try:
            rows = asyncio.run(drive(bench_args(url, shape), shape))
        finally:
            stop_server(proc, log_fh)
        out_path = cli.out or os.path.join(HERE, f"results_{cli.mode}.json")
        with open(out_path, "w") as f:
            json.dump(
                {
                    "mode": cli.mode,
                    "isl": shape["isl"],
                    "osl": shape["osl"],
                    "duration_s": shape["duration"],
                    "engine": shape["engine"],
                    "model_geometry": shape["config"],
                    "rows": rows,
                },
                f,
                indent=1,
            )
        # markdown table for RESULTS.md
        print("\n| conc | out tok/s | p50 TTFT ms | p99 TTFT ms | p50 e2e ms |")
        print("|---|---|---|---|---|")
        for r in rows:
            print(
                f"| {r['concurrency']} | {r['output_tok_per_s']} "
                f"| {r['ttft_ms']['p50']} | {r['ttft_ms']['p99']} "
                f"| {r['e2e_ms']['p50']} |"
            )
    finally:
        if cli.keep_logs:
            os.makedirs(cli.keep_logs, exist_ok=True)
            for f in os.listdir(tmp):
                if f.startswith("server") and f.endswith(".log"):
                    shutil.copy(os.path.join(tmp, f), cli.keep_logs)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
