{{- define "dynamo-tpu.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end -}}

{{- define "dynamo-tpu.labels" -}}
app.kubernetes.io/name: dynamo-tpu
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{- define "dynamo-tpu.storeHost" -}}
{{ .Release.Name }}-store
{{- end -}}
