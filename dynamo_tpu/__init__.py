"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of NVIDIA Dynamo
(reference: /root/reference, surveyed in SURVEY.md): disaggregated
prefill/decode serving, KV-aware routing, multi-tier KV block management,
an OpenAI-compatible frontend, and a planner for dynamic scaling — built
for TPU device meshes (ICI/DCN) instead of CUDA/NVLink/RDMA.

Layer map (TPU-native analogue of reference layer map, SURVEY.md §1):

  runtime/    distributed runtime: component model, streaming engines,
              pipeline graph, push routers        (≈ lib/runtime, Rust)
  store/      control plane: KV+lease+watch, pub/sub, queues, object
              store — self-hosted, no external etcd/NATS (≈ L0 infra)
  tokens.py   token blocks + chained hashing      (≈ lib/llm/src/tokens.rs)
  protocols/  OpenAI protocol types, SSE, deltas  (≈ lib/llm/src/protocols)
  preprocessor/ chat templates + tokenization     (≈ lib/llm/src/preprocessor.rs)
  backend.py  incremental detokenize + stop logic (≈ lib/llm/src/backend.rs)
  http/       OpenAI HTTP service                 (≈ lib/llm/src/http)
  kv_router/  radix indexer + KV-aware scheduler  (≈ lib/llm/src/kv_router)
  block_manager/ tiered KV block pools + offload  (≈ lib/llm/src/block_manager)
  engine/     native JAX inference engine (continuous batching, paged KV)
  models/     flagship model families (Llama, Mixtral, ...)
  ops/        Pallas TPU kernels (paged attention, block copy, rearrange)
  parallel/   mesh/sharding utilities, ring attention, collectives
  disagg/     disaggregated prefill/decode + KV transfer agent
  planner/    dynamic scaling
  sdk/        @service decorators + serve/run CLI (≈ deploy/sdk)

Heavy imports (jax, transformers) are deferred: importing ``dynamo_tpu``
itself is cheap so control-plane tools start fast.
"""

__version__ = "0.1.0"
