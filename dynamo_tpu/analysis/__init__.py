"""dynalint: AST-based invariant checks for the async/TPU serving stack.

The reference Dynamo leans on Rust's compiler to rule out whole classes
of concurrency and resource bugs statically; this package is the Python
reproduction's substitute guardrail. Pure stdlib (``ast`` + ``fnmatch``)
— zero dependencies, runs at pytest time and on every PR.

Public API::

    from dynamo_tpu.analysis import lint_paths, lint_source, all_rules
    findings = lint_paths(["dynamo_tpu"], config=load_config())

CLI: ``dynamo-tpu lint [paths] [--format json]`` — exits non-zero on
unsuppressed findings. Suppress a finding in place with
``# dynalint: disable=<rule-name> — justification``.
"""

from dynamo_tpu.analysis.config import DEFAULTS, load_config  # noqa: F401
from dynamo_tpu.analysis.findings import (  # noqa: F401
    Finding,
    format_json,
    format_text,
    unsuppressed,
)
from dynamo_tpu.analysis.registry import (  # noqa: F401
    LintModule,
    Rule,
    all_rules,
    get_rule,
    rule,
)
from dynamo_tpu.analysis.walker import (  # noqa: F401
    iter_files,
    lint_paths,
    lint_source,
)
