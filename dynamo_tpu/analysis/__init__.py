"""dynalint: whole-program invariant checks for the async/TPU stack.

The reference Dynamo leans on Rust's compiler to rule out whole classes
of concurrency and resource bugs statically; this package is the Python
reproduction's substitute guardrail. Pure stdlib (``ast`` + ``fnmatch``)
— zero dependencies, runs at pytest time and on every PR.

Two layers (docs/static_analysis.md):

- per-file AST rules (DL0xx) over one ``LintModule`` at a time;
- whole-program rules (DL1xx) over a :class:`LintProgram` — a
  project-wide symbol table + call graph (``callgraph.py``) with
  async-context / step-loop / thread-affinity taints propagated along
  it (``taint.py``) — catching blocking calls, device syncs, and
  undeclared cross-thread mutations hidden call levels deep.

Public API::

    from dynamo_tpu.analysis import lint_paths, lint_source, all_rules
    findings = lint_paths(["dynamo_tpu"], config=load_config())

CLI: ``dynamo-tpu lint [paths] [--format json|github] [--changed]
[--baseline FILE]`` — exits non-zero on gating findings. Suppress a
finding in place with ``# dynalint: disable=<rule-name> —
justification``; declare a deliberate cross-thread write with
``# dynalint: handoff=<why>`` (plus ``affinity.handoff(...)`` for the
runtime sanitizer).
"""

from dynamo_tpu.analysis.config import DEFAULTS, load_config  # noqa: F401
from dynamo_tpu.analysis.findings import (  # noqa: F401
    Finding,
    apply_baseline,
    format_github,
    format_json,
    format_sarif,
    format_text,
    gating,
    stale_baseline_entries,
    unsuppressed,
    write_baseline,
)
from dynamo_tpu.analysis.registry import (  # noqa: F401
    LintModule,
    Rule,
    all_rules,
    get_rule,
    rule,
)
from dynamo_tpu.analysis.walker import (  # noqa: F401
    iter_files,
    lint_paths,
    lint_source,
    lint_sources_program,
)


def __getattr__(name):
    # program-layer API without import cycles at package import time
    if name in (
        "LintProgram",
        "ProgramRule",
        "all_program_rules",
        "get_program_rule",
        "program_rule",
        "build_program",
    ):
        from dynamo_tpu.analysis import program

        return getattr(program, name)
    if name in ("CallGraph", "build_callgraph"):
        from dynamo_tpu.analysis import callgraph

        return getattr(callgraph, name)
    if name in ("Taints", "compute_taints", "format_chain"):
        from dynamo_tpu.analysis import taint

        return getattr(taint, name)
    if name in ("LintCache", "default_cache_dir", "rule_signature"):
        from dynamo_tpu.analysis import cache

        return getattr(cache, name)
    raise AttributeError(name)
