"""Pure AST helpers shared by rules and the call-graph layer.

A leaf module: the call graph / taint / program machinery imports from
here without touching the rules package (whose __init__ imports every
rule module, some of which import the program machinery — a cycle).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_in_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node``'s body without descending into nested function
    definitions — "what executes in THIS function's frame"."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))
