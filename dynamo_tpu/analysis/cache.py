"""On-disk lint result cache: warm whole-repo lint in well under 5s.

Keys are *content-derived*, so invalidation is automatic and exact:

- per-file entry: ``sha256(file bytes) + rule-set signature + config
  hash`` -> that file's per-file-rule findings;
- whole-program entry: ``sha256(every file's sha, sorted by path) +
  program-rule signature + config hash`` -> the program pass findings
  (the call graph spans every file, so ANY edit invalidates it — the
  per-file entries for untouched files still hit).

The **rule-set signature folds in a hash of the analysis package's own
sources**: editing a rule, the call-graph builder, or the taint engine
invalidates every entry without a version knob to forget to bump.

Storage is one JSON file under ``.dynalint_cache/`` next to
pyproject.toml (gitignored), written atomically (tmp + rename) and
pruned of entries unused for 7 days so stale blobs don't accumulate.
Every failure path degrades to a miss — the cache must never be the
reason lint is wrong or crashes; ``dynamo-tpu lint --no-cache``
bypasses it entirely.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import time
from pathlib import Path
from typing import Dict, List, Optional

from dynamo_tpu.analysis.findings import Finding

_PRUNE_AFTER_S = 7 * 24 * 3600
_pkg_hash: Optional[str] = None


def _package_hash() -> str:
    """sha256 over the analysis package's own sources (+ the affinity
    vocabulary the rules read), computed once per process."""
    global _pkg_hash
    if _pkg_hash is None:
        h = hashlib.sha256()
        pkg = Path(__file__).parent
        files = sorted(pkg.rglob("*.py"))
        affinity = pkg.parent / "utils" / "affinity.py"
        if affinity.exists():
            files.append(affinity)
        for f in files:
            try:
                h.update(f.name.encode())
                h.update(f.read_bytes())
            except OSError:
                pass
        _pkg_hash = h.hexdigest()[:16]
    return _pkg_hash


def file_sha(path: Path) -> Optional[str]:
    try:
        return hashlib.sha256(path.read_bytes()).hexdigest()
    except OSError:
        return None


def rule_signature(rule_names: List[str], config: dict) -> str:
    """One token binding the enabled rules + config + analyzer code."""
    h = hashlib.sha256()
    h.update(",".join(sorted(rule_names)).encode())
    h.update(json.dumps(config, sort_keys=True, default=str).encode())
    h.update(_package_hash().encode())
    return h.hexdigest()[:16]


class LintCache:
    def __init__(self, cache_dir: Path):
        self.path = Path(cache_dir) / "cache.json"
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._entries: Dict[str, dict] = {}
        try:
            data = json.loads(self.path.read_text())
            if isinstance(data, dict) and data.get("version") == 1:
                self._entries = data.get("entries", {})
        except (OSError, ValueError):
            self._entries = {}

    # -- keys ------------------------------------------------------------
    @staticmethod
    def file_key(path: str, sha: str, sig: str) -> str:
        # path is part of the key: findings embed it, so identical
        # content at a new location must not replay the old path
        ph = hashlib.sha256(path.encode()).hexdigest()[:12]
        return f"f:{sha}:{ph}:{sig}"

    @staticmethod
    def program_key(shas: Dict[str, str], sig: str) -> str:
        h = hashlib.sha256()
        for path in sorted(shas):
            h.update(path.encode())
            h.update(shas[path].encode())
        return f"p:{h.hexdigest()[:32]}:{sig}"

    # -- get/put ---------------------------------------------------------
    def get(self, key: str) -> Optional[List[Finding]]:
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        entry["ts"] = time.time()
        self._dirty = True  # ts refresh keeps hot entries alive
        self.hits += 1
        try:
            return [Finding(**f) for f in entry["findings"]]
        except (TypeError, KeyError):
            self.misses += 1
            return None

    def put(self, key: str, findings: List[Finding]) -> None:
        self._entries[key] = {
            "ts": time.time(),
            "findings": [dataclasses.asdict(f) for f in findings],
        }
        self._dirty = True

    # -- persistence -----------------------------------------------------
    def save(self) -> None:
        if not self._dirty:
            return
        now = time.time()
        entries = {
            k: v
            for k, v in self._entries.items()
            if now - v.get("ts", 0) < _PRUNE_AFTER_S
        }
        payload = json.dumps({"version": 1, "entries": entries})
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=str(self.path.parent), suffix=".tmp"
            )
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a cache that can't persist is just a cold cache


def default_cache_dir(start: Path) -> Optional[Path]:
    """.dynalint_cache/ next to the governing pyproject.toml."""
    from dynamo_tpu.analysis.config import find_pyproject

    pyproject = find_pyproject(start)
    if pyproject is None:
        return None
    return pyproject.parent / ".dynalint_cache"
