"""Whole-program symbol table + call graph for dynalint.

Per-file AST rules (DL001-DL010) see one frame at a time; a blocking
call or device sync hidden one call level deep is invisible to them.
This module builds the project-wide view the DL1xx rules run on:

- a **symbol table**: every module, class, function, and method in the
  linted file set, addressed by qualname ``pkg.mod:Class.method`` /
  ``pkg.mod:func`` / ``pkg.mod:outer.<locals>.inner``;
- a **call graph**: best-effort resolution of every ``ast.Call`` to a
  project function — direct names, imported symbols (``import a.b as
  c`` / ``from a.b import f``), ``self.``/``cls.`` method dispatch
  (including one level of attribute-type inference from
  ``self.x = ClassName(...)`` in any method), class instantiation
  (edge to ``__init__``), ``functools.partial`` unwrapping, and
  function *references* passed as callbacks;
- **edge kinds**: a reference passed to a thread-handoff construct
  (``run_in_executor``, ``asyncio.to_thread``, ``threading.Thread
  (target=...)``, ``call_soon_threadsafe``,
  ``run_coroutine_threadsafe``) is a ``spawn``/``to_loop`` edge, not a
  same-context call — the taint passes (taint.py) must not propagate
  the caller's execution context across it;
- **unresolved calls are counted, not dropped**: dynamic dispatch we
  can't see (``getattr(obj, name)()``, callables in dicts, externals'
  callbacks) is tallied per caller so the analysis reports its own
  blind spots instead of silently pretending coverage.

Resolution is deliberately conservative-but-useful: a miss becomes an
``unresolved`` entry (no edge), never a wrong edge to an unrelated
symbol.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from dynamo_tpu.analysis.astutil import dotted_name

# call-site receivers that hand a callable to ANOTHER thread/loop.
# value = the execution context the callable lands on: "other" (a fresh
# or pool thread), "loop" (the event loop). Matched on the last one or
# two segments of the dotted receiver.
HANDOFF_RECEIVERS: Dict[str, str] = {
    "run_in_executor": "other",
    "to_thread": "other",
    "call_soon_threadsafe": "loop",
    "call_soon": "loop",
    "call_later": "loop",
    "run_coroutine_threadsafe": "loop",
    "Thread": "other",  # threading.Thread(target=...)
    "spawn": "loop",  # utils.tasks.spawn(coro) — stays on the loop
    "create_task": "loop",
    "ensure_future": "loop",
}

# edge kinds
CALL = "call"  # same execution context: caller's frame invokes callee
REF = "ref"  # callable passed around in the same context (callback)
SPAWN_OTHER = "spawn-other"  # callee runs on some other thread
SPAWN_LOOP = "spawn-loop"  # callee runs on the event loop

# same-context kinds (taint flows across these)
SAME_CONTEXT = (CALL, REF)


@dataclass
class FunctionInfo:
    qualname: str  # "pkg.mod:Class.method" or "pkg.mod:func"
    module: str  # dotted module name
    path: str  # source file
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_async: bool
    cls: Optional[str] = None  # enclosing class qualname ("pkg.mod:Cls")
    decorators: List[str] = field(default_factory=list)  # dotted names
    affinity: Optional[str] = None  # @thread_affinity("...") literal

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1].split(":")[-1]

    @property
    def lineno(self) -> int:
        return getattr(self.node, "lineno", 1)


@dataclass
class ClassInfo:
    qualname: str  # "pkg.mod:Cls"
    module: str
    path: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)  # raw dotted names
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fn qualname
    # self.<attr> = ClassName(...) inference: attr -> class qualname
    attr_types: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[str] = None  # @thread_affinity on the class


@dataclass
class Edge:
    caller: str
    callee: str
    kind: str  # CALL | REF | SPAWN_OTHER | SPAWN_LOOP
    lineno: int


@dataclass
class CallGraph:
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    edges: List[Edge] = field(default_factory=list)
    # caller qualname -> raw call strings that did not resolve
    unresolved: Dict[str, List[str]] = field(default_factory=dict)
    # module dotted name -> {local symbol -> fully dotted target}
    imports: Dict[str, Dict[str, str]] = field(default_factory=dict)

    # -- derived views ---------------------------------------------------
    def out_edges(self, caller: str) -> List[Edge]:
        return self._by_caller.get(caller, [])

    def in_edges(self, callee: str) -> List[Edge]:
        return self._by_callee.get(callee, [])

    def freeze(self) -> None:
        """Build the adjacency indexes once the edge list is final."""
        self._by_caller: Dict[str, List[Edge]] = {}
        self._by_callee: Dict[str, List[Edge]] = {}
        for e in self.edges:
            self._by_caller.setdefault(e.caller, []).append(e)
            self._by_callee.setdefault(e.callee, []).append(e)

    def stats(self) -> Dict[str, int]:
        return {
            "functions": len(self.functions),
            "classes": len(self.classes),
            "edges": len(self.edges),
            "unresolved_calls": sum(len(v) for v in self.unresolved.values()),
        }


def module_name_for(path: Path, roots: Optional[List[Path]] = None) -> str:
    """Dotted module name for a file: walk up while __init__.py exists
    (the project layout truth), so dynamo_tpu/ops/kv_quant.py maps to
    dynamo_tpu.ops.kv_quant regardless of cwd."""
    path = path.resolve()
    parts = [path.stem] if path.name != "__init__.py" else []
    cur = path.parent
    while (cur / "__init__.py").exists():
        parts.append(cur.name)
        cur = cur.parent
    if not parts:  # stray script: module name is the stem
        parts = [path.stem]
    return ".".join(reversed(parts))


def _decorator_names(node: ast.AST) -> List[str]:
    out = []
    for d in getattr(node, "decorator_list", []):
        target = d.func if isinstance(d, ast.Call) else d
        name = dotted_name(target)
        if name:
            out.append(name)
    return out


def _affinity_literal(node: ast.AST) -> Optional[str]:
    """The literal domain from a @thread_affinity("...") decorator."""
    for d in getattr(node, "decorator_list", []):
        if not isinstance(d, ast.Call):
            continue
        name = dotted_name(d.func) or ""
        if name.split(".")[-1] == "thread_affinity" and d.args:
            arg = d.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
    return None


class _ModuleIndexer(ast.NodeVisitor):
    """First pass over one module: symbols + imports (no call edges)."""

    def __init__(self, graph: CallGraph, module: str, path: str,
                 tree: ast.Module):
        self.graph = graph
        self.module = module
        self.path = path
        self.tree = tree
        self.imports: Dict[str, str] = {}
        self._scope: List[str] = []  # qualname suffix stack
        self._class: List[str] = []  # enclosing class qualnames
        self._class_depth: List[int] = []  # len(_scope) at class entry
        self._in_function = False

    def run(self) -> None:
        self.graph.imports[self.module] = self.imports
        self.visit(self.tree)

    # imports ------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.asname:
                self.imports[a.asname] = a.name
            else:
                # `import a.b.c` binds `a`; dotted call spellings
                # (a.b.c.f()) resolve through the full prefix entry
                self.imports[a.name.split(".")[0]] = a.name.split(".")[0]
                if "." in a.name:
                    self.imports[a.name] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level:  # relative: resolve against this module/package
            parts = self.module.split(".")
            # in pkg/mod.py (module pkg.mod) "from ." is pkg: strip 1;
            # in pkg/__init__.py (module pkg) "from ." is pkg: strip 0
            strip = node.level if not self._is_package() else node.level - 1
            anchor = ".".join(parts[: len(parts) - strip]) if strip else \
                self.module
            prefix = anchor + ("." + node.module if node.module else "")
        else:
            prefix = node.module or ""
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = (
                f"{prefix}.{a.name}" if prefix else a.name
            )

    def _is_package(self) -> bool:
        return Path(self.path).name == "__init__.py"

    # defs ---------------------------------------------------------------
    def _qual(self, name: str) -> str:
        if self._scope:
            return f"{self.module}:{'.'.join(self._scope)}.{name}"
        return f"{self.module}:{name}"

    def _add_function(self, node, is_async: bool) -> None:
        qn = self._qual(node.name)
        in_class_body = bool(self._class) and \
            len(self._scope) == self._class_depth[-1]
        info = FunctionInfo(
            qualname=qn,
            module=self.module,
            path=self.path,
            node=node,
            is_async=is_async,
            cls=self._class[-1] if in_class_body else None,
            decorators=_decorator_names(node),
            affinity=_affinity_literal(node),
        )
        self.graph.functions[qn] = info
        if info.cls is not None:
            self.graph.classes[info.cls].methods[node.name] = qn
        # children defined inside this function are <locals>-scoped
        self._scope.append(f"{node.name}.<locals>")
        was = self._in_function
        self._in_function = True
        self.generic_visit(node)
        self._in_function = was
        self._scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._add_function(node, is_async=False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._add_function(node, is_async=True)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        qn = self._qual(node.name)
        self.graph.classes[qn] = ClassInfo(
            qualname=qn,
            module=self.module,
            path=self.path,
            node=node,
            bases=[b for b in (dotted_name(x) for x in node.bases) if b],
            affinity=_affinity_literal(node),
        )
        self._scope.append(node.name)
        self._class.append(qn)
        self._class_depth.append(len(self._scope))
        self.generic_visit(node)
        self._class_depth.pop()
        self._class.pop()
        self._scope.pop()


def _infer_attr_types(graph: CallGraph) -> None:
    """self.<attr> = ClassName(...) in any method -> attr type, so
    ``self.scheduler.plan()`` resolves into the Scheduler class."""
    for cls in graph.classes.values():
        for mname, fq in cls.methods.items():
            fn = graph.functions.get(fq)
            if fn is None:
                continue
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                value = node.value
                if isinstance(value, ast.BoolOp):
                    # the `self.x = x or Default()` idiom: type from the
                    # constructor operand
                    calls = [v for v in value.values
                             if isinstance(v, ast.Call)]
                    value = calls[-1] if calls else value
                if not isinstance(value, ast.Call):
                    continue
                cname = dotted_name(value.func)
                if not cname:
                    continue
                target_cls = _resolve_class(graph, fn.module, cname)
                if target_cls is None:
                    continue
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        cls.attr_types.setdefault(t.attr, target_cls)


def _resolve_symbol(graph: CallGraph, module: str, name: str) -> Optional[str]:
    """Resolve a dotted name as seen from ``module`` to a project
    function qualname (follows import aliases one level)."""
    imports = graph.imports.get(module, {})
    head, _, rest = name.partition(".")
    # same-module function (incl. nested refs by bare name)
    if not rest:
        qn = f"{module}:{name}"
        if qn in graph.functions:
            return qn
        target = imports.get(name)
        if target:
            return _dotted_to_function(graph, target)
        return None
    # head is an import alias: a module or a symbol
    target = imports.get(head)
    if target:
        return _dotted_to_function(graph, f"{target}.{rest}")
    # fully dotted name used without alias (import a.b.c)
    return _dotted_to_function(graph, name)


def _dotted_to_function(
    graph: CallGraph, dotted: str, _seen: Optional[Set[str]] = None
) -> Optional[str]:
    """pkg.mod.func / pkg.mod.Cls.method -> qualname, if in-project."""
    seen = _seen if _seen is not None else set()
    if dotted in seen:  # re-export cycle (import x as x, pkg __init__s)
        return None
    seen.add(dotted)
    parts = dotted.split(".")
    for split in range(len(parts) - 1, 0, -1):
        mod = ".".join(parts[:split])
        if mod not in graph.imports:  # not a project module
            continue
        sym = ".".join(parts[split:])
        qn = f"{mod}:{sym}"
        if qn in graph.functions:
            return qn
        # Cls.method
        if "." in sym:
            cls_name, _, meth = sym.rpartition(".")
            cls = graph.classes.get(f"{mod}:{cls_name}")
            if cls and meth in cls.methods:
                return cls.methods[meth]
        # Cls -> __init__
        cls = graph.classes.get(qn)
        if cls is not None:
            return cls.methods.get("__init__")
        # re-exported symbol (from x import y in mod's __init__)
        reexport = graph.imports.get(mod, {}).get(sym.split(".")[0])
        if reexport:
            tail = sym.partition(".")[2]
            return _dotted_to_function(
                graph, reexport + ("." + tail if tail else ""), seen
            )
    return None


def _resolve_class(graph: CallGraph, module: str, name: str) -> Optional[str]:
    """Resolve a dotted name to a project class qualname."""
    imports = graph.imports.get(module, {})
    head, _, rest = name.partition(".")
    if not rest:
        qn = f"{module}:{name}"
        if qn in graph.classes:
            return qn
        target = imports.get(name)
        if target:
            return _dotted_to_class(graph, target)
        return None
    target = imports.get(head)
    if target:
        return _dotted_to_class(graph, f"{target}.{rest}")
    return _dotted_to_class(graph, name)


def _dotted_to_class(
    graph: CallGraph, dotted: str, _seen: Optional[Set[str]] = None
) -> Optional[str]:
    seen = _seen if _seen is not None else set()
    if dotted in seen:
        return None
    seen.add(dotted)
    parts = dotted.split(".")
    for split in range(len(parts), 0, -1):
        mod = ".".join(parts[:split])
        if mod not in graph.imports:
            continue
        sym = ".".join(parts[split:])
        if not sym:
            continue
        qn = f"{mod}:{sym}"
        if qn in graph.classes:
            return qn
        reexport = graph.imports.get(mod, {}).get(sym.split(".")[0])
        if reexport:
            tail = sym.partition(".")[2]
            return _dotted_to_class(
                graph, reexport + ("." + tail if tail else ""), seen
            )
    return None


def _method_in_mro(graph: CallGraph, cls_qn: str, method: str,
                   _seen: Optional[Set[str]] = None) -> Optional[str]:
    """Look up a method through project-local base classes."""
    seen = _seen or set()
    if cls_qn in seen:
        return None
    seen.add(cls_qn)
    cls = graph.classes.get(cls_qn)
    if cls is None:
        return None
    if method in cls.methods:
        return cls.methods[method]
    for base in cls.bases:
        base_qn = _resolve_class(graph, cls.module, base)
        if base_qn:
            hit = _method_in_mro(graph, base_qn, method, seen)
            if hit:
                return hit
    return None


class _CallResolver(ast.NodeVisitor):
    """Second pass: walk one function's own frame and emit edges."""

    def __init__(self, graph: CallGraph, fn: FunctionInfo):
        self.graph = graph
        self.fn = fn

    def run(self) -> None:
        # body only: decorator expressions run at import time, not in
        # this function's frame
        for child in self.fn.node.body:
            self._walk(child)

    def _walk(self, node: ast.AST) -> None:
        # stay in this frame: nested defs resolve their own bodies
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # defining a nested function is a same-context REF edge —
            # if the parent never calls it the taint is conservative,
            # which is the right direction for a linter
            nested = self._nested_qualname(node.name)
            if nested:
                self._edge(nested, REF, node.lineno)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Call):
            self._handle_call(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _nested_qualname(self, name: str) -> Optional[str]:
        qn = f"{self.fn.qualname}.<locals>.{name}"
        return qn if qn in self.graph.functions else None

    def _enclosing_class(self) -> Optional[str]:
        """The class a closure's ``self`` refers to: walk the qualname
        up past ``<locals>`` segments to the outermost method."""
        if self.fn.cls is not None:
            return self.fn.cls
        if "<locals>" not in self.fn.qualname:
            return None
        outer_qn = self.fn.qualname.split(".<locals>.", 1)[0]
        outer = self.graph.functions.get(outer_qn)
        return outer.cls if outer else None

    def _edge(self, callee: str, kind: str, lineno: int) -> None:
        self.graph.edges.append(
            Edge(caller=self.fn.qualname, callee=callee, kind=kind,
                 lineno=lineno)
        )

    def _unresolved(self, raw: str) -> None:
        self.graph.unresolved.setdefault(self.fn.qualname, []).append(raw)

    # -- resolution ------------------------------------------------------
    def _handle_call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        handled_args: Set[int] = set()
        if name is not None:
            tail = name.split(".")[-1]
            handoff = HANDOFF_RECEIVERS.get(tail)
            if handoff is not None:
                handled_args = self._handoff_refs(node, handoff)
            if tail == "partial" and node.args:
                # functools.partial(f, ...): same-context ref to f
                target = self._resolve_expr(node.args[0])
                if target:
                    self._edge(target, REF, node.lineno)
                handled_args.add(0)
            target = self._resolve_call_name(name)
            if target is not None:
                self._edge(target, CALL, node.lineno)
            elif handoff is None and not self._is_external(name):
                self._unresolved(name)
        else:
            # getattr(x, n)(), obj.table[k](), (a or b)() — dynamic
            self._unresolved(ast.unparse(node.func)[:60] if hasattr(
                ast, "unparse") else "<dynamic>")
        # callable references in arguments (callbacks): same context
        for i, arg in enumerate(node.args):
            if i in handled_args:
                continue
            ref = self._resolve_expr(arg)
            if ref:
                self._edge(ref, REF, node.lineno)
        for kw in node.keywords:
            if kw.arg == "target" and "Thread" in (name or ""):
                continue  # handled by _handoff_refs
            ref = self._resolve_expr(kw.value)
            if ref:
                self._edge(ref, REF, node.lineno)

    def _handoff_refs(self, node: ast.Call, context: str) -> Set[int]:
        """Emit spawn edges for callables handed to another context;
        returns positional arg indexes consumed."""
        kind = SPAWN_LOOP if context == "loop" else SPAWN_OTHER
        consumed: Set[int] = set()
        for i, arg in enumerate(node.args):
            target = self._resolve_expr(arg)
            if target:
                self._edge(target, kind, node.lineno)
                consumed.add(i)
        for kw in node.keywords:
            if kw.arg in ("target", "func", "callback"):
                target = self._resolve_expr(kw.value)
                if target:
                    self._edge(target, kind, node.lineno)
        return consumed

    def _resolve_expr(self, expr: ast.AST) -> Optional[str]:
        """A bare function reference (or call producing a coroutine —
        ``run_coroutine_threadsafe(coro_fn(...), loop)``)."""
        if isinstance(expr, ast.Call):
            # coroutine objects / partial results: resolve the callee
            inner = dotted_name(expr.func)
            if inner and inner.split(".")[-1] == "partial" and expr.args:
                return self._resolve_expr(expr.args[0])
            if inner:
                return self._resolve_call_name(inner)
            return None
        name = dotted_name(expr)
        if name is None:
            return None
        return self._resolve_call_name(name)

    def _resolve_call_name(self, name: str) -> Optional[str]:
        graph, fn = self.graph, self.fn
        parts = name.split(".")
        if parts[0] in ("self", "cls"):
            cls_qn = self._enclosing_class()
            if cls_qn is None:
                return None
            if len(parts) == 2:
                return _method_in_mro(graph, cls_qn, parts[1])
            if len(parts) >= 3:
                # self.attr.method(): one level of attr-type inference
                cls = graph.classes.get(cls_qn)
                attr_cls = cls.attr_types.get(parts[1]) if cls else None
                if attr_cls is not None:
                    return _method_in_mro(graph, attr_cls, parts[2])
            return None
        # nested function in the enclosing chain
        if len(parts) == 1:
            qn = self._nested_qualname(parts[0])
            if qn:
                return qn
            # sibling nested function (shared parent scope)
            if "<locals>" in fn.qualname:
                parent = fn.qualname.rsplit(".<locals>.", 1)[0]
                sibling = f"{parent}.<locals>.{parts[0]}"
                if sibling in graph.functions:
                    return sibling
        resolved = _resolve_symbol(graph, fn.module, name)
        if resolved:
            return resolved
        # ClassName(...) instantiation -> __init__
        cls_qn = _resolve_class(graph, fn.module, name)
        if cls_qn:
            init = _method_in_mro(graph, cls_qn, "__init__")
            return init
        return None

    def _is_external(self, name: str) -> bool:
        """True when the call clearly targets an import we know is NOT
        a project module (stdlib/third-party): not 'unresolved', just
        out of scope."""
        head = name.split(".")[0]
        imports = self.graph.imports.get(self.fn.module, {})
        target = imports.get(head)
        if target is None:
            # builtins (len, print, isinstance...) and local variables:
            # plain single names are out-of-scope, dotted ones through
            # unknown receivers are dynamic -> count those
            return "." not in name
        root = target.split(".")[0]
        return not any(m == root or m.startswith(root + ".")
                       for m in self.graph.imports)


def resolve_name(graph: CallGraph, fn: FunctionInfo, name: str) -> Optional[str]:
    """Public seam for sibling analyses (jaxsem.py): resolve a dotted
    call name *as seen from inside ``fn``* — same-frame nested
    functions, ``self``/``cls`` dispatch, import aliases, class
    constructors — to a project function qualname, or None. Exactly the
    resolution the edge builder uses, so a DL2xx rule and the call
    graph can never disagree about what a call targets."""
    return _CallResolver(graph, fn)._resolve_call_name(name)


def enclosing_class(graph: CallGraph, fn: FunctionInfo) -> Optional[str]:
    """The class ``self`` refers to inside ``fn`` (walks ``<locals>``
    closures up to the outermost method) — public twin of the edge
    builder's own lookup, shared with jaxsem.py."""
    return _CallResolver(graph, fn)._enclosing_class()


def build_callgraph(
    modules: List[Tuple[str, ast.Module]],  # (path, parsed tree)
) -> CallGraph:
    """Build the project call graph from parsed modules."""
    graph = CallGraph()
    indexed = []
    for path, tree in modules:
        mod = module_name_for(Path(path))
        indexed.append((mod, path, tree))
        _ModuleIndexer(graph, mod, str(path), tree).run()
    _infer_attr_types(graph)
    for fn in list(graph.functions.values()):
        _CallResolver(graph, fn).run()
    graph.freeze()
    return graph
