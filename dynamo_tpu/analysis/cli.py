"""`dynamo-tpu lint` — run dynalint from the command line.

Exit codes: 0 clean, 1 unsuppressed findings (merge-gating), 2 usage
error. ``--format json`` emits the machine-readable report on stdout so
CI can archive it; the exit code gates either way.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Any

from dynamo_tpu.analysis.config import load_config
from dynamo_tpu.analysis.findings import format_json, format_text, unsuppressed
from dynamo_tpu.analysis.registry import all_rules, get_rule
from dynamo_tpu.analysis.walker import iter_files, lint_paths


def add_lint_parser(sub: Any) -> None:
    """Attach the `lint` subparser (called from cli/main.build_parser)."""
    lint = sub.add_parser(
        "lint",
        help="static invariant checks for the async/TPU serving stack",
        description="AST-based repo linter (dynalint). Rules target the "
        "failure modes this codebase actually has: blocked event loops, "
        "dropped task handles, swallowed cancellation, host syncs in jit "
        "paths, awaits under thread locks, bare excepts.",
    )
    lint.add_argument("paths", nargs="*", default=None,
                      help="files/dirs to lint (default: [tool.dynalint] "
                           "include, i.e. dynamo_tpu/)")
    lint.add_argument("--format", dest="fmt", default="text",
                      choices=["text", "json"])
    lint.add_argument("--rules", default=None,
                      help="comma-separated rule names to run "
                           "(default: all minus config `disable`)")
    lint.add_argument("--list-rules", action="store_true",
                      help="print the rule catalog and exit")
    lint.add_argument("--show-suppressed", action="store_true",
                      help="text format: also print waived findings")
    lint.add_argument("--pyproject", default=None,
                      help="explicit pyproject.toml for [tool.dynalint]")


def cmd_lint(args: Any) -> int:
    if args.list_rules:
        for r in all_rules():
            print(f"{r.code}  {r.name:26s} {r.summary}")
        return 0
    # anchor config discovery at the linted tree, not the cwd: `dynamo-tpu
    # lint /repo/pkg` from anywhere must see /repo's [tool.dynalint]
    config = load_config(
        start=args.paths[0] if args.paths else ".", pyproject=args.pyproject
    )
    if args.rules:
        try:
            rules = [get_rule(n.strip()) for n in args.rules.split(",") if n.strip()]
        except KeyError as exc:
            print(f"dynalint: {exc.args[0]}", file=sys.stderr)
            return 2
    else:
        rules = None  # lint_paths applies config `disable`
    paths = args.paths or list(config.get("include", ["dynamo_tpu"]))
    # a gate that scans nothing must fail loudly, not pass green: a
    # typo'd path (or running outside the repo) would otherwise report
    # "0 findings" and exit 0 while checking zero files. Diagnostics go
    # to stderr so `--format json > report.json` stays machine-readable.
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"dynalint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    files = iter_files(paths, exclude=list(config.get("exclude", [])))
    if not files:
        print(f"dynalint: no python files under: {', '.join(map(str, paths))}",
              file=sys.stderr)
        return 2
    findings = lint_paths(paths, rules=rules, config=config, files=files)
    if args.fmt == "json":
        print(format_json(findings))
    else:
        print(format_text(findings, show_suppressed=args.show_suppressed))
    return 1 if unsuppressed(findings) else 0


def main(argv: list[str] | None = None) -> int:
    """Standalone entry: `python -m dynamo_tpu.analysis.cli [paths...]`."""
    parser = argparse.ArgumentParser(prog="dynalint")
    sub = parser.add_subparsers(dest="command", required=True)
    add_lint_parser(sub)
    if argv is None:
        argv = sys.argv[1:]
    return cmd_lint(parser.parse_args(["lint", *argv]))


if __name__ == "__main__":
    raise SystemExit(main())
