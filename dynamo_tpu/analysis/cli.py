"""`dynamo-tpu lint` — run dynalint from the command line.

Exit codes: 0 clean, 1 gating findings (merge-blocking), 2 usage
error. ``--format json`` emits the machine-readable report on stdout so
CI can archive it; ``--format github`` emits workflow-command
annotations that land inline on a PR diff; the exit code gates either
way. ``--changed`` scopes the *report* to files touched vs git HEAD
(the whole-program pass still sees the full project — a one-line edit
can create a transitive finding in the file it touched). ``--baseline``
grandfathers a findings backlog: listed findings warn, new ones fail;
``--update-baseline`` rewrites the file from the current state.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import Any, Optional

from dynamo_tpu.analysis.config import find_pyproject, load_config
from dynamo_tpu.analysis.findings import (
    apply_baseline,
    format_github,
    format_json,
    format_sarif,
    format_text,
    gating,
    stale_baseline_entries,
    write_baseline,
)
from dynamo_tpu.analysis.program import all_program_rules, get_program_rule
from dynamo_tpu.analysis.registry import all_rules, get_rule
from dynamo_tpu.analysis.walker import iter_files, lint_paths


def add_lint_parser(sub: Any) -> None:
    """Attach the `lint` subparser (called from cli/main.build_parser)."""
    lint = sub.add_parser(
        "lint",
        help="static invariant checks for the async/TPU serving stack",
        description="Whole-program repo linter (dynalint). Per-file AST "
        "rules (DL0xx) target blocked event loops, dropped task handles, "
        "swallowed cancellation, host syncs in jit paths, awaits under "
        "thread locks; whole-program rules (DL1xx) propagate async/"
        "step-loop/thread-affinity taints over the project call graph to "
        "catch the same bugs hidden one or more call levels deep.",
    )
    lint.add_argument("paths", nargs="*", default=None,
                      help="files/dirs to lint (default: [tool.dynalint] "
                           "include, i.e. dynamo_tpu/)")
    lint.add_argument("--format", dest="fmt", default="text",
                      choices=["text", "json", "github", "sarif"])
    lint.add_argument("--rules", default=None,
                      help="comma-separated rule names to run "
                           "(default: all minus config `disable`)")
    lint.add_argument("--list-rules", action="store_true",
                      help="print the rule catalog and exit")
    lint.add_argument("--show-suppressed", action="store_true",
                      help="text format: also print waived findings")
    lint.add_argument("--pyproject", default=None,
                      help="explicit pyproject.toml for [tool.dynalint]")
    lint.add_argument("--changed", action="store_true",
                      help="report only findings in files changed vs git "
                           "HEAD (incl. untracked); the whole-program "
                           "pass still analyzes the full project")
    lint.add_argument("--no-cache", action="store_true",
                      help="bypass the on-disk result cache "
                           "(.dynalint_cache/)")
    lint.add_argument("--stats", action="store_true",
                      help="print cache + call-graph + shard-inventory "
                           "statistics to stderr")
    lint.add_argument("--baseline", default=None,
                      help="baseline file: listed findings warn instead "
                           "of gating (default: config `baseline` key)")
    lint.add_argument("--update-baseline", action="store_true",
                      help="rewrite the baseline file from the current "
                           "live findings, then exit 0")


def _changed_files(repo_root: Path) -> Optional[set[Path]]:
    """Files changed vs HEAD plus untracked, absolute; None = git
    unavailable (the caller degrades to a full report). Paths are
    anchored at the git TOPLEVEL — `git diff --name-only` always
    reports relative to it, which is not necessarily the pyproject
    directory (monorepos)."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"], cwd=repo_root,
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if top.returncode != 0:
        return None
    toplevel = Path(top.stdout.strip())
    out: set[Path] = set()
    for args in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "-o", "--exclude-standard"],
    ):
        try:
            r = subprocess.run(
                args, cwd=toplevel, capture_output=True, text=True,
                timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        for line in r.stdout.splitlines():
            if line.strip():
                out.add((toplevel / line.strip()).resolve())
    return out


def _resolve_rules(spec: str):
    """Split a --rules list across both registries."""
    file_rules, prog_rules = [], []
    for name in (n.strip() for n in spec.split(",")):
        if not name:
            continue
        try:
            file_rules.append(get_rule(name))
            continue
        except KeyError:
            pass
        prog_rules.append(get_program_rule(name))  # raises with catalog
    return file_rules, prog_rules


def cmd_lint(args: Any) -> int:
    if args.list_rules:
        for r in all_rules():
            print(f"{r.code}  {r.name:34s} {r.summary}")
        for r in all_program_rules():
            print(f"{r.code}  {r.name:34s} {r.summary}")
        return 0
    # anchor config discovery at the linted tree, not the cwd: `dynamo-tpu
    # lint /repo/pkg` from anywhere must see /repo's [tool.dynalint]
    config = load_config(
        start=args.paths[0] if args.paths else ".", pyproject=args.pyproject
    )
    file_rules = prog_rules = None
    if args.rules:
        try:
            file_rules, prog_rules = _resolve_rules(args.rules)
        except KeyError as exc:
            print(f"dynalint: {exc.args[0]}", file=sys.stderr)
            return 2
    paths = args.paths or list(config.get("include", ["dynamo_tpu"]))
    # a gate that scans nothing must fail loudly, not pass green: a
    # typo'd path (or running outside the repo) would otherwise report
    # "0 findings" and exit 0 while checking zero files. Diagnostics go
    # to stderr so `--format json > report.json` stays machine-readable.
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"dynalint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    files = iter_files(paths, exclude=list(config.get("exclude", [])))
    if not files:
        print(f"dynalint: no python files under: {', '.join(map(str, paths))}",
              file=sys.stderr)
        return 2

    cache = None
    if not args.no_cache:
        from dynamo_tpu.analysis.cache import LintCache, default_cache_dir

        cache_dir = default_cache_dir(Path(str(paths[0])))
        if cache_dir is not None:
            cache = LintCache(cache_dir)
    stats: dict = {}
    findings = lint_paths(
        paths,
        rules=file_rules,
        config=config,
        files=files,
        program_rules=prog_rules,
        cache=cache,
        stats_out=stats,
    )
    if args.stats:
        if cache is not None:
            print(
                f"dynalint: cache {cache.hits} hit(s), "
                f"{cache.misses} miss(es)",
                file=sys.stderr,
            )
        graph_stats = stats.get("callgraph")
        if graph_stats == "cached":
            print("dynalint: program pass served from cache "
                  "(no graph rebuilt)", file=sys.stderr)
        elif isinstance(graph_stats, dict):
            print(
                "dynalint: call graph: "
                + ", ".join(f"{k}={v}" for k, v in graph_stats.items()),
                file=sys.stderr,
            )
        shard_stats = stats.get("shardsem")
        if isinstance(shard_stats, dict):
            print(
                "dynalint: shard inventory: "
                + ", ".join(f"{k}={v}" for k, v in shard_stats.items()),
                file=sys.stderr,
            )

    pyproject = (
        Path(args.pyproject)
        if args.pyproject
        else find_pyproject(Path(str(paths[0])))
    )
    root = pyproject.parent if pyproject else None

    baseline_arg = args.baseline or config.get("baseline") or None
    baseline_path = None
    if baseline_arg:
        baseline_path = Path(baseline_arg)
        if not baseline_path.is_absolute() and root is not None:
            baseline_path = root / baseline_path
    if args.update_baseline:
        # BEFORE the --changed filter: rewriting the baseline from a
        # scoped report would silently drop every other grandfathered
        # entry and fail the next full-repo run
        if baseline_path is None:
            print("dynalint: --update-baseline needs --baseline PATH or a "
                  "config `baseline` key", file=sys.stderr)
            return 2
        # name what the rewrite prunes: the grandfather list must only
        # ever shrink toward zero, and a silent rewrite hides progress
        stale = (
            stale_baseline_entries(findings, baseline_path, root)
            if baseline_path.exists()
            else []
        )
        n = write_baseline(findings, baseline_path, root)
        pruned = f", pruned {len(stale)} stale" if stale else ""
        print(f"dynalint: baseline written: {n} grandfathered finding(s)"
              f"{pruned} -> {baseline_path}", file=sys.stderr)
        return 0

    if args.changed:
        changed = _changed_files(root or Path.cwd())
        if changed is None:
            print("dynalint: --changed needs git; reporting everything",
                  file=sys.stderr)
        else:
            findings = [
                f for f in findings
                if Path(f.path).resolve() in changed
            ]

    if baseline_path is not None and baseline_path.exists():
        findings = apply_baseline(findings, baseline_path, root)
        if not args.changed:
            # a fingerprint matching nothing is a fixed violation whose
            # grandfather entry lingers; surface it so the backlog list
            # shrinks monotonically (--changed scopes the report, so
            # its narrowed view must not cry stale about the rest)
            stale = stale_baseline_entries(findings, baseline_path, root)
            for rule, spath, _ in stale[:10]:
                print(f"dynalint: stale baseline entry: [{rule}] {spath} "
                      "matches no current finding", file=sys.stderr)
            if stale:
                print(f"dynalint: {len(stale)} stale baseline entr"
                      f"{'y' if len(stale) == 1 else 'ies'} — prune with "
                      "--update-baseline", file=sys.stderr)

    if args.fmt == "json":
        print(format_json(findings))
    elif args.fmt == "github":
        print(format_github(findings))
    elif args.fmt == "sarif":
        print(format_sarif(findings))
    else:
        print(format_text(findings, show_suppressed=args.show_suppressed))
    return 1 if gating(findings) else 0


def main(argv: list[str] | None = None) -> int:
    """Standalone entry: `python -m dynamo_tpu.analysis.cli [paths...]`."""
    parser = argparse.ArgumentParser(prog="dynalint")
    sub = parser.add_subparsers(dest="command", required=True)
    add_lint_parser(sub)
    if argv is None:
        argv = sys.argv[1:]
    return cmd_lint(parser.parse_args(["lint", *argv]))


if __name__ == "__main__":
    raise SystemExit(main())
