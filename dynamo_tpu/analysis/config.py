"""[tool.dynalint] configuration from pyproject.toml.

Keys (all optional):
  include       — path globs linted when the CLI gets no paths
                  (default: ["dynamo_tpu"])
  exclude       — path prefixes/globs skipped during the walk
  disable       — rule names turned off globally
  hot-functions — extra function names treated as jit hot paths (DL004)
  step-loop-functions — function names treated as the engine step loop
                  by hidden-host-sync-in-step-loop (DL010) and as the
                  seeds of the transitive DL102 taint
  sse-writer-functions — function names treated as SSE chunk paths by
                  blocking-work-in-chunk-path (DL013) in addition to
                  any function whose name contains "stream_sse" or
                  "sse_write"
  affinity-entry-points — "pattern=domain" strings seeding the thread-
                  affinity taint (DL103) for entry points that carry no
                  @thread_affinity decorator; pattern is a bare function
                  name or an fnmatch over qualnames
                  ("pkg.mod:Cls.method")
  prewarm-functions — extra function names treated as prewarm roots by
                  prewarm-coverage (DL203) in addition to any function
                  whose name contains "prewarm"; jitted callables
                  reachable from the step loop must be referenced from
                  a prewarm root (or code it reaches)
  baseline      — path (relative to pyproject.toml) of the findings
                  baseline file; listed findings warn instead of gating
                  (see `dynamo-tpu lint --baseline/--update-baseline`)

Parsing uses stdlib ``tomllib`` when present (3.11+), else the vendored
``tomli`` this environment ships; with neither, config silently falls
back to defaults — the linter must never add a dependency.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Any, Optional

DEFAULTS: dict[str, Any] = {
    "include": ["dynamo_tpu"],
    "exclude": [],
    "disable": [],
    "hot-functions": [],
    "step-loop-functions": [],
    "sse-writer-functions": [],
    "affinity-entry-points": [],
    "prewarm-functions": [],
    "baseline": "",
}


def _load_toml(path: Path) -> Optional[dict]:
    try:
        import tomllib  # py311+
    except ImportError:
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ImportError:
            return None
    try:
        with open(path, "rb") as f:
            return tomllib.load(f)
    except (OSError, ValueError):
        return None


def find_pyproject(start: Path) -> Optional[Path]:
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    for candidate in [cur, *cur.parents]:
        p = candidate / "pyproject.toml"
        if p.is_file():
            return p
    return None


def load_config(start: Optional[str] = None,
                pyproject: Optional[str] = None) -> dict[str, Any]:
    """Merged config: DEFAULTS overlaid with [tool.dynalint]."""
    cfg = dict(DEFAULTS)
    path = Path(pyproject) if pyproject else find_pyproject(Path(start or "."))
    if path is None:
        return cfg
    data = _load_toml(path)
    if not data:
        return cfg
    table = data.get("tool", {}).get("dynalint", {})
    if isinstance(table, dict):
        # a typo'd key (hot_functions vs hot-functions) would otherwise
        # no-op silently while the author believes the guard is active —
        # the same failure mode bad-suppression findings exist for
        unknown = sorted(set(table) - set(DEFAULTS))
        if unknown:
            print(
                f"dynalint: unknown [tool.dynalint] key(s) in {path}: "
                f"{', '.join(unknown)} (known: {', '.join(sorted(DEFAULTS))})",
                file=sys.stderr,
            )
        cfg.update({k: v for k, v in table.items() if k in DEFAULTS})
    # anchor relative include paths at the pyproject's directory so
    # `dynamo-tpu lint` works from any cwd inside the repo
    root = path.parent
    cfg["include"] = [
        p if Path(p).is_absolute() else str(root / p)
        for p in cfg.get("include", [])
    ]
    return cfg
