"""Finding record + report formatting for dynalint.

A ``Finding`` is one rule violation at one source location. Suppressed
findings are kept (flagged) rather than dropped so reporters can show
what was waived and the self-clean gate can count both populations.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Optional


@dataclass(frozen=True)
class Finding:
    rule: str  # kebab-case rule name, e.g. "blocking-call-in-async"
    code: str  # stable short code, e.g. "DL001"
    path: str  # file the finding is in (as given to the walker)
    line: int  # 1-based source line
    col: int  # 0-based column
    message: str
    suppressed: bool = False
    baselined: bool = False  # grandfathered by a --baseline file: warn


def unsuppressed(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if not f.suppressed]


def gating(findings: list[Finding]) -> list[Finding]:
    """Findings that fail the build: live AND not grandfathered."""
    return [f for f in findings if not f.suppressed and not f.baselined]


def format_text(findings: list[Finding], *, show_suppressed: bool = False) -> str:
    """flake8-style one-line-per-finding report plus a summary line."""
    lines = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)):
        if f.suppressed and not show_suppressed:
            continue
        tag = " (suppressed)" if f.suppressed else (
            " (baseline)" if f.baselined else ""
        )
        lines.append(
            f"{f.path}:{f.line}:{f.col + 1}: {f.code} [{f.rule}] {f.message}{tag}"
        )
    live = len(gating(findings))
    baselined = sum(1 for f in findings if f.baselined and not f.suppressed)
    waived = len(findings) - live - baselined
    summary = f"dynalint: {live} finding(s), {waived} suppressed"
    if baselined:
        summary += f", {baselined} baselined"
    lines.append(summary)
    return "\n".join(lines)


def format_github(findings: list[Finding]) -> str:
    """GitHub Actions workflow commands: gating findings annotate as
    errors, baselined ones as warnings, suppressed ones are omitted —
    the annotations land inline on the PR diff with no extra action."""

    def esc(msg: str) -> str:
        # workflow-command data escaping (%, CR, LF)
        return (
            msg.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
        )

    lines = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)):
        if f.suppressed:
            continue
        level = "warning" if f.baselined else "error"
        lines.append(
            f"::{level} file={f.path},line={f.line},col={f.col + 1},"
            f"title={f.code} [{f.rule}]::{esc(f.message)}"
        )
    live = len(gating(findings))
    lines.append(f"dynalint: {live} finding(s)")
    return "\n".join(lines)


def format_json(findings: list[Finding]) -> str:
    """Machine-readable report: {findings: [...], summary: {...}}."""
    payload = {
        "findings": [
            asdict(f)
            for f in sorted(
                findings, key=lambda f: (f.path, f.line, f.col, f.code)
            )
        ],
        "summary": {
            "total": len(findings),
            "unsuppressed": len(unsuppressed(findings)),
            "suppressed": len(findings) - len(unsuppressed(findings)),
            "baselined": sum(
                1 for f in findings if f.baselined and not f.suppressed
            ),
            "gating": len(gating(findings)),
        },
    }
    return json.dumps(payload, indent=2)


def format_sarif(findings: list[Finding]) -> str:
    """SARIF 2.1.0 report — the interchange format GitHub code scanning
    ingests, so dynalint findings land in the repo's Security tab with
    the same rule metadata the other emitters carry.  Gating findings
    map to level "error", baselined ones to "warning"; suppressed
    findings are emitted with a SARIF ``suppressions`` entry (status
    "accepted") so the waiver stays visible rather than vanishing.
    Rule metadata comes from both registries lazily — findings.py stays
    import-light for every other consumer."""
    from dynamo_tpu.analysis.program import all_program_rules
    from dynamo_tpu.analysis.registry import all_rules

    catalog = {}
    for r in (*all_rules(), *all_program_rules()):
        catalog[r.name] = {
            "id": r.code,
            "name": r.name,
            "shortDescription": {"text": r.summary},
        }
    # findings can reference rules absent from the registries (old
    # cache entries, tests): synthesize a minimal descriptor for those
    for f in findings:
        catalog.setdefault(f.rule, {
            "id": f.code,
            "name": f.rule,
            "shortDescription": {"text": f.rule},
        })
    rules = sorted(catalog.values(), key=lambda r: (r["id"], r["name"]))
    index = {r["name"]: i for i, r in enumerate(rules)}

    results = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)):
        result = {
            "ruleId": f.code,
            "ruleIndex": index[f.rule],
            "level": "warning" if f.baselined else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/"),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": f.line,
                        "startColumn": f.col + 1,
                    },
                },
            }],
        }
        if f.suppressed:
            result["suppressions"] = [{
                "kind": "inSource",
                "status": "accepted",
            }]
        results.append(result)

    payload = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "dynalint",
                    "informationUri":
                        "https://github.com/dynamo-tpu/dynamo-tpu"
                        "/blob/main/docs/static_analysis.md",
                    "rules": rules,
                },
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
    return json.dumps(payload, indent=2)


# -- baseline files -------------------------------------------------------
# A baseline grandfathers existing findings so a newly-tightened rule can
# gate NEW violations immediately while the backlog burns down: listed
# findings warn, unlisted ones fail. Fingerprints are (rule, path,
# message) — deliberately line-free, so unrelated edits that shift a
# grandfathered finding up or down the file don't resurrect it.


def _fingerprint(f: Finding, root: Optional[Path]) -> tuple[str, str, str]:
    path = f.path
    if root is not None:
        try:
            path = str(Path(path).resolve().relative_to(root.resolve()))
        except ValueError:
            pass
    return (f.rule, path, f.message)


def write_baseline(
    findings: list[Finding], path: Path, root: Optional[Path] = None
) -> int:
    """Write the current live findings as the new baseline; returns the
    entry count."""
    entries = sorted(
        {_fingerprint(f, root) for f in unsuppressed(findings)}
    )
    payload = {
        "version": 1,
        "findings": [
            {"rule": r, "path": p, "message": m} for r, p, m in entries
        ],
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")
    return len(entries)


def stale_baseline_entries(
    findings: list[Finding], path: Path, root: Optional[Path] = None
) -> list[tuple[str, str, str]]:
    """Baseline entries that match NO current finding — the violation
    was fixed (or the rule/message changed) but the grandfather entry
    lingers.  The CLI warns about these so the backlog list shrinks
    monotonically, and ``--update-baseline`` prunes them (it rewrites
    from live findings, so a stale fingerprint cannot survive)."""
    try:
        data = json.loads(path.read_text())
        entries = {
            (e["rule"], e["path"], e["message"])
            for e in data.get("findings", [])
        }
    except (OSError, ValueError, KeyError, TypeError):
        return []
    live = {_fingerprint(f, root) for f in unsuppressed(findings)}
    return sorted(entries - live)


def apply_baseline(
    findings: list[Finding], path: Path, root: Optional[Path] = None
) -> list[Finding]:
    """Demote findings listed in the baseline file to warnings."""
    import dataclasses

    try:
        data = json.loads(path.read_text())
        known = {
            (e["rule"], e["path"], e["message"])
            for e in data.get("findings", [])
        }
    except (OSError, ValueError, KeyError, TypeError):
        return findings  # unreadable baseline = no grandfathering
    out = []
    for f in findings:
        if not f.suppressed and _fingerprint(f, root) in known:
            f = dataclasses.replace(f, baselined=True)
        out.append(f)
    return out
