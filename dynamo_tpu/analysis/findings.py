"""Finding record + report formatting for dynalint.

A ``Finding`` is one rule violation at one source location. Suppressed
findings are kept (flagged) rather than dropped so reporters can show
what was waived and the self-clean gate can count both populations.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass


@dataclass(frozen=True)
class Finding:
    rule: str  # kebab-case rule name, e.g. "blocking-call-in-async"
    code: str  # stable short code, e.g. "DL001"
    path: str  # file the finding is in (as given to the walker)
    line: int  # 1-based source line
    col: int  # 0-based column
    message: str
    suppressed: bool = False


def unsuppressed(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if not f.suppressed]


def format_text(findings: list[Finding], *, show_suppressed: bool = False) -> str:
    """flake8-style one-line-per-finding report plus a summary line."""
    lines = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)):
        if f.suppressed and not show_suppressed:
            continue
        tag = " (suppressed)" if f.suppressed else ""
        lines.append(
            f"{f.path}:{f.line}:{f.col + 1}: {f.code} [{f.rule}] {f.message}{tag}"
        )
    live = len(unsuppressed(findings))
    waived = len(findings) - live
    lines.append(f"dynalint: {live} finding(s), {waived} suppressed")
    return "\n".join(lines)


def format_json(findings: list[Finding]) -> str:
    """Machine-readable report: {findings: [...], summary: {...}}."""
    payload = {
        "findings": [
            asdict(f)
            for f in sorted(
                findings, key=lambda f: (f.path, f.line, f.col, f.code)
            )
        ],
        "summary": {
            "total": len(findings),
            "unsuppressed": len(unsuppressed(findings)),
            "suppressed": len(findings) - len(unsuppressed(findings)),
        },
    }
    return json.dumps(payload, indent=2)
