"""JAX-semantics layer for dynalint: the jit-site inventory the DL2xx
rules share.

The reference Dynamo's hot-path contracts are enforced by Rust's type
system; our TPU engine's equivalents are *conventions around jit*:

- a buffer passed in a ``donate_argnums`` position **no longer exists**
  after the dispatch — the caller must rebind it from the outputs (the
  engine's ``self.k_cache, self.v_cache = step_fn(...)`` swap idiom);
- a value landing in a ``static_argnums``/``static_argnames`` slot is a
  **compile-time constant**: feed it a per-step local and every step
  silently recompiles; feed it a device array and the call needs a
  host sync just to hash it;
- every jitted callable reachable from the step loop must be compiled
  by ``_prewarm`` — a cold variant is a multi-second mid-serve stall
  (docs/performance.md).

None of these are visible to Python.  This module builds, once per
program pass, the inventory those contracts are checked against:

- **sites**: every ``jax.jit(...)`` / ``functools.partial(jax.jit,
  ...)`` expression in the project — as a decorator, assigned to a
  ``self.<attr>`` (including the engine's ``jax.jit(f) if cond else
  None`` and alias ``self._step_fn_mm = self._step_fn`` forms), or
  bound to a local — resolved to the wrapped function where possible,
  with parsed ``donate_argnums`` / ``static_argnums`` /
  ``static_argnames``;
- **call resolution**: given an ``ast.Call`` inside a function, which
  jit site (if any) it invokes — through the same name-resolution
  machinery the call graph uses (``callgraph.resolve_name``), plus the
  attr/local binding maps the call graph has no notion of;
- **one-level summaries**: which *parameters* of an ordinary function
  flow (as bare names) into a donated or static slot of a jit site in
  its body — so DL201/DL202 see through one wrapper frame
  (``scatter_blocks(k, v, ...)`` donates its callers' buffers just as
  surely as ``_scatter`` does).

The inventory is memoized on the :class:`LintProgram` instance, so the
three DL2xx rules share one build.  Cache correctness is free: this
file lives in the analysis package, whose source bytes are folded into
the rule-set signature (``cache._package_hash``) — editing jaxsem.py
invalidates every cached DL2xx finding.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from dynamo_tpu.analysis.astutil import dotted_name, walk_in_scope
from dynamo_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    enclosing_class,
    resolve_name,
)


@dataclass
class JitSite:
    """One ``jax.jit`` wrapping in the project."""

    key: str  # stable identity ("qualname" / "cls::attr" / "fn::local")
    path: str
    lineno: int
    kind: str  # "decorator" | "attr" | "local"
    wrapped: Optional[str]  # wrapped fn qualname (None: lambda/opaque)
    donate: Tuple[int, ...] = ()
    static: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()

    @property
    def label(self) -> str:
        """Human name for messages: the bound attr/local for assigned
        sites, the wrapped function's short name for decorators."""
        if self.kind == "attr":
            return "self." + self.key.rsplit("::", 1)[-1]
        if self.kind == "local":
            return self.key.rsplit("::", 1)[-1]
        return self.key.rsplit(":", 1)[-1]


@dataclass
class ParamFlow:
    """A wrapper parameter that flows into a jit slot one level down."""

    site: JitSite
    param: str  # the wrapper's parameter name (for kwarg call sites)


@dataclass
class JitInventory:
    sites: List[JitSite] = field(default_factory=list)
    by_qualname: Dict[str, JitSite] = field(default_factory=dict)
    by_attr: Dict[Tuple[str, str], JitSite] = field(default_factory=dict)
    by_local: Dict[Tuple[str, str], JitSite] = field(default_factory=dict)
    # wrapper fn qualname -> {param positional index -> flow} (index is
    # the CALLER-side positional index: ``self`` already stripped)
    donating_params: Dict[str, Dict[int, ParamFlow]] = field(
        default_factory=dict
    )
    static_params: Dict[str, Dict[int, ParamFlow]] = field(
        default_factory=dict
    )


# -- jit-expression recognition ------------------------------------------


def _resolves_to(imports: Dict[str, str], name: str, full: str) -> bool:
    """Does ``name``, as written in a module with ``imports``, denote
    the fully-qualified ``full`` (e.g. "jax.jit")?"""
    if name == full:
        return True
    head, _, rest = name.partition(".")
    target = imports.get(head)
    if target is None:
        return False
    return (target + ("." + rest if rest else "")) == full


def _argnums(node: Optional[ast.AST]) -> Tuple[int, ...]:
    """donate_argnums/static_argnums literal -> tuple of ints (an int,
    a tuple/list of ints; anything dynamic degrades to empty — a miss,
    never a wrong index)."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
        return tuple(out)
    return ()


def _argnames(node: Optional[ast.AST]) -> Tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            el.value
            for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, str)
        )
    return ()


@dataclass
class _JitExpr:
    wrapped: Optional[ast.AST]  # the wrapped-callable expression
    donate: Tuple[int, ...]
    static: Tuple[int, ...]
    static_names: Tuple[str, ...]
    lineno: int


def parse_jit_expr(node: ast.AST, imports: Dict[str, str]) -> Optional[_JitExpr]:
    """Recognize ``jax.jit``, ``jax.jit(f, ...)`` and
    ``functools.partial(jax.jit, ...)`` expressions (any import
    spelling); None for everything else."""
    if not isinstance(node, ast.Call):
        # bare `@jax.jit` decorator
        name = dotted_name(node)
        if name and _resolves_to(imports, name, "jax.jit"):
            return _JitExpr(None, (), (), (), getattr(node, "lineno", 1))
        return None
    name = dotted_name(node.func)
    if name is None:
        return None
    kw = {k.arg: k.value for k in node.keywords if k.arg}
    if _resolves_to(imports, name, "jax.jit"):
        wrapped = node.args[0] if node.args else None
        return _JitExpr(
            wrapped,
            _argnums(kw.get("donate_argnums")),
            _argnums(kw.get("static_argnums")),
            _argnames(kw.get("static_argnames")),
            node.lineno,
        )
    if _resolves_to(imports, name, "functools.partial") and node.args:
        inner = dotted_name(node.args[0])
        if inner and _resolves_to(imports, inner, "jax.jit"):
            # partial(jax.jit, ...)(f): wrapped supplied by the
            # decorator context
            wrapped = node.args[1] if len(node.args) > 1 else None
            return _JitExpr(
                wrapped,
                _argnums(kw.get("donate_argnums")),
                _argnums(kw.get("static_argnums")),
                _argnames(kw.get("static_argnames")),
                node.lineno,
            )
    return None


def _jit_value_candidates(value: ast.AST) -> Iterator[ast.AST]:
    """Expressions a jit binding may hide in on an assignment RHS: the
    value itself, either arm of ``jit(f) if cond else None``, the
    operands of ``x or jit(f)``."""
    yield value
    if isinstance(value, ast.IfExp):
        yield from _jit_value_candidates(value.body)
        yield from _jit_value_candidates(value.orelse)
    elif isinstance(value, ast.BoolOp):
        for v in value.values:
            yield from _jit_value_candidates(v)


# -- inventory build ------------------------------------------------------


def _positional_params(fn: FunctionInfo) -> List[str]:
    """Caller-visible positional parameter names (``self``/``cls``
    stripped for methods — call-site index 0 is the first real arg)."""
    a = fn.node.args
    names = [p.arg for p in (*a.posonlyargs, *a.args)]
    if fn.cls is not None and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _resolve_wrapped(
    graph: CallGraph, fn: FunctionInfo, expr: Optional[ast.AST]
) -> Optional[str]:
    if expr is None or isinstance(expr, ast.Lambda):
        return None
    name = dotted_name(expr)
    if name is None:
        return None
    return resolve_name(graph, fn, name)


def build_inventory(graph: CallGraph) -> JitInventory:
    inv = JitInventory()

    def add(site: JitSite) -> JitSite:
        inv.sites.append(site)
        return site

    # pass 1a: decorated functions
    for qn, fn in graph.functions.items():
        imports = graph.imports.get(fn.module, {})
        for deco in getattr(fn.node, "decorator_list", []):
            je = parse_jit_expr(deco, imports)
            if je is None:
                continue
            inv.by_qualname[qn] = add(
                JitSite(
                    key=qn,
                    path=fn.path,
                    lineno=fn.lineno,
                    kind="decorator",
                    wrapped=qn,
                    donate=je.donate,
                    static=je.static,
                    static_names=je.static_names,
                )
            )
            break

    # pass 1b: jit expressions assigned to attrs / locals
    aliases: List[Tuple[str, str, str]] = []  # (cls_qn, new_attr, src_attr)
    for qn, fn in graph.functions.items():
        imports = graph.imports.get(fn.module, {})
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            je = None
            for cand in _jit_value_candidates(node.value):
                je = parse_jit_expr(cand, imports)
                if je is not None:
                    break
            cls_qn = enclosing_class(graph, fn)
            if je is None:
                # alias form: self.Y = self.X where X is a jit attr
                src = dotted_name(node.value)
                if cls_qn and src and src.startswith(("self.", "cls.")):
                    for t in node.targets:
                        tn = dotted_name(t)
                        if tn and tn.startswith(("self.", "cls.")):
                            aliases.append(
                                (cls_qn, tn.split(".", 1)[1],
                                 src.split(".", 1)[1])
                            )
                continue
            wrapped = _resolve_wrapped(graph, fn, je.wrapped)
            for t in node.targets:
                tn = dotted_name(t)
                if tn is None:
                    continue
                if tn.startswith(("self.", "cls.")) and cls_qn:
                    attr = tn.split(".", 1)[1]
                    if "." in attr:
                        continue
                    inv.by_attr[(cls_qn, attr)] = add(
                        JitSite(
                            key=f"{cls_qn}::{attr}",
                            path=fn.path,
                            lineno=node.lineno,
                            kind="attr",
                            wrapped=wrapped,
                            donate=je.donate,
                            static=je.static,
                            static_names=je.static_names,
                        )
                    )
                elif "." not in tn:
                    inv.by_local[(qn, tn)] = add(
                        JitSite(
                            key=f"{qn}::{tn}",
                            path=fn.path,
                            lineno=node.lineno,
                            kind="local",
                            wrapped=wrapped,
                            donate=je.donate,
                            static=je.static,
                            static_names=je.static_names,
                        )
                    )
    # pass 1c: attr aliases share the source site — coverage and
    # donation semantics follow the CALLABLE, not the binding name
    for cls_qn, new_attr, src_attr in aliases:
        src = inv.by_attr.get((cls_qn, src_attr))
        if src is not None:
            inv.by_attr.setdefault((cls_qn, new_attr), src)

    # pass 2: one-level wrapper summaries (param -> donated/static slot)
    for qn, fn in graph.functions.items():
        params = _positional_params(fn)
        if not params:
            continue
        index_of = {p: i for i, p in enumerate(params)}
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            site = resolve_call_site(inv, graph, fn, node)
            if site is None:
                continue
            for slot_kind, slots in (("donate", site.donate),
                                     ("static", site.static)):
                out = (inv.donating_params if slot_kind == "donate"
                       else inv.static_params)
                for i in slots:
                    if i >= len(node.args):
                        continue
                    arg = node.args[i]
                    if isinstance(arg, ast.Name) and arg.id in index_of:
                        out.setdefault(qn, {})[index_of[arg.id]] = ParamFlow(
                            site=site, param=arg.id
                        )
            for kwarg in node.keywords:
                if kwarg.arg in site.static_names and isinstance(
                    kwarg.value, ast.Name
                ) and kwarg.value.id in index_of:
                    inv.static_params.setdefault(qn, {})[
                        index_of[kwarg.value.id]
                    ] = ParamFlow(site=site, param=kwarg.value.id)
    return inv


def inventory_of(program) -> JitInventory:
    """The program's jit inventory, built once and memoized on the
    LintProgram instance (the three DL2xx rules share it)."""
    inv = getattr(program, "_jaxsem_inventory", None)
    if inv is None:
        inv = build_inventory(program.graph)
        program._jaxsem_inventory = inv
    return inv


# -- call-site resolution -------------------------------------------------


def _attr_site(
    inv: JitInventory, graph: CallGraph, cls_qn: Optional[str], attr: str
) -> Optional[JitSite]:
    """(class, attr) lookup through project-local bases."""
    seen = set()
    while cls_qn and cls_qn not in seen:
        seen.add(cls_qn)
        site = inv.by_attr.get((cls_qn, attr))
        if site is not None:
            return site
        cls = graph.classes.get(cls_qn)
        if cls is None or not cls.bases:
            return None
        from dynamo_tpu.analysis.callgraph import _resolve_class

        cls_qn = _resolve_class(graph, cls.module, cls.bases[0])
    return None


def resolve_call_site(
    inv: JitInventory, graph: CallGraph, fn: FunctionInfo, call: ast.Call
) -> Optional[JitSite]:
    """The jit site an ``ast.Call`` inside ``fn`` invokes, or None."""
    name = dotted_name(call.func)
    if name is None:
        return None
    parts = name.split(".")
    if parts[0] in ("self", "cls") and len(parts) == 2:
        return _attr_site(inv, graph, enclosing_class(graph, fn), parts[1])
    if len(parts) == 1:
        # local jit binding — in this frame or an enclosing closure's
        scope = fn.qualname
        while True:
            site = inv.by_local.get((scope, parts[0]))
            if site is not None:
                return site
            if ".<locals>." not in scope:
                break
            scope = scope.rsplit(".<locals>.", 1)[0]
    resolved = resolve_name(graph, fn, name)
    if resolved is not None:
        return inv.by_qualname.get(resolved)
    return None


def donated_flows(
    inv: JitInventory, graph: CallGraph, fn: FunctionInfo, call: ast.Call
) -> Optional[Tuple[str, Dict[int, JitSite]]]:
    """(label, {positional index -> site}) for a call that donates —
    directly a jit site, or through a one-level wrapper summary."""
    site = resolve_call_site(inv, graph, fn, call)
    if site is not None and site.donate:
        return site.label, {i: site for i in site.donate}
    name = dotted_name(call.func)
    if name is None:
        return None
    resolved = resolve_name(graph, fn, name)
    if resolved is None:
        return None
    flows = inv.donating_params.get(resolved)
    if not flows:
        return None
    short = resolved.rsplit(":", 1)[-1]
    return (
        short,
        {i: pf.site for i, pf in flows.items()},
    )


# -- call-argument helpers ------------------------------------------------


def effective_positional(
    call: ast.Call, local_tuples: Dict[str, ast.Tuple]
) -> List[Optional[ast.AST]]:
    """Positional argument expressions by index, expanding a leading
    ``*name`` whose ``name`` is bound to a tuple literal in the same
    frame (the engine's ``base_args = (params, k, v, ...)`` /
    ``self._step_fn(*base_args)`` idiom).  An unexpandable ``*arg``
    yields None placeholders — a miss, never a wrong index."""
    out: List[Optional[ast.AST]] = []
    for arg in call.args:
        if isinstance(arg, ast.Starred):
            name = dotted_name(arg.value)
            tup = local_tuples.get(name) if name else None
            if tup is not None:
                out.extend(tup.elts)
            else:
                return out  # unknown star: later indexes unknowable
        else:
            out.append(arg)
    return out


def value_key(expr: ast.AST) -> Optional[str]:
    """Dataflow key for a donate-position argument: a bare name
    ("k_cache"), a dotted attribute ("self.k_cache"), or the base of a
    subscript (donating ``k[0]`` invalidates an element of ``k``)."""
    if isinstance(expr, ast.Subscript):
        return value_key(expr.value)
    return dotted_name(expr)
