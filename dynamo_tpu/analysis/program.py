"""Whole-program lint pass: LintProgram + the program-rule registry.

Per-file rules (``registry.Rule``) see one ``LintModule``; program
rules see a :class:`LintProgram` — every parsed module plus the call
graph and taints — and can report findings anywhere in the project.
They register with :func:`program_rule` and are run by the walker's
``lint_paths`` after the per-file pass, through the same suppression
and config-``disable`` machinery, so the CLI / pytest gate / API all
agree.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Tuple

from dynamo_tpu.analysis.callgraph import CallGraph, build_callgraph
from dynamo_tpu.analysis.registry import LintModule
from dynamo_tpu.analysis.taint import Taints, compute_taints

# (path, anchor node, message)
ProgramCheckResult = Iterable[Tuple[str, ast.AST, str]]


@dataclass
class LintProgram:
    """Everything a whole-program rule needs, built once per run."""

    modules: Dict[str, LintModule]  # path -> parsed module
    graph: CallGraph
    taints: Taints
    config: Dict[str, Any] = field(default_factory=dict)

    def function_module(self, qualname: str) -> LintModule | None:
        fn = self.graph.functions.get(qualname)
        return self.modules.get(fn.path) if fn else None


@dataclass(frozen=True)
class ProgramRule:
    name: str
    code: str
    summary: str
    check: Callable[[LintProgram], ProgramCheckResult]


_PROGRAM_REGISTRY: Dict[str, ProgramRule] = {}


def program_rule(name: str, code: str, summary: str):
    """Register ``check(program)`` as a whole-program rule."""

    def deco(check: Callable[[LintProgram], ProgramCheckResult]):
        if name in _PROGRAM_REGISTRY:
            raise ValueError(f"duplicate program rule {name!r}")
        _PROGRAM_REGISTRY[name] = ProgramRule(
            name=name, code=code, summary=summary, check=check
        )
        return check

    return deco


def all_program_rules() -> List[ProgramRule]:
    import dynamo_tpu.analysis.rules  # noqa: F401  (registration)

    return sorted(_PROGRAM_REGISTRY.values(), key=lambda r: r.code)


def get_program_rule(name: str) -> ProgramRule:
    import dynamo_tpu.analysis.rules  # noqa: F401

    try:
        return _PROGRAM_REGISTRY[name]
    except KeyError:
        known = ", ".join(sorted(_PROGRAM_REGISTRY))
        raise KeyError(
            f"unknown program rule {name!r} (known: {known})"
        ) from None


def build_program(
    modules: Dict[str, LintModule], config: Dict[str, Any]
) -> LintProgram:
    graph = build_callgraph(
        [(path, m.tree) for path, m in modules.items()]
    )
    taints = compute_taints(graph, config)
    return LintProgram(
        modules=modules, graph=graph, taints=taints, config=config
    )
