"""Rule registry: rules self-register at import via the @rule decorator.

A rule is a function ``check(module: LintModule) -> Iterable[(node, msg)]``
plus metadata (kebab-case name, stable DLxxx code, summary). The walker
runs every enabled rule over every file and stamps the rule's metadata
onto each (node, message) pair to build ``Finding``s; rules never import
each other.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Tuple

CheckResult = Iterable[Tuple[ast.AST, str]]


@dataclass
class LintModule:
    """One parsed source file handed to each rule."""

    path: str
    source: str
    tree: ast.Module
    config: dict[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class Rule:
    name: str
    code: str
    summary: str
    check: Callable[[LintModule], CheckResult]


_REGISTRY: dict[str, Rule] = {}


def rule(name: str, code: str, summary: str):
    """Register ``check(module)`` as a rule. Import-time side effect."""

    def deco(check: Callable[[LintModule], CheckResult]):
        if name in _REGISTRY:
            raise ValueError(f"duplicate rule {name!r}")
        _REGISTRY[name] = Rule(name=name, code=code, summary=summary, check=check)
        return check

    return deco


def all_rules() -> list[Rule]:
    """Every registered rule, ordered by code (imports rule modules)."""
    # importing the rules package triggers registration; deferred so the
    # registry module itself stays import-cycle-free
    import dynamo_tpu.analysis.rules  # noqa: F401

    return sorted(_REGISTRY.values(), key=lambda r: r.code)


def get_rule(name: str) -> Rule:
    import dynamo_tpu.analysis.rules  # noqa: F401

    try:
        return _REGISTRY[name]
    except KeyError:
        known = ", ".join(sorted(_REGISTRY))
        raise KeyError(f"unknown rule {name!r} (known: {known})") from None
