"""dynalint rule modules — importing this package registers every rule.

Adding a per-file rule: create a module here, decorate a
``check(module)`` function with ``@rule(name, code, summary)`` from
``dynamo_tpu.analysis.registry``, and import the module below. Pick the
next free DLxxx code; never reuse a retired one (suppression comments
reference rule names, reports reference codes).

Whole-program rules (DL1xx) decorate ``check(program)`` with
``@program_rule(...)`` from ``dynamo_tpu.analysis.program`` instead —
they see the call graph + taints rather than a single file.
"""

from dynamo_tpu.analysis.rules import (  # noqa: F401
    await_locked,
    bare_except,
    blocking_async,
    chunk_path,
    collective_axis,
    cross_thread,
    donation_mesh,
    dropped_task,
    dynamic_static,
    hidden_sync,
    host_sync_jit,
    prewarm_coverage,
    retry_loop,
    shard_sync,
    spec_arity,
    swallowed_cancel,
    transitive_blocking,
    transitive_sync,
    unbounded_buffer,
    unclosed_span,
    use_after_donate,
    wall_clock,
)
