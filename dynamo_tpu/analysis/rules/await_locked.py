"""DL005 await-while-locked: a suspension point (``await``, ``async
for``, ``async with``, async comprehension) inside a ``with`` block
whose context manager looks like a *threading* lock.

Suspending at an await point while holding a thread lock is a deadlock
factory: the coroutine parks, the loop runs other tasks, and any thread
(or task via an executor) that touches the same lock wedges — including
the one needed to let the awaiting coroutine resume. Use
``asyncio.Lock`` with ``async with``, or do the awaited work outside the
critical section.

Heuristic: the context expression is ``threading.Lock()/RLock()`` (or a
call to a name ending in Lock), or a name/attribute whose last segment
is "lock"/"rlock"/"mutex" (optionally prefixed, e.g. ``write_lock``) —
a *word-boundary* match, so ``free_blocks`` and other "…block…" names in
this KV-block-manager codebase are not mistaken for locks. ``async
with`` is never flagged."""

from __future__ import annotations

import ast
import re

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import (
    FunctionScopeVisitor,
    dotted_name,
    walk_in_scope,
)

LOCK_CALLS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_LOCK_NAME = re.compile(r"(?:^|.*_)r?(?:lock|mutex)$")


def _looks_like_thread_lock(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        return (dotted_name(expr.func) or "") in LOCK_CALLS
    name = dotted_name(expr)
    if name is None:
        return False
    return _LOCK_NAME.match(name.rsplit(".", 1)[-1].lower()) is not None


@rule(
    "await-while-locked",
    "DL005",
    "await suspends while holding a threading lock (deadlock risk)",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []
    flagged: set[ast.AST] = set()  # one finding per await, however many locks

    class V(FunctionScopeVisitor):
        def visit_With(self, node: ast.With) -> None:
            if self.in_async and any(
                _looks_like_thread_lock(item.context_expr)
                for item in node.items
            ):
                for sub in walk_in_scope(node):
                    # every suspension point counts, not just `await`:
                    # async for/with and async comprehensions suspend too
                    if isinstance(
                        sub, (ast.Await, ast.AsyncFor, ast.AsyncWith)
                    ):
                        suspends = sub
                    elif isinstance(sub, ast.comprehension) and sub.is_async:
                        suspends = sub.iter
                    else:
                        continue
                    if suspends in flagged:
                        continue
                    flagged.add(suspends)
                    findings.append(
                        (
                            suspends,
                            "suspension point (await / async for / "
                            "async with) while holding a threading "
                            "lock: the coroutine parks mid-critical-"
                            "section and anything contending the lock "
                            "wedges; use asyncio.Lock (`async with`) "
                            "or move the async work out",
                        )
                    )
            self.generic_visit(node)

    V().visit(module.tree)
    return findings
