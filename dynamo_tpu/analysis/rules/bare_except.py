"""DL006 bare-except: ``except:`` with no exception type.

A bare except catches everything including ``SystemExit``,
``KeyboardInterrupt``, and ``asyncio.CancelledError`` — shutdown and
cancellation silently stop working. Catch the narrowest type that the
handler actually recovers from; ``except Exception`` is the widest
acceptable net."""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule


@rule(
    "bare-except",
    "DL006",
    "bare `except:` catches SystemExit/KeyboardInterrupt/CancelledError",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(
                (
                    node,
                    "bare `except:` also catches SystemExit/"
                    "KeyboardInterrupt/CancelledError; catch a specific "
                    "type (at widest, `except Exception`)",
                )
            )
    return findings
