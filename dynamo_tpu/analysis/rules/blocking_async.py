"""DL001 blocking-call-in-async: synchronous sleeps / process / network
calls inside ``async def`` bodies stall the whole event loop — every
in-flight request stream on that loop freezes for the duration.

Remediations: ``await asyncio.sleep``, ``asyncio.create_subprocess_*``,
``loop.run_in_executor`` / ``asyncio.to_thread`` for everything else.
Calls inside nested *sync* ``def``s are not flagged (those run wherever
the helper is invoked — often a worker thread)."""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import (
    BLOCKING_CALLS,
    FunctionScopeVisitor,
    dotted_name,
)

# the shared table lives in common.py (DL101 reuses it for the
# transitive pass); this module keeps the name for its callers


@rule(
    "blocking-call-in-async",
    "DL001",
    "blocking sleep/process/network call inside an async def body",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []

    class V(FunctionScopeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            if self.in_async:
                name = dotted_name(node.func)
                hint = BLOCKING_CALLS.get(name or "")
                if hint is not None:
                    findings.append(
                        (
                            node,
                            f"`{name}(...)` blocks the event loop; use "
                            f"{hint} or offload to an executor",
                        )
                    )
            self.generic_visit(node)

    V().visit(module.tree)
    return findings
