"""DL001 blocking-call-in-async: synchronous sleeps / process / network
calls inside ``async def`` bodies stall the whole event loop — every
in-flight request stream on that loop freezes for the duration.

Remediations: ``await asyncio.sleep``, ``asyncio.create_subprocess_*``,
``loop.run_in_executor`` / ``asyncio.to_thread`` for everything else.
Calls inside nested *sync* ``def``s are not flagged (those run wherever
the helper is invoked — often a worker thread)."""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import FunctionScopeVisitor, dotted_name

# dotted call name -> suggested replacement
BLOCKING_CALLS = {
    "time.sleep": "await asyncio.sleep(...)",
    "subprocess.run": "asyncio.create_subprocess_exec(...)",
    "subprocess.call": "asyncio.create_subprocess_exec(...)",
    "subprocess.check_call": "asyncio.create_subprocess_exec(...)",
    "subprocess.check_output": "asyncio.create_subprocess_exec(...)",
    "subprocess.getoutput": "asyncio.create_subprocess_shell(...)",
    "os.system": "asyncio.create_subprocess_shell(...)",
    "socket.create_connection": "asyncio.open_connection(...)",
    "socket.getaddrinfo": "loop.getaddrinfo(...)",
    "socket.gethostbyname": "loop.getaddrinfo(...)",
    "urllib.request.urlopen": "loop.run_in_executor(...)",
    "requests.get": "loop.run_in_executor(...)",
    "requests.post": "loop.run_in_executor(...)",
    "requests.put": "loop.run_in_executor(...)",
    "requests.delete": "loop.run_in_executor(...)",
    "requests.head": "loop.run_in_executor(...)",
    "requests.request": "loop.run_in_executor(...)",
}


@rule(
    "blocking-call-in-async",
    "DL001",
    "blocking sleep/process/network call inside an async def body",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []

    class V(FunctionScopeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            if self.in_async:
                name = dotted_name(node.func)
                hint = BLOCKING_CALLS.get(name or "")
                if hint is not None:
                    findings.append(
                        (
                            node,
                            f"`{name}(...)` blocks the event loop; use "
                            f"{hint} or offload to an executor",
                        )
                    )
            self.generic_visit(node)

    V().visit(module.tree)
    return findings
