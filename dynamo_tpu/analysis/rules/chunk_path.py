"""DL013 blocking-work-in-chunk-path: heavyweight per-chunk work inside
an SSE writer loop.

The frontend's chunk path (http/service.py ``_stream_sse``) runs once
per delta for EVERY open stream on ONE event loop — at the fan-out
ceiling (``bench.py --fanout``) a microsecond of per-chunk work is
multiplied by thousands of streams times hundreds of chunks, and a
MILLISECOND of synchronous work is a loop stall every stream observes
(telemetry/hostplane.py measures exactly this). Three families of work
do not belong inside the chunk loop:

- ``json.dumps``/``json.dump`` of whole aggregates — serializing a
  growing object per delta is O(stream²) host work; serialize the
  DELTA (protocols/sse.py ``encode_sse``) and keep aggregates out of
  the loop;
- tokenizer decode of accumulated history (``*.tokenizer.decode`` /
  ``.detokenize`` / ``.batch_decode``) — the preprocessor already
  detokenized the delta once; re-decoding the full history per chunk is
  the classic quadratic-TTFT bug;
- synchronous file/socket ops (``open``, ``os.read``/``os.write``,
  ``socket.sendall``/``recv``, ``time.sleep``) — any of these parks the
  WHOLE loop, not just this stream (DL002 catches generic blocking
  calls in async defs; DL013 scopes tighter and fires even in the sync
  helpers the writer loop calls).

Scope is name-structural like DL010: a function is a chunk path when
its name contains ``stream_sse`` or ``sse_write``, or appears in the
``sse-writer-functions`` config list ([tool.dynalint] — seeded with the
frontend's writer entry points). Only code inside a loop body
(``for``/``async for``/``while``, nested defs included) is flagged:
one-shot work before the stream starts is priming, not per-chunk cost.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import dotted_name

# whole-aggregate serializers (the delta path uses encode_sse once per
# chunk — that call lives OUTSIDE these functions and stays legal)
_JSON_CALLS = {"json.dumps", "json.dump"}

# blocking file/socket primitives by dotted name or bare call
_SYNC_CALLS = {
    "open", "os.open", "os.read", "os.write", "os.fsync", "time.sleep",
}
# blocking socket methods by attribute (receiver-agnostic: a socket in
# an SSE writer loop is wrong whatever it is called)
_SYNC_ATTRS = {"sendall", "recv", "recv_into"}

_DECODE_ATTRS = {"decode", "detokenize", "batch_decode"}


def _in_scope(name: str, extra: set[str]) -> bool:
    return "stream_sse" in name or "sse_write" in name or name in extra


def _flag(call: ast.Call) -> str | None:
    """The violation message for ``call``, or None."""
    name = dotted_name(call.func) or ""
    if name in _JSON_CALLS:
        return (
            f"`{name}(...)` inside the SSE chunk loop — serializing "
            "whole aggregates per delta is quadratic host work; "
            "serialize only the delta (protocols/sse.py encode_sse) "
            "and keep aggregates out of the loop"
        )
    if name in _SYNC_CALLS:
        return (
            f"`{name}(...)` inside the SSE chunk loop blocks the whole "
            "event loop once per chunk per stream — every concurrent "
            "stream observes the stall (loop-lag p99, "
            "telemetry/hostplane.py)"
        )
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in _SYNC_ATTRS:
            return (
                f"`.{attr}(...)` (sync socket op) inside the SSE chunk "
                "loop blocks the event loop — use the response's async "
                "write path"
            )
        if attr in _DECODE_ATTRS:
            recv = dotted_name(call.func.value) or ""
            if "tokenizer" in recv or "detok" in recv:
                return (
                    f"`{recv}.{attr}(...)` inside the SSE chunk loop — "
                    "re-decoding token history per chunk is quadratic; "
                    "the preprocessor already detokenized the delta "
                    "once"
                )
    return None


@rule(
    "blocking-work-in-chunk-path",
    "DL013",
    "heavyweight per-chunk work (whole-aggregate json.dumps, tokenizer "
    "decode of history, sync file/socket ops) inside an SSE writer "
    "loop — multiplied by streams × chunks on one event loop",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []
    extra = set(module.config.get("sse-writer-functions", []))

    def scan_loop(loop: ast.AST) -> None:
        """Flag offending calls anywhere under a loop body, nested defs
        included (a helper defined in the loop runs per chunk too)."""
        for child in ast.walk(loop):
            if isinstance(child, ast.Call):
                msg = _flag(child)
                if msg is not None:
                    findings.append((child, msg))

    def scan_fn(fn: ast.AST) -> None:
        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                    scan_loop(child)
                    continue  # scan_loop covered the whole subtree
                walk(child)

        walk(fn)

    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if _in_scope(node.name, extra):
            scan_fn(node)
    return findings
