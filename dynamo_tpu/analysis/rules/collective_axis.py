"""DL302 collective-axis-mismatch: a collective whose literal
``axis_name`` is not among the enclosing shard site's declared axes.

``psum(x, "dp")`` inside a body mapped with ``axis_names={"tp"}`` is
not a Python error and not even a trace error on a single-axis dev
mesh — it surfaces as a ``NameError``-at-trace on the real pod mesh,
or worse, silently reduces over the wrong axis when both names exist.
The shard-site inventory (``analysis/shardsem.py``) records each
site's declared manual axes (a literal ``axis_names=`` set, the
``auto=`` complement, or all mesh axes for the fully-manual form), and
this rule checks every collective in the wrapped body, its nested
closures, and helpers **one call level down** (the DL2xx one-level
summary discipline) against them.

The jaxsem degradation rules apply: a variable axis name (ring
attention's ``axis_name`` parameter), an opaque mesh, or a dynamic
``axis_names=`` value means the site's axis set is unknown — the
collective is skipped and the miss is counted in ``--stats``, never
guessed at.  A function reached from several shard sites is judged
against the union of their declared axes (flagging only what no
enclosing site declares).
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis import shardsem
from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.rules.common import walk_in_scope
from dynamo_tpu.analysis.taint import format_chain


@program_rule(
    "collective-axis-mismatch",
    "DL302",
    "collective axis_name literal not among the enclosing shard_map "
    "site's declared axes (trace error on the real mesh, or a reduce "
    "over the wrong axis)",
)
def check(program: LintProgram):
    graph = program.graph
    reach = shardsem.body_reach(program)
    for qn in sorted(reach):
        fn = graph.functions.get(qn)
        if fn is None:
            continue
        # one-level scope: the body's closure tree plus direct callees
        candidates = []
        for site, chain in reach[qn]:
            root = chain[0]
            outside = [
                q for q in chain
                if not shardsem.in_closure_tree(root, q)
            ]
            if len(outside) <= 1:
                candidates.append((site, chain))
        if not candidates:
            continue
        declared = frozenset()
        unknown = False
        for site, _ in candidates:
            axes = site.declared_axes()
            if axes is None:
                unknown = True
                break
            declared |= axes
        if unknown:
            continue  # counted in the inventory's dynamic misses
        imports = graph.imports.get(fn.module, {})
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            hit = shardsem.collective_axis_arg(imports, node)
            if hit is None:
                continue
            cname, axis_expr = hit
            used = shardsem.parse_axis_set(axis_expr)
            if used is None:
                continue  # dynamic axis expression: degrade, don't guess
            missing = sorted(used - declared)
            if not missing:
                continue
            site, chain = candidates[0]
            where = (
                f"one call level down: `{site.label}` -> "
                f"`{fn.name}`"
                if not shardsem.in_closure_tree(chain[0], qn)
                else f"in the body of `{site.label}`"
            )
            yield (
                fn.path,
                node,
                f"`{cname}` names axis {missing} but the enclosing "
                f"shard_map site ({site.path}:{site.lineno}) declares "
                f"axes {sorted(declared) or '{}'} ({where}; chain: "
                f"{format_chain(chain)}); declare the axis in "
                "axis_names= or fix the collective's axis_name",
            )
