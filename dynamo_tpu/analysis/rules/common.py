"""Shared AST helpers for dynalint rules."""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_in_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node``'s body without descending into nested function
    definitions — "what executes in THIS function's frame"."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


class FunctionScopeVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the kind of the innermost enclosing
    function ("async" or "sync"), so rules can ask whether a node
    executes in an async frame without being fooled by nested sync
    helpers defined inside ``async def`` bodies."""

    def __init__(self) -> None:
        self._scope: list[str] = []

    @property
    def in_async(self) -> bool:
        return bool(self._scope) and self._scope[-1] == "async"

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scope.append("async")
        self.generic_visit(node)
        self._scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scope.append("sync")
        self.generic_visit(node)
        self._scope.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._scope.append("sync")
        self.generic_visit(node)
        self._scope.pop()
