"""Shared AST helpers for dynalint rules."""

from __future__ import annotations

import ast
from typing import Iterator, Optional  # noqa: F401

from dynamo_tpu.analysis.astutil import (  # noqa: F401
    dotted_name,
    walk_in_scope,
)


class FunctionScopeVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the kind of the innermost enclosing
    function ("async" or "sync"), so rules can ask whether a node
    executes in an async frame without being fooled by nested sync
    helpers defined inside ``async def`` bodies."""

    def __init__(self) -> None:
        self._scope: list[str] = []

    @property
    def in_async(self) -> bool:
        return bool(self._scope) and self._scope[-1] == "async"

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scope.append("async")
        self.generic_visit(node)
        self._scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scope.append("sync")
        self.generic_visit(node)
        self._scope.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._scope.append("sync")
        self.generic_visit(node)
        self._scope.pop()


# blocking sync calls that stall an event loop (DL001 direct-frame,
# DL101 transitive) — dotted call name -> suggested replacement
BLOCKING_CALLS = {
    "time.sleep": "await asyncio.sleep(...)",
    "subprocess.run": "asyncio.create_subprocess_exec(...)",
    "subprocess.call": "asyncio.create_subprocess_exec(...)",
    "subprocess.check_call": "asyncio.create_subprocess_exec(...)",
    "subprocess.check_output": "asyncio.create_subprocess_exec(...)",
    "subprocess.getoutput": "asyncio.create_subprocess_shell(...)",
    "os.system": "asyncio.create_subprocess_shell(...)",
    "socket.create_connection": "asyncio.open_connection(...)",
    "socket.getaddrinfo": "loop.getaddrinfo(...)",
    "socket.gethostbyname": "loop.getaddrinfo(...)",
    "urllib.request.urlopen": "loop.run_in_executor(...)",
    "requests.get": "loop.run_in_executor(...)",
    "requests.post": "loop.run_in_executor(...)",
    "requests.put": "loop.run_in_executor(...)",
    "requests.delete": "loop.run_in_executor(...)",
    "requests.head": "loop.run_in_executor(...)",
    "requests.request": "loop.run_in_executor(...)",
}

# device->host sync operations (DL010 direct-frame, DL102 transitive)
SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
SYNC_CALLS = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
    "jax.block_until_ready",
    # the house sync primitive (parallel/multihost.py)
    "host_value",
    "multihost.host_value",
}


import re as _re

_LOCK_NAME = _re.compile(r"(?:^|.*_)r?(?:lock|mutex)$")
LOCK_CALLS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}


def looks_like_thread_lock(expr: ast.AST) -> bool:
    """Shared lock heuristic (DL005, DL103): the expression constructs a
    threading lock or is a name whose last segment is lock/rlock/mutex
    (word-boundary matched — `free_blocks` is not a lock)."""
    if isinstance(expr, ast.Call):
        return (dotted_name(expr.func) or "") in LOCK_CALLS
    name = dotted_name(expr)
    if name is None:
        return False
    return _LOCK_NAME.match(name.rsplit(".", 1)[-1].lower()) is not None
