"""DL103 cross-thread-mutation: an attribute written from a different
concurrency domain than the one that owns it, without a declared
handoff.

The codebase's cross-thread seams are attribute flips: the event loop
(planner degradation task) writes ``engine.spec_suspended`` while the
engine thread reads it every step; watcher tasks update registries
that other threads snapshot. Each of these is *fine when declared* —
and a latent race when it isn't. This rule makes the declaration
mandatory:

**Ownership** comes from two sources:

1. ``affinity.guard_attrs(obj, {"attr": "domain"})`` — the runtime
   sanitizer's registration doubles as the static declaration, scoped
   to the class whose method registers it. A write from a
   differently-tainted function is flagged when the receiver's class
   matches the declaring class — or cannot be resolved at all
   (parameters, untyped attributes like degradation's ``self.engine``:
   exactly the cross-object seams the rule exists for, kept
   name-matched on purpose). A *resolvable* receiver of an unrelated
   class that merely shares the attribute name is left to the
   undeclared-conflict scan below.
2. Undeclared attributes: per class, ``self.<attr>`` write sites are
   grouped by the writing method's affinity taint
   (analysis/taint.py: ``@thread_affinity`` declarations + coroutines
   = "loop", propagated along calls). If one attribute is written from
   two or more distinct domains, every cross-domain write site is
   flagged — the attribute is de facto shared state and nobody said
   so. ``__init__``/``__post_init__``/``__new__`` writes are
   construction-time and exempt (the object is not shared yet).

**A declared handoff waives the site.** Any of:

- the write is inside ``with affinity.handoff(...)`` (the runtime
  sanitizer's sanction — using it makes both planes agree);
- the write is inside ``with <lock>:`` (DL005's word-boundary lock
  heuristic: ``threading.Lock()`` / names ending in lock/rlock/mutex);
- the statement's first line carries ``# dynalint: handoff=<why>`` —
  an explicit declaration-with-justification, deliberately distinct
  from ``disable=`` (a handoff is a design statement, not a waiver);
- the value flows through ``queue.Queue`` / ``call_soon_threadsafe`` /
  ``run_coroutine_threadsafe`` instead of a direct attribute write —
  those simply never look like attribute writes, so they pass for
  free.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.rules.common import (
    dotted_name,
    looks_like_thread_lock,
)
from dynamo_tpu.analysis.taint import format_chain

_HANDOFF_COMMENT = re.compile(r"#\s*dynalint:\s*handoff=")
_CTOR_NAMES = {"__init__", "__post_init__", "__new__", "__enter__"}


def _is_handoff_cm(expr: ast.AST) -> bool:
    """``with affinity.handoff(...)`` / ``with handoff(...)``."""
    if isinstance(expr, ast.Call):
        name = dotted_name(expr.func) or ""
        return name.split(".")[-1] == "handoff"
    return False


class _WriteCollector(ast.NodeVisitor):
    """Attribute write sites in one function frame, with sanction info
    from the enclosing ``with`` stack."""

    def __init__(self, source_lines: List[str]):
        self.lines = source_lines
        self.sanction_depth = 0
        # (receiver, attr, node, sanctioned)
        self.writes: List[Tuple[str, str, ast.AST, bool]] = []

    def _sanctioned(self, node: ast.AST) -> bool:
        if self.sanction_depth > 0:
            return True
        i = getattr(node, "lineno", 0) - 1
        if 0 <= i < len(self.lines) and _HANDOFF_COMMENT.search(self.lines[i]):
            return True
        return False

    def _note(self, target: ast.AST, node: ast.AST) -> None:
        if not isinstance(target, ast.Attribute):
            return
        receiver = dotted_name(target.value)
        if receiver is None:
            return
        self.writes.append(
            (receiver, target.attr, node, self._sanctioned(node))
        )

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._note(node.target, node)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        guards = any(
            _is_handoff_cm(i.context_expr) or
            looks_like_thread_lock(i.context_expr)
            for i in node.items
        )
        if guards:
            self.sanction_depth += 1
        self.generic_visit(node)
        if guards:
            self.sanction_depth -= 1

    # stay in this frame — nested defs are their own graph nodes with
    # their own taints and get collected separately
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass


def _declared_attrs(program: LintProgram) -> Dict[str, Tuple[str, str]]:
    """Scan for ``guard_attrs(obj, {literal})`` calls: attr name ->
    (domain, declaring class qualname or '')."""
    out: Dict[str, Tuple[str, str]] = {}
    graph = program.graph
    for qn, fn in graph.functions.items():
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name.split(".")[-1] != "guard_attrs":
                continue
            if len(node.args) < 2 or not isinstance(node.args[1], ast.Dict):
                continue
            for k, v in zip(node.args[1].keys, node.args[1].values):
                if (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    out[k.value] = (v.value, fn.cls or "")
    return out


def _enclosing_class_of(program: LintProgram, qn: str) -> Optional[str]:
    fn = program.graph.functions.get(qn)
    if fn is None:
        return None
    if fn.cls is not None:
        return fn.cls
    if "<locals>" in qn:
        outer = program.graph.functions.get(qn.split(".<locals>.", 1)[0])
        return outer.cls if outer else None
    return None


def _receiver_class(
    program: LintProgram, qn: str, receiver: str
) -> Optional[str]:
    """Best-effort class of the write's receiver: ``self`` -> enclosing
    class; ``self.<a>`` -> the enclosing class's inferred attr type;
    anything else (parameters, locals) -> unknown (None)."""
    parts = receiver.split(".")
    if parts[0] not in ("self", "cls"):
        return None
    own = _enclosing_class_of(program, qn)
    if own is None or len(parts) == 1:
        return own
    if len(parts) == 2:
        cls = program.graph.classes.get(own)
        return cls.attr_types.get(parts[1]) if cls else None
    return None


@program_rule(
    "cross-thread-mutation",
    "DL103",
    "attribute written from a different concurrency domain than its "
    "owner without a declared handoff (queue/call_soon_threadsafe/"
    "lock/affinity.handoff/# dynalint: handoff=)",
)
def check(program: LintProgram):
    graph = program.graph
    taints = program.taints
    declared = _declared_attrs(program)

    # collect write sites once per function
    # qn -> [(receiver, attr, node, sanctioned)]
    sites: Dict[str, List[Tuple[str, str, ast.AST, bool]]] = {}
    for qn, fn in graph.functions.items():
        module = program.modules.get(fn.path)
        if module is None:
            continue
        collector = _WriteCollector(module.source.splitlines())
        for stmt in fn.node.body:
            collector.visit(stmt)
        if collector.writes:
            sites[qn] = collector.writes

    # -- pass 1: declared attributes, any receiver -----------------------
    for qn, writes in sites.items():
        fn = graph.functions[qn]
        if fn.name in _CTOR_NAMES:
            continue
        domains = taints.domains(qn)
        if not domains:
            continue
        for receiver, attr, node, sanctioned in writes:
            decl = declared.get(attr)
            if decl is None or sanctioned:
                continue
            owner_domain, owner_cls = decl
            if owner_domain in domains:
                continue  # writer (at least sometimes) IS the owner
            # ownership is class-scoped when both sides are known: an
            # unrelated class's attribute that merely shares the name
            # must not inherit the declaration. Unresolvable receivers
            # (parameters, untyped attrs — e.g. degradation's
            # self.engine) stay name-matched: conservative on purpose,
            # that IS the cross-object seam the rule exists for.
            recv_cls = _receiver_class(program, qn, receiver)
            if owner_cls and recv_cls and recv_cls != owner_cls:
                continue
            chain = taints.affinity.get(qn, {})
            some_chain = next(iter(chain.values()), [qn])
            yield (
                fn.path,
                node,
                f"`{receiver}.{attr}` is {owner_domain!r}-affine "
                f"(affinity.guard_attrs) but written from "
                f"{'/'.join(sorted(domains))}-domain code "
                f"(chain: {format_chain(some_chain)}); wrap in "
                "affinity.handoff(...)/a lock, route through "
                "call_soon_threadsafe or a queue, or mark the line "
                "`# dynalint: handoff=<why>`",
            )

    # -- pass 2: undeclared self.<attr> written from >= 2 domains --------
    # class qualname -> attr -> [(qn, node, domains, sanctioned)]
    by_class: Dict[str, Dict[str, List]] = {}
    for qn, writes in sites.items():
        fn = graph.functions[qn]
        if fn.name in _CTOR_NAMES:
            continue
        cls = _enclosing_class_of(program, qn)
        if cls is None:
            continue
        domains = taints.domains(qn)
        for receiver, attr, node, sanctioned in writes:
            if receiver != "self":
                continue
            decl = declared.get(attr)
            # a declaration only exempts the conflict scan for the
            # class it was registered against (or an unscoped one) —
            # other classes' same-named attrs are still judged
            if decl is not None and decl[1] in ("", cls):
                continue
            by_class.setdefault(cls, {}).setdefault(attr, []).append(
                (qn, node, domains, sanctioned)
            )
    for cls, attrs in sorted(by_class.items()):
        for attr, entries in sorted(attrs.items()):
            all_domains: Set[str] = set()
            for _, _, domains, _ in entries:
                all_domains |= domains
            if len(all_domains) < 2:
                continue
            cls_name = cls.split(":")[-1]
            for qn, node, domains, sanctioned in entries:
                if sanctioned or not domains:
                    continue
                fn = graph.functions[qn]
                others = sorted(all_domains - domains)
                if not others:
                    continue
                chain = taints.affinity.get(qn, {})
                some_chain = next(iter(chain.values()), [qn])
                yield (
                    fn.path,
                    node,
                    f"`{cls_name}.{attr}` is written from "
                    f"{'/'.join(sorted(domains))} here (chain: "
                    f"{format_chain(some_chain)}) AND from "
                    f"{'/'.join(others)} elsewhere — shared state "
                    "with no declared handoff; guard with a lock/"
                    "affinity.handoff(...), hand off via a queue/"
                    "call_soon_threadsafe, or mark the deliberate "
                    "seam `# dynalint: handoff=<why>`",
                )
