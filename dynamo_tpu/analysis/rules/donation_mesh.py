"""DL303 donation-across-mesh: a donated buffer whose sharding cannot
be reused in place.

Donation (DL201's subject) is a layout contract as much as a lifetime
one: XLA reuses the donated buffer only when the parameter's sharding
matches.  Two ways the mesh breaks it silently:

- **spec drift**: the caller constrains a buffer to one
  ``PartitionSpec`` and donates it to a jit/pjit site whose declared
  ``in_shardings`` for that slot says another — XLA inserts a
  resharding copy first, the "donated" buffer is copied anyway, and
  the HBM headroom the donation was supposed to buy never appears
  (it shows up later as an OOM at twice the KV-cache size);
- **donation inside a shard_map body**: the body is traced per shard,
  so the donated value is one shard's *view* — freeing it from inside
  the mapped region invalidates storage the other shards (and the
  caller's rebind idiom) still alias.

Both endpoints come from the shard-site inventory
(``analysis/shardsem.py``): per-function
``x = with_sharding_constraint(x, P(...))`` bindings on one side,
jit/pjit sites combining ``donate_argnums`` with literal
``in_shardings`` on the other; the body-reachability map supplies the
shard_map case, with the jit sites themselves resolved through the
jaxsem inventory.  Dynamic specs degrade to counted misses — a
comparison only happens between two literal specs.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis import jaxsem, shardsem
from dynamo_tpu.analysis.astutil import dotted_name, walk_in_scope
from dynamo_tpu.analysis.callgraph import resolve_name
from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.taint import format_chain


def _fmt(spec) -> str:
    return "P(" + ", ".join(repr(e) for e in spec) + ")"


@program_rule(
    "donation-across-mesh",
    "DL303",
    "buffer donated under a mismatched sharding (resharding copy "
    "defeats the donation) or donated inside a shard_map body",
)
def check(program: LintProgram):
    graph = program.graph
    inv = shardsem.inventory_of(program)
    jinv = jaxsem.inventory_of(program)

    # (a) donation from inside a shard_map body: the jit site invoked
    # in a mapped frame donates a per-shard view
    reach = shardsem.body_reach(program)
    for qn in sorted(reach):
        fn = graph.functions.get(qn)
        if fn is None:
            continue
        site, chain = reach[qn][0]
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            jsite = jaxsem.resolve_call_site(jinv, graph, fn, node)
            if jsite is None or not jsite.donate:
                continue
            yield (
                fn.path,
                node,
                f"`{jsite.label}` donates argument(s) "
                f"{list(jsite.donate)} inside the shard_map body "
                f"`{site.label}` (site {site.path}:{site.lineno}, "
                f"chain: {format_chain(chain)}) — the donated value is "
                "one shard's view and the other shards still alias its "
                "storage; donate at the unmapped call boundary instead",
            )

    # (b) donated argument constrained to a spec that differs from the
    # jit/pjit site's declared in_shardings for that slot
    for qn, fn in sorted(graph.functions.items()):
        constrained = inv.constraints.get(qn)
        if not constrained:
            continue
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            jsite = _sharded_jit_site(inv, graph, fn, node)
            if jsite is None or not jsite.donate:
                continue
            if jsite.in_shardings is None:
                continue  # dynamic shardings: counted, not compared
            for i in jsite.donate:
                if i >= len(node.args) or i >= len(jsite.in_shardings):
                    continue
                arg = node.args[i]
                if not isinstance(arg, ast.Name):
                    continue
                got = constrained.get(arg.id)
                want = jsite.in_shardings[i]
                if got is None or want == shardsem.DYNAMIC:
                    continue
                if shardsem.DYNAMIC in got or shardsem.DYNAMIC in want:
                    continue
                if got != want:
                    yield (
                        fn.path,
                        node,
                        f"`{arg.id}` is constrained to {_fmt(got)} but "
                        f"donated into slot {i} of `{jsite.label}` "
                        f"({jsite.path}:{jsite.lineno}) declared as "
                        f"{_fmt(want)} — XLA reshards into a fresh "
                        "buffer first, so the donation frees nothing; "
                        "align the constraint with the site's "
                        "in_shardings (or drop the donate)",
                    )


def _sharded_jit_site(inv, graph, fn, call):
    """The donate+in_shardings site an ast.Call invokes, through a
    local binding (closure chain) or a module-level name."""
    name = dotted_name(call.func)
    if name is None:
        return None
    if "." not in name:
        scope = fn.qualname
        while True:
            site = inv.jit_by_local.get((scope, name))
            if site is not None:
                return site
            if ".<locals>." not in scope:
                break
            scope = scope.rsplit(".<locals>.", 1)[0]
    resolved = resolve_name(graph, fn, name)
    if resolved is not None:
        return inv.jit_by_qualname.get(resolved)
    return None
