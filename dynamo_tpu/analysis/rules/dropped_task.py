"""DL002 dropped-task-handle: ``asyncio.create_task(...)`` (or
``ensure_future`` / ``loop.create_task``) as a bare expression statement.

The event loop holds only a *weak* reference to tasks — a handle that is
neither assigned, awaited, nor registered anywhere can be garbage
collected mid-flight, silently cancelling the task; its exceptions are
also never observed. Keep a strong reference (``dynamo_tpu.utils.tasks
.spawn`` does this and logs crashes) or await the task.

``asyncio.TaskGroup``-style receivers (``tg.create_task(...)`` etc.) are
exempt: the group holds the reference and re-raises exceptions."""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import dotted_name

SPAWNERS = {
    "asyncio.create_task",
    "asyncio.ensure_future",
    "create_task",  # from asyncio import create_task
    "ensure_future",
}
# receivers whose .create_task already keeps a strong reference and
# surfaces exceptions (structured concurrency): not a dropped handle
GROUP_RECEIVERS = {"tg", "group", "task_group", "taskgroup", "nursery"}
# `asyncio.get_running_loop().create_task(...)` — the chain roots in a
# Call, so dotted_name() can't resolve it; match the loop getter itself
LOOP_GETTERS = {
    "asyncio.get_running_loop",
    "asyncio.get_event_loop",
    "get_running_loop",
    "get_event_loop",
}


def _is_spawner(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if name is not None:
        if name in SPAWNERS:
            return True
        if name.endswith(".create_task"):
            receiver = name[: -len(".create_task")].rsplit(".", 1)[-1].lower()
            return receiver not in GROUP_RECEIVERS
        return False
    if isinstance(call.func, ast.Attribute) and call.func.attr == "create_task":
        base = call.func.value
        return (
            isinstance(base, ast.Call)
            and (dotted_name(base.func) or "") in LOOP_GETTERS
        )
    return False


def _display(func: ast.AST) -> str:
    """Readable call-target for Call-rooted chains dotted_name can't
    resolve, e.g. `asyncio.get_running_loop().create_task`."""
    if isinstance(func, ast.Attribute):
        if isinstance(func.value, ast.Call):
            base = dotted_name(func.value.func)
            if base:
                return f"{base}().{func.attr}"
        return func.attr
    return "create_task"


@rule(
    "dropped-task-handle",
    "DL002",
    "task spawned without keeping a handle (GC can cancel it mid-flight)",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []
    for node in ast.walk(module.tree):
        # only a *bare expression statement* drops the handle; assignment,
        # await, or use as an argument (gather, list.append) all keep one
        if (
            isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Call)
            and _is_spawner(node.value)
        ):
            name = dotted_name(node.value.func) or _display(node.value.func)
            findings.append(
                (
                    node,
                    f"`{name}(...)` result is dropped — the loop only "
                    "weak-refs tasks, so GC can cancel it and its "
                    "exceptions are never logged; keep the handle "
                    "(e.g. dynamo_tpu.utils.tasks.spawn)",
                )
            )
    return findings
