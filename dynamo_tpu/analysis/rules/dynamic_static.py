"""DL202 dynamic-static-arg: a per-step / unhashable / device value
flowing into a jit ``static_argnums``/``static_argnames`` slot.

A static slot is part of the *compile key*: jit hashes the value and
specializes the program on it.  Three ways to get that wrong, in
rising order of subtlety:

- **unhashable containers** (a list/dict/set literal, a comprehension)
  — ``TypeError`` at the first call, or a silent retrace per identity;
- **device arrays** — a value produced by another jitted call needs a
  host sync just to hash, and retraces whenever the *content* changes;
- **per-step values** — a local recomputed each loop iteration
  (``len(batch)``, a call result) inside step-loop-reachable code:
  every distinct value silently compiles a new executable, turning the
  steady-state decode loop into a compile loop (the mid-serve stall
  DL203's prewarm contract exists to prevent).

Container literals and device-array locals are flagged everywhere —
they are wrong regardless of context.  Call expressions and
loop-assigned locals are flagged only in functions carrying the
**step-loop taint** (reachable from the configured step-loop entry
points): at init/prewarm time, feeding a computed bucket size to a
static slot is exactly how AOT warming is supposed to work, so flagging
it there would be noise.  Like DL201, a one-level wrapper summary sees
a dynamic value handed to a helper whose parameter lands in a static
slot one frame down — the message prints the hop.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dynamo_tpu.analysis import jaxsem
from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.rules.common import dotted_name
from dynamo_tpu.analysis.astutil import walk_in_scope
from dynamo_tpu.analysis.taint import format_chain

_UNHASHABLE = (
    ast.List,
    ast.Dict,
    ast.Set,
    ast.ListComp,
    ast.DictComp,
    ast.SetComp,
    ast.GeneratorExp,
)


def _local_facts(
    program: LintProgram, fn
) -> Tuple[Set[str], Set[str], Dict[str, ast.Tuple]]:
    """(names assigned from jit-site calls, names assigned inside a
    loop body, same-frame tuple literals) for one function."""
    inv = jaxsem.inventory_of(program)
    device_names: Set[str] = set()
    loop_names: Set[str] = set()
    tuples: Dict[str, ast.Tuple] = {}

    def scan(body: List[ast.stmt], in_loop: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                # the loop TARGET is the archetypal per-iteration value
                loop_names.update(
                    n.id for n in ast.walk(stmt.target)
                    if isinstance(n, ast.Name)
                )
            if isinstance(stmt, ast.Assign):
                names = [
                    t.id for t in stmt.targets if isinstance(t, ast.Name)
                ]
                for t in stmt.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        names.extend(
                            el.id for el in t.elts
                            if isinstance(el, ast.Name)
                        )
                if in_loop:
                    loop_names.update(names)
                if isinstance(stmt.value, ast.Call) and jaxsem.resolve_call_site(
                    inv, program.graph, fn, stmt.value
                ):
                    device_names.update(names)
                if (
                    isinstance(stmt.value, ast.Tuple)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                ):
                    tuples[stmt.targets[0].id] = stmt.value
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    scan(
                        sub,
                        in_loop or isinstance(stmt, (ast.For, ast.AsyncFor,
                                                     ast.While)),
                    )
            for h in getattr(stmt, "handlers", []):
                scan(h.body, in_loop)

    scan(fn.node.body, False)
    return device_names, loop_names, tuples


def _classify(
    expr: ast.AST,
    *,
    in_step_loop: bool,
    device_names: Set[str],
    loop_names: Set[str],
) -> Optional[str]:
    """Why this expression must not land in a static slot, or None."""
    if isinstance(expr, _UNHASHABLE):
        return (
            "an unhashable container literal (jit cannot hash it into "
            "the compile key)"
        )
    if isinstance(expr, ast.Name):
        if expr.id in device_names:
            return (
                "a device array (the result of a jitted call — hashing "
                "it needs a host sync and retraces per value)"
            )
        if in_step_loop and expr.id in loop_names:
            return (
                "a per-step local (assigned inside a loop — every new "
                "value silently compiles a new executable)"
            )
        return None
    if in_step_loop and isinstance(expr, ast.Call):
        return (
            "computed per call in step-loop-reachable code (every "
            "distinct value is a silent recompile)"
        )
    return None


@program_rule(
    "dynamic-static-arg",
    "DL202",
    "a non-compile-time-constant value (per-step local, device array, "
    "unhashable container) flowing into a jit static_argnums slot",
)
def check(program: LintProgram):
    inv = jaxsem.inventory_of(program)
    graph = program.graph
    for qn, fn in graph.functions.items():
        chain = program.taints.step_loop.get(qn)
        in_step_loop = chain is not None
        device_names, loop_names, tuples = _local_facts(program, fn)
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            # direct jit site, else a one-level wrapper summary
            site = jaxsem.resolve_call_site(inv, graph, fn, node)
            via = ""
            slots: Dict[int, str] = {}
            names: Dict[str, str] = {}
            if site is not None and (site.static or site.static_names):
                label = site.label
                slots = {i: label for i in site.static}
                names = {n: label for n in site.static_names}
            else:
                name = dotted_name(node.func)
                resolved = (
                    jaxsem.resolve_name(graph, fn, name) if name else None
                )
                flows = inv.static_params.get(resolved or "", {})
                if not flows:
                    continue
                short = (resolved or "").rsplit(":", 1)[-1]
                for i, pf in flows.items():
                    slots[i] = pf.site.label
                    names[pf.param] = pf.site.label
                    via = f" (one call level down: `{short}` -> " \
                          f"`{pf.site.label}`)"
            args = jaxsem.effective_positional(node, tuples)
            checks: List[Tuple[ast.AST, str]] = []
            for i, label in slots.items():
                if i < len(args) and args[i] is not None:
                    checks.append((args[i], label))
            for kw in node.keywords:
                if kw.arg in names:
                    checks.append((kw.value, names[kw.arg]))
            for expr, label in checks:
                why = _classify(
                    expr,
                    in_step_loop=in_step_loop,
                    device_names=device_names,
                    loop_names=loop_names,
                )
                if why is None:
                    continue
                suffix = ""
                if in_step_loop and chain and len(chain) > 1:
                    suffix = f" (step-loop chain: {format_chain(chain)})"
                yield (
                    fn.path,
                    expr,
                    f"static_argnums slot of jitted `{label}`{via} "
                    f"receives {why}; hoist a genuine constant, or make "
                    f"the argument a traced (non-static) input{suffix}",
                )
