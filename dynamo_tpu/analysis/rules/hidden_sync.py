"""DL010 hidden-host-sync-in-step-loop: device→host synchronization in
the engine step loop anywhere but the designated harvest point.

The overlapped decode pipeline (docs/performance.md) only hides host
work behind device execution if the step loop's ONE sync happens at its
harvest point — a function whose name marks it as such. Any other
``jax.block_until_ready(...)`` / ``.block_until_ready()`` / ``.item()``
/ ``.tolist()`` / ``np.asarray``/``np.array`` / ``jax.device_get`` /
``host_value`` (the house sync primitive, parallel/multihost.py)
inside the loop silently re-serializes the pipeline: the host parks on
the device mid-plan, the device then parks on the host mid-step, and
the idle gap the pipeline exists to remove comes back — invisibly,
because the code still computes the right answer. (This is the runtime
twin of DL004, which guards the *inside* of jit-compiled functions;
DL010 guards the host loop that drives them.)

Scope is name-structural, like DL009: a function is part of the step
loop when its name contains ``step_loop`` (the engine's loop itself) or
appears in the ``step-loop-functions`` config list ([tool.dynalint] —
seeded with the engine's dispatch/pipeline entry points). Nested defs
inside a scoped function are the loop's helper closures and stay in
scope — EXCEPT functions whose name contains ``harvest``, the
designated sync point, which are exempt along with everything they
alone contain. ``np.asarray`` on an already-host array is flagged too:
inside the step loop it is at best a redundant copy and at worst a
hidden sync the next refactor trips over — move the materialization to
the harvest function either way.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import (
    SYNC_ATTRS,
    SYNC_CALLS,
    dotted_name,
)

# SYNC_ATTRS / SYNC_CALLS live in common.py (DL102 reuses them
# for the transitive pass)


def _is_harvest(name: str) -> bool:
    return "harvest" in name


def _in_scope(name: str, extra: set[str]) -> bool:
    return "step_loop" in name or name in extra


@rule(
    "hidden-host-sync-in-step-loop",
    "DL010",
    "device sync (.item/np.asarray/block_until_ready) in the engine "
    "step loop outside the designated harvest point — re-serializes "
    "the overlapped decode pipeline",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []
    extra = set(module.config.get("step-loop-functions", []))

    def scan(fn: ast.AST) -> None:
        """Flag sync calls in ``fn`` and its nested defs, skipping any
        nested subtree whose def is harvest-named (the designated sync
        point scopes apart, including its own nested helpers)."""

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and _is_harvest(child.name):
                    continue
                if isinstance(child, ast.Call):
                    name = dotted_name(child.func) or ""
                    if name in SYNC_CALLS:
                        findings.append(
                            (
                                child,
                                f"`{name}(...)` syncs device->host inside "
                                "the engine step loop — move the "
                                "materialization to the designated "
                                "harvest function so the overlapped "
                                "pipeline keeps the device fed",
                            )
                        )
                    elif (
                        isinstance(child.func, ast.Attribute)
                        and child.func.attr in SYNC_ATTRS
                    ):
                        findings.append(
                            (
                                child,
                                f"`.{child.func.attr}()` syncs device->"
                                "host inside the engine step loop "
                                "outside the designated harvest point — "
                                "it re-serializes the overlapped "
                                "pipeline",
                            )
                        )
                walk(child)

        walk(fn)

    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if _is_harvest(node.name):
            continue
        if _in_scope(node.name, extra):
            scan(node)
    return findings
