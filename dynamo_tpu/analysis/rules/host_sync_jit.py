"""DL004 host-sync-in-jit-path: host↔device synchronization inside a
jit-compiled function.

``.item()`` / ``.tolist()`` / ``np.asarray`` / ``jax.device_get`` /
``.block_until_ready()`` inside a ``jax.jit``/``pjit`` function either
fail at trace time or — worse, via callbacks — force a device round-trip
on every step of the decode hot loop, collapsing throughput.

Detection: functions decorated with jit/pjit (including
``functools.partial(jax.jit, ...)``), functions *passed* to a
``jax.jit(fn, ...)`` call in the same module, and any function named in
the ``hot-functions`` config list ([tool.dynalint]) — the engine step
loop can be pinned there without a decorator.

``float()``/``int()`` are deliberately not flagged: shape arithmetic
(``float(x.shape[-1]) ** -0.5``) is static and idiomatic in jit code."""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import dotted_name

JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit"}
SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
SYNC_CALLS = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
}


def _is_jit_expr(expr: ast.AST) -> bool:
    """jit / jax.jit / pjit, possibly wrapped in functools.partial."""
    if (dotted_name(expr) or "") in JIT_NAMES:
        return True
    if isinstance(expr, ast.Call):
        fn = dotted_name(expr.func) or ""
        if fn in JIT_NAMES:
            return True
        if fn in ("functools.partial", "partial") and expr.args:
            return (dotted_name(expr.args[0]) or "") in JIT_NAMES
    return False


def _jit_function_names(tree: ast.Module) -> set[str]:
    """Names of plain functions passed to a jit call: `jax.jit(step, ...)`."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and (dotted_name(node.func) or "") in JIT_NAMES:
            if node.args and isinstance(node.args[0], ast.Name):
                names.add(node.args[0].id)
    return names


@rule(
    "host-sync-in-jit-path",
    "DL004",
    "host-device sync (.item/np.asarray/block_until_ready) in a jit path",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []
    jit_called = _jit_function_names(module.tree)
    hot_extra = set(module.config.get("hot-functions", []))

    def scan(fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_ATTRS
            ):
                findings.append(
                    (
                        node,
                        f"`.{node.func.attr}()` synchronizes host and "
                        "device inside a jit path; hoist it out of the "
                        "compiled function",
                    )
                )
            elif (dotted_name(node.func) or "") in SYNC_CALLS:
                findings.append(
                    (
                        node,
                        f"`{dotted_name(node.func)}(...)` materializes on "
                        "host inside a jit path; use jnp ops or hoist it "
                        "out of the compiled function",
                    )
                )

    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        jit_decorated = any(_is_jit_expr(d) for d in node.decorator_list)
        if jit_decorated or node.name in jit_called or node.name in hot_extra:
            scan(node)
    return findings
