"""DL203 prewarm-coverage: a jitted callable the step loop can reach
that no ``_prewarm`` path references.

The engine's static-shape discipline (docs/performance.md) promises
that every jit signature the serve path can hit is compiled at startup
by ``_prewarm`` — a signature that isn't is a multi-second XLA compile
in the middle of serving, exactly the TTFT/ITL stall the shape
bucketing exists to prevent.  That contract has been re-broken by hand
in almost every pipeline PR (the spec/overlap prewarm patches, the
PR-12 review's unreachable-prewarm find), because nothing checked it.

This rule checks it mechanically:

1. collect the jit-site inventory (jaxsem.py) — decorated functions
   and ``self.<attr> = jax.jit(...)`` bindings;
2. find every site *invoked* from a function carrying the **step-loop
   taint** (reachable from the configured ``step-loop-functions`` /
   ``*step_loop*`` entry points along same-context call edges — the
   PR-8 pass);
3. find every site *referenced* on a **prewarm path**: any function
   whose name contains ``prewarm`` (plus config ``prewarm-functions``
   entries), and everything reachable from those along same-context
   edges;
4. a site in (2) but not (3) is a compile-at-serve-time hazard — one
   finding per jitted callable, anchored at its first step-loop
   invocation, printing the taint chain that makes it reachable.

The runtime twin is the compile fence (``DYN_COMPILE_FENCE=1``,
utils/compile_fence.py): a serve-phase XLA compile — i.e. this rule's
hazard actually firing in production — escalates to a flight-recorder
``serve_compile`` record and a black-box bundle.  Static rule for the
PR diff, runtime fence for everything the static view can't see.

Coverage is judged per *callable*, not per jit signature: prewarm
feeding the right shapes/dtypes through the referenced callable is its
job (and the fence's to verify), not this rule's.  A deliberately
cold variant (e.g. a rare diagnostic path) is suppressed in place with
``# dynalint: disable=prewarm-coverage — why``.
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Dict, List, Set, Tuple

from dynamo_tpu.analysis import jaxsem
from dynamo_tpu.analysis.astutil import walk_in_scope
from dynamo_tpu.analysis.callgraph import SAME_CONTEXT, enclosing_class
from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.taint import format_chain


def _prewarm_roots(program: LintProgram) -> Set[str]:
    extra = set(program.config.get("prewarm-functions", []))
    roots = set()
    for qn, fn in program.graph.functions.items():
        if "prewarm" in fn.name.lower() or fn.name in extra:
            roots.add(qn)
    return roots


def _reachable(program: LintProgram, roots: Set[str]) -> Set[str]:
    graph = program.graph
    seen = set(roots)
    work = deque(roots)
    while work:
        cur = work.popleft()
        for e in graph.out_edges(cur):
            if e.kind in SAME_CONTEXT and e.callee not in seen:
                if e.callee in graph.functions:
                    seen.add(e.callee)
                    work.append(e.callee)
    return seen


def _referenced_sites(program: LintProgram, fns: Set[str]) -> Set[str]:
    """Site keys referenced (called OR mentioned) inside ``fns``."""
    inv = jaxsem.inventory_of(program)
    graph = program.graph
    covered: Set[str] = set()
    for qn in fns:
        fn = graph.functions.get(qn)
        if fn is None:
            continue
        cls_qn = enclosing_class(graph, fn)
        for node in walk_in_scope(fn.node):
            if isinstance(node, ast.Call):
                site = jaxsem.resolve_call_site(inv, graph, fn, node)
                if site is not None:
                    covered.add(site.key)
            elif isinstance(node, ast.Attribute) and cls_qn is not None:
                # a bare mention (`self._step_fn is not None`, passing
                # the callable along) counts as prewarm awareness
                site = inv.by_attr.get((cls_qn, node.attr))
                if site is not None:
                    covered.add(site.key)
            elif isinstance(node, ast.Name):
                site = inv.by_qualname.get(
                    jaxsem.resolve_name(graph, fn, node.id) or ""
                )
                if site is not None:
                    covered.add(site.key)
    return covered


@program_rule(
    "prewarm-coverage",
    "DL203",
    "a jitted callable reachable from the step loop that no _prewarm "
    "path references (first serve-time call compiles mid-serve)",
)
def check(program: LintProgram):
    inv = jaxsem.inventory_of(program)
    graph = program.graph
    covered = _referenced_sites(
        program, _reachable(program, _prewarm_roots(program))
    )
    # first step-loop invocation per site key, in deterministic order
    hits: Dict[str, Tuple[str, ast.AST, List[str]]] = {}
    for qn in sorted(program.taints.step_loop):
        fn = graph.functions.get(qn)
        if fn is None:
            continue
        chain = program.taints.step_loop[qn]
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            site = jaxsem.resolve_call_site(inv, graph, fn, node)
            if site is None or site.key in covered:
                continue
            prev = hits.get(site.key)
            if prev is None or (
                len(chain) < len(prev[2])
            ):
                hits[site.key] = (fn.path, node, chain)
    for key in sorted(hits):
        path, node, chain = hits[key]
        site = next(s for s in inv.sites if s.key == key)
        yield (
            path,
            node,
            f"jitted `{site.label}` (defined {site.path}:{site.lineno}) "
            "is invoked on the serve path but referenced by no prewarm "
            f"function — its first call is a mid-serve XLA compile "
            f"(step-loop chain: {format_chain(chain)}); warm it in "
            "_prewarm, or waive a deliberately cold variant in place",
        )
