"""DL008 unbounded-retry-loop: a ``while True:`` reconnect loop with no
pacing.

A loop that redials a peer (``asyncio.open_connection``, ``.connect``,
``create_connection``, ...) and handles failure with a bare
``continue`` hammers a flapping or restarting peer as fast as the
connect syscall fails — a tight loop that turns one dead coordinator
into a self-inflicted connect storm across the fleet (the SRE
retry-budget literature's canonical anti-pattern). Every reconnect loop
must pace itself: capped exponential backoff + jitter
(``utils/backoff.py Backoff``) is the house idiom; a plain
``asyncio.sleep`` bound also counts.

The rule fires on ``while True:`` (or ``while 1:``) loops whose body
awaits a connection-establishing call and contains NO pacing bound —
no ``asyncio.sleep``/``time.sleep`` call, and nothing named like a
backoff helper (``backoff.sleep()``, ``Backoff(...)``,
``next_delay``). Read loops (``await read_frame(...)`` etc.) are not
connection-establishing and are never flagged: blocking on data is the
correct way to wait.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import dotted_name

# call names (last dotted component) that establish a connection
CONNECT_NAMES = {
    "open_connection",
    "create_connection",
    "connect",
    "reconnect",
    "dial",
    "open_unix_connection",
}

# names that count as pacing: a sleep, or anything backoff-shaped
SLEEP_NAMES = {"sleep"}
BACKOFFISH = ("backoff", "next_delay")


def _last_component(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _is_while_true(node: ast.While) -> bool:
    test = node.test
    return (
        isinstance(test, ast.Constant) and bool(test.value) is True
    )


class _LoopScan(ast.NodeVisitor):
    """One loop body: connection-establishing awaits + pacing bounds.
    Nested function definitions scope separately (their loops are
    scanned when the walker reaches them; their sleeps don't pace us).
    """

    def __init__(self) -> None:
        self.connects: list[tuple[ast.AST, str]] = []
        self.paced = False

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return  # separate scope

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return  # separate scope

    def visit_While(self, node: ast.While) -> None:
        return  # inner loops are scanned on their own

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        last = _last_component(name)
        if last in SLEEP_NAMES or any(b in name.lower() for b in BACKOFFISH):
            self.paced = True
        elif last in CONNECT_NAMES:
            self.connects.append((node, name))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if any(b in node.id.lower() for b in BACKOFFISH):
            self.paced = True
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if any(b in node.attr.lower() for b in BACKOFFISH):
            self.paced = True
        self.generic_visit(node)


@rule(
    "unbounded-retry-loop",
    "DL008",
    "while-True reconnect loop with no backoff/sleep pacing (hammers a "
    "flapping peer)",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.While) or not _is_while_true(node):
            continue
        scan = _LoopScan()
        for stmt in node.body:
            scan.visit(stmt)
        if scan.paced or not scan.connects:
            continue
        for site, name in scan.connects:
            findings.append(
                (
                    site,
                    f"`{name}(...)` retried in a `while True:` loop with "
                    "no backoff/sleep — pace reconnects with "
                    "utils.backoff.Backoff (capped exponential + jitter) "
                    "or at least `await asyncio.sleep(...)`",
                )
            )
    return findings
