"""DL301 host-sync-in-shard-body: a device->host sync reachable from
inside a shard_map-wrapped body.

DL010/DL102 police the step loop because one hidden ``.item()`` stalls
one device.  Inside a ``shard_map`` body the same call is worse by a
mesh factor: the body is traced into every shard's program, so a host
materialization executes *per shard* and the slowest host round-trip
gates all of them — the collective that follows waits on the last
device, and the whole mesh serializes (the multi-host variant of
docs/performance.md's overlap collapse).  On pods it is usually also a
trace error, but only at deploy scale, long after the PR merged.

The rule reuses DL010's sync-op set (``rules/common.py``) and scans
every function the shard-site inventory's **body reachability** map
covers: the wrapped callable, its nested closures (the house style
wraps a local ``def``), and everything they reach along same-context
call edges — direct and transitive frames alike, with the call chain
printed.  There is no harvest exemption here: a sanctioned sync point
cannot exist inside a mapped region.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis import shardsem
from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.rules.common import (
    SYNC_ATTRS,
    SYNC_CALLS,
    dotted_name,
    walk_in_scope,
)
from dynamo_tpu.analysis.taint import format_chain


@program_rule(
    "host-sync-in-shard-body",
    "DL301",
    "device sync reachable from inside a shard_map body (executes per "
    "shard and serializes the whole mesh)",
)
def check(program: LintProgram):
    graph = program.graph
    reach = shardsem.body_reach(program)
    for qn in sorted(reach):
        fn = graph.functions.get(qn)
        if fn is None:
            continue
        site, chain = reach[qn][0]
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name in SYNC_CALLS:
                what = f"`{name}(...)`"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_ATTRS
            ):
                what = f"`.{node.func.attr}()`"
            else:
                continue
            yield (
                fn.path,
                node,
                f"{what} syncs device->host inside the shard_map body "
                f"`{site.label}` (site {site.path}:{site.lineno}, "
                f"chain: {format_chain(chain)}) — the body runs per "
                "shard, so this serializes every device in the mesh; "
                "hoist the materialization outside the mapped region",
            )
