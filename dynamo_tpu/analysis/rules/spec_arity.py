"""DL304 spec-arity-drift: literal in_specs/out_specs that disagree
with the wrapped function's signature or the declared axis set.

``shard_map``'s spec pytrees are positional: add a parameter to the
wrapped body and forget the matching ``in_specs`` entry, and jax
reports a pytree-structure error at trace time — on the dev box if
you're lucky, on the pod if the extra argument only flows on the
multi-host path.  Worse, an axis name that appears in a spec but not
in the site's declared manual axes partitions over an axis the body
was never mapped over.  Both are mechanical to check once the
shard-site inventory has parsed the literals:

- **arity**: an ``in_specs=`` literal tuple must have one entry per
  positional parameter of the (resolved) wrapped function; an
  ``out_specs=`` literal tuple must match the body's returned tuple
  arity when every ``return`` is a literal tuple of one consistent
  length;
- **axis set**: every axis a spec names must be among the site's
  declared axes (literal ``axis_names=``, the ``auto=`` complement,
  or a statically-known mesh's full axis set).

Everything else follows the jaxsem degradation rules: a dynamic spec,
an unresolved wrapped callable, ``*args`` in the signature, or an
opaque mesh means the check silently doesn't apply — the miss is
counted in ``--stats`` (shard inventory ``dynamic_misses``), never
turned into a guessed index.
"""

from __future__ import annotations

import ast
from typing import Optional

from dynamo_tpu.analysis import shardsem
from dynamo_tpu.analysis.jaxsem import _positional_params
from dynamo_tpu.analysis.program import LintProgram, program_rule


def _return_arity(fn_node: ast.AST) -> Optional[int]:
    """The wrapped body's returned-tuple arity, when every ``return``
    is a literal tuple of one consistent length; None otherwise."""
    arity: Optional[int] = None
    from dynamo_tpu.analysis.astutil import walk_in_scope

    for node in walk_in_scope(fn_node):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        if not isinstance(node.value, ast.Tuple):
            return None
        if arity is None:
            arity = len(node.value.elts)
        elif arity != len(node.value.elts):
            return None
    return arity


@program_rule(
    "spec-arity-drift",
    "DL304",
    "shard_map in_specs/out_specs literal whose arity or axis set "
    "disagrees with the wrapped function's signature or declared axes",
)
def check(program: LintProgram):
    graph = program.graph
    inv = shardsem.inventory_of(program)
    for site in inv.sites:
        if site.kind != "shard_map" or site.node is None:
            continue
        wrapped = (
            graph.functions.get(site.wrapped) if site.wrapped else None
        )

        if wrapped is not None and site.in_specs is not None:
            a = wrapped.node.args
            if a.vararg is None and a.kwarg is None:
                params = _positional_params(wrapped)
                if len(site.in_specs) != len(params):
                    yield (
                        site.path,
                        site.node,
                        f"in_specs has {len(site.in_specs)} entries "
                        f"but `shard_map` -> `{site.label}` takes "
                        f"{len(params)} positional parameter(s) "
                        f"({', '.join(params) or 'none'}) — jax "
                        "raises a pytree-structure error at trace "
                        "time; keep one spec per argument",
                    )

        if wrapped is not None and site.out_specs is not None:
            ret = _return_arity(wrapped.node)
            if ret is not None and ret != len(site.out_specs):
                yield (
                    site.path,
                    site.node,
                    f"out_specs has {len(site.out_specs)} entries but "
                    f"`shard_map` -> `{site.label}` returns a "
                    f"{ret}-tuple — trace-time pytree mismatch; keep "
                    "one spec per output",
                )

        declared = site.declared_axes()
        if declared is not None and site.spec_axes:
            stray = sorted(site.spec_axes - declared)
            if stray:
                yield (
                    site.path,
                    site.node,
                    f"specs of `shard_map` -> `{site.label}` name "
                    f"axis {stray} outside the site's declared axes "
                    f"{sorted(declared) or '{}'} — the body was never "
                    "mapped over that axis; declare it in axis_names= "
                    "or fix the spec",
                )
