"""DL003 swallowed-cancellation: an ``except`` handler inside an
``async def`` that catches ``asyncio.CancelledError`` (explicitly, via
``BaseException``, or via a tuple containing either) without re-raising.

Swallowing cancellation makes ``task.cancel()`` a no-op: shutdown hangs,
timeouts never fire, and the canceller believes the task stopped while
it keeps running. The fix is a dedicated handler first::

    except asyncio.CancelledError:
        raise

``except Exception`` is deliberately NOT flagged: since Python 3.8
``CancelledError`` derives from ``BaseException``, so ``Exception``
cannot catch it. Bare ``except:`` is left to DL006 (one finding per
defect)."""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import (
    FunctionScopeVisitor,
    dotted_name,
    walk_in_scope,
)

CANCEL_NAMES = {
    "BaseException",
    "CancelledError",
    "asyncio.CancelledError",
    "concurrent.futures.CancelledError",
}


def _catches_cancellation(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False  # bare except: DL006's territory
    exprs = t.elts if isinstance(t, ast.Tuple) else [t]
    return any((dotted_name(e) or "") in CANCEL_NAMES for e in exprs)


def _reraises(handler: ast.ExceptHandler) -> bool:
    """True if any code path in the handler body (this frame only)
    contains a raise statement."""
    for node in handler.body:
        # a `raise` inside a nested def/lambda runs in another frame
        # (maybe never): it does not re-raise for THIS handler
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Raise):
            return True
        for sub in walk_in_scope(node):
            if isinstance(sub, ast.Raise):
                return True
    return False


@rule(
    "swallowed-cancellation",
    "DL003",
    "except handler in async code catches CancelledError without re-raising",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []

    class V(FunctionScopeVisitor):
        def visit_Try(self, node: ast.Try) -> None:
            if self.in_async:
                for handler in node.handlers:
                    if _catches_cancellation(handler) and not _reraises(handler):
                        findings.append(
                            (
                                handler,
                                "handler catches asyncio.CancelledError "
                                "but never re-raises — task.cancel() is "
                                "silently absorbed; add `except asyncio."
                                "CancelledError: raise` first",
                            )
                        )
            self.generic_visit(node)

    V().visit(module.tree)
    return findings
