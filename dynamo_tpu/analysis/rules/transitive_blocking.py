"""DL101 transitive-blocking-call-in-async: a blocking call inside a
*sync* function that async code reaches through ordinary calls.

DL001 sees ``time.sleep`` directly inside an ``async def``; it cannot
see the same sleep one call level down — ``await handler()`` ->
``handler`` calls ``_retry()`` -> ``_retry`` sleeps. The event loop
stalls identically either way. This rule flags blocking calls in any
function carrying the *async-context* taint (analysis/taint.py):
reachable from a coroutine along same-context call/ref edges, with
propagation stopped at thread handoffs (``run_in_executor`` /
``asyncio.to_thread`` / ``Thread(target=...)`` — running the helper on
another thread is the sanctioned fix) and at functions declared
``@thread_affinity`` for a non-loop domain.

Direct frames (the blocking call lexically inside ``async def``) are
DL001's and are not re-reported here; findings come with the call
chain that makes them believable.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.rules.common import (
    BLOCKING_CALLS,
    dotted_name,
    walk_in_scope,
)
from dynamo_tpu.analysis.taint import format_chain


@program_rule(
    "transitive-blocking-call-in-async",
    "DL101",
    "blocking call in a sync function reachable from a coroutine "
    "(stalls the event loop from one or more call levels down)",
)
def check(program: LintProgram):
    graph = program.graph
    for qn, chain in program.taints.async_ctx.items():
        fn = graph.functions.get(qn)
        if fn is None or fn.is_async:
            continue  # direct async frames are DL001's
        if len(chain) < 2:
            continue
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            hint = BLOCKING_CALLS.get(name or "")
            if hint is None:
                continue
            depth = len(chain) - 1
            yield (
                fn.path,
                node,
                f"`{name}(...)` blocks the event loop {depth} call "
                f"level(s) below coroutine `{chain[0].split(':')[-1]}` "
                f"(chain: {format_chain(chain)}); use {hint} or run "
                "the helper in an executor",
            )
