"""DL102 transitive-host-sync-in-step-loop: a device->host sync in a
helper the engine step loop reaches through ordinary calls.

DL010 guards the step loop's *own* frames (entry points named in
config ``step-loop-functions``, anything named ``*step_loop*``, and
their nested closures). But a `.item()` buried in a utility the loop
calls re-serializes the overlapped decode pipeline just as surely —
the host parks mid-plan, the device drains, and the idle gap the
pipeline exists to remove comes back invisibly (docs/performance.md).

This rule closes that gap: it flags the DL010 sync-op set inside any
function carrying the *step-loop* taint at depth >= 1 (reachable from
an entry point along same-context edges). Harvest-named functions are
the sanctioned sync points: they neither receive nor forward the
taint, so the designated harvest and everything only it calls stay
exempt — same convention as DL010, which keeps direct frames; together
the two rules subsume the old single-frame view.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.rules.common import (
    SYNC_ATTRS,
    SYNC_CALLS,
    dotted_name,
    walk_in_scope,
)
from dynamo_tpu.analysis.taint import format_chain


@program_rule(
    "transitive-host-sync-in-step-loop",
    "DL102",
    "device sync in a helper reachable from the engine step loop "
    "(re-serializes the overlapped pipeline from a call level down)",
)
def check(program: LintProgram):
    graph = program.graph
    for qn, chain in program.taints.step_loop.items():
        if len(chain) < 2:
            continue  # entry points' own frames are DL010's
        fn = graph.functions.get(qn)
        if fn is None or "harvest" in fn.name.lower():
            continue
        for node in walk_in_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name in SYNC_CALLS:
                what = f"`{name}(...)`"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_ATTRS
            ):
                what = f"`.{node.func.attr}()`"
            else:
                continue
            yield (
                fn.path,
                node,
                f"{what} syncs device->host {len(chain) - 1} call "
                f"level(s) below step-loop entry "
                f"`{chain[0].split(':')[-1]}` (chain: "
                f"{format_chain(chain)}); move the materialization to "
                "the designated harvest function",
            )
