"""DL007 unbounded-telemetry-buffer: an in-memory telemetry buffer that
only ever grows.

Telemetry state (histories, step records, event/sample buffers, span
rings) lives for the PROCESS lifetime and is appended to on hot paths —
an append with no ``maxlen``/trim is a slow memory leak that surfaces
as an OOM days into a serving run, exactly when the buffer was supposed
to help debug. The flight recorder (telemetry/recorder.py) and planner
history show the two sanctioned shapes:

    self.ring = deque(maxlen=256)          # bounded by construction
    self.history.append(snap)
    del self.history[:-600]                # explicit trim

The rule fires on growth sites (``.append``/``.extend``/
``.appendleft``/``+=``) of instance attributes that (a) are initialized
as a plain ``[]`` or ``deque()`` *without* ``maxlen``, (b) have a
telemetry-ish name (history/record/buffer/event/sample/trace/span/
metric/timing/latency/outcome/measurement/dump/log/ring/step), and
(c) are never bounded anywhere in the class (``del x[...]``, slice
assignment, ``.pop()``/``.popleft()``/``.clear()``, or re-assignment
outside the initializing statement all count as bounding).
"""

from __future__ import annotations

import ast
from typing import Optional

from dynamo_tpu.analysis.registry import LintModule, rule

BUFFERISH = (
    "history", "record", "buffer", "buf", "event", "sample", "trace",
    "span", "metric", "timing", "latenc", "outcome", "measurement",
    "dump", "log", "ring", "step",
)
GROW_METHODS = {"append", "extend", "appendleft", "extendleft", "insert"}
BOUND_METHODS = {"pop", "popleft", "popitem", "clear"}


def _is_bufferish(name: str) -> bool:
    low = name.lower()
    return any(k in low for k in BUFFERISH)


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.NAME`` -> "NAME" (None otherwise)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _unbounded_buffer_ctor(value: ast.AST) -> bool:
    """True for ``[]`` / ``list()`` / ``deque()`` without maxlen."""
    if isinstance(value, ast.List) and not value.elts:
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else ""
        )
        if name == "list" and not value.args and not value.keywords:
            return True
        if name == "deque":
            has_maxlen = any(k.arg == "maxlen" for k in value.keywords) or (
                len(value.args) >= 2
            )
            return not has_maxlen
    return False


class _ClassScan(ast.NodeVisitor):
    """One class body: buffer inits, growth sites, bounding ops.
    Nested classes scan separately (visit_ClassDef stops descent)."""

    def __init__(self) -> None:
        self.inits: dict[str, ast.AST] = {}  # attr -> init stmt node
        self.grows: list[tuple[str, ast.AST]] = []
        self.bounded: set[str] = set()
        self.assign_counts: dict[str, int] = {}

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        return  # nested class: scanned on its own

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._note_assign(tgt, node.value, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._note_assign(node.target, node.value, node)
        self.generic_visit(node)

    def _note_assign(self, tgt: ast.AST, value: ast.AST, stmt: ast.AST) -> None:
        if isinstance(tgt, ast.Subscript):
            # slice/item assignment bounds (`x[:] = x[-n:]`)
            attr = _self_attr(tgt.value)
            if attr:
                self.bounded.add(attr)
            return
        attr = _self_attr(tgt)
        if attr is None:
            return
        self.assign_counts[attr] = self.assign_counts.get(attr, 0) + 1
        if _unbounded_buffer_ctor(value):
            self.inits.setdefault(attr, stmt)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                attr = _self_attr(tgt.value)
                if attr:
                    self.bounded.add(attr)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target)
        if attr is not None and isinstance(node.op, ast.Add):
            self.grows.append((attr, node))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            attr = _self_attr(node.func.value)
            if attr is not None:
                if node.func.attr in GROW_METHODS:
                    self.grows.append((attr, node))
                elif node.func.attr in BOUND_METHODS:
                    self.bounded.add(attr)
        self.generic_visit(node)


@rule(
    "unbounded-telemetry-buffer",
    "DL007",
    "telemetry buffer appended without maxlen/trim (grows for the "
    "process lifetime)",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        scan = _ClassScan()
        for stmt in node.body:
            scan.visit(stmt)
        for attr, site in scan.grows:
            if attr not in scan.inits or not _is_bufferish(attr):
                continue
            if attr in scan.bounded:
                continue
            if scan.assign_counts.get(attr, 0) > 1:
                # re-assigned elsewhere (e.g. snapshot-and-reset): the
                # buffer has a lifecycle, not unbounded growth
                continue
            findings.append(
                (
                    site,
                    f"`self.{attr}` grows without a bound — telemetry "
                    "buffers live for the process lifetime; use "
                    "deque(maxlen=N) or trim after appending "
                    "(`del self." + attr + "[:-N]`)",
                )
            )
    return findings
