"""DL012 unclosed-span: a started span must end on EVERY path.

Spans (telemetry/spans.py) export at ``end()``; a span that never ends
silently vanishes from the trace — the request *looks* untraced exactly
when something went wrong enough to take an early exit, which is when
the span mattered. The sanctioned shapes are:

- ``with tracer.span(...)`` / ``with span:`` — ``__exit__`` ends it,
  exception or not;
- ``span = tracer.span(...)`` followed by ``span.end()`` inside a
  ``finally:`` block;
- straight-line ``span.end()`` with no ``return``/``raise``/``break``/
  ``continue`` between start and end.

Flagged: a span-start result bound to a name whose ``end()`` is only
reachable conditionally (inside an ``if``/loop/``except`` arm), or
never called, or separated from the start by an early exit. A span that
*escapes* the function — returned, yielded, stored on an object, passed
to another call — is someone else's lifecycle and is not flagged
(``propagation_context(span, ...)`` hand-offs stay clean).

Span-start detection is name-based (the linter sees one file at a
time): calls to an attribute named ``span``/``start_span``, or
``start`` on a receiver whose name mentions spans/tracers
(``spans.start(...)``, ``self._tracer.start(...)``).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Tuple

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import dotted_name

# var uses that neither close nor leak the span
_NEUTRAL_METHODS = {"set_attr", "trace_context", "to_dict"}
_CONDITIONAL_ANCESTORS = (
    ast.If, ast.While, ast.For, ast.AsyncFor, ast.ExceptHandler,
    ast.IfExp,
)
_EARLY_EXITS = (ast.Return, ast.Raise, ast.Break, ast.Continue)


def _is_span_start(call: ast.Call) -> bool:
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return False
    if fn.attr in ("span", "start_span"):
        return True
    if fn.attr == "start":
        recv = dotted_name(fn.value) or ""
        last = recv.rsplit(".", 1)[-1].lower()
        return "span" in last or "tracer" in last
    return False


def _parent_map(root: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _ancestors(node: ast.AST, parents: dict, stop: ast.AST) -> list[ast.AST]:
    out = []
    cur = parents.get(node)
    while cur is not None and cur is not stop:
        out.append(cur)
        cur = parents.get(cur)
    return out


def _in_finally(node: ast.AST, parents: dict, stop: ast.AST) -> bool:
    cur, prev = parents.get(node), node
    while cur is not None and prev is not stop:
        if isinstance(cur, ast.Try) and any(
            prev is s or _contains(s, prev) for s in cur.finalbody
        ):
            return True
        prev, cur = cur, parents.get(cur)
    return False


def _contains(tree: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(tree))


def _stmt_of(node: ast.AST, parents: dict, fn: ast.AST) -> Optional[ast.stmt]:
    """The direct-child statement of ``fn``'s body chain holding node."""
    cur = node
    while cur is not None and parents.get(cur) is not fn:
        cur = parents.get(cur)
    return cur if isinstance(cur, ast.stmt) else None


def _check_function(fn) -> Iterable[Tuple[ast.AST, str]]:
    parents = _parent_map(fn)
    # span vars started in THIS function; starts inside nested defs are
    # skipped here (the module walk hands every def to _check_function,
    # so nested lifecycles scope apart)
    assigns: list[tuple[str, ast.Assign]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        if isinstance(node.value, ast.Call) and _is_span_start(node.value):
            # skip starts inside nested defs: their enclosing function
            # is checked separately
            if any(
                isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                for a in _ancestors(node, parents, fn)
            ):
                continue
            assigns.append((tgt.id, node))
    for var, assign in assigns:
        ends: list[ast.AST] = []
        end_in_finally = False
        end_unconditional: Optional[ast.AST] = None
        closed_by_with = False
        escapes = False
        rebound = False
        for node in ast.walk(fn):
            if node is assign.targets[0]:
                continue
            if isinstance(node, ast.Name) and node.id == var:
                if isinstance(node.ctx, ast.Store):
                    if parents.get(node) is not assign:
                        rebound = True  # reassigned: stop tracking
                    continue
                parent = parents.get(node)
                anc = _ancestors(node, parents, fn)
                if any(
                    isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                    for a in anc
                ):
                    escapes = True  # captured by a closure
                    continue
                if isinstance(parent, ast.withitem) and parent.context_expr is node:
                    closed_by_with = True
                    continue
                if isinstance(parent, ast.Attribute):
                    call = parents.get(parent)
                    is_call = (
                        isinstance(call, ast.Call) and call.func is parent
                    )
                    if parent.attr == "end" and is_call:
                        ends.append(node)
                        if _in_finally(node, parents, fn):
                            end_in_finally = True
                        elif not any(
                            isinstance(a, _CONDITIONAL_ANCESTORS)
                            for a in anc
                        ):
                            end_unconditional = node
                        continue
                    if parent.attr in _NEUTRAL_METHODS or not is_call:
                        continue  # set_attr / attribute read: neutral
                    escapes = True
                    continue
                if isinstance(parent, (ast.BoolOp, ast.UnaryOp, ast.Compare)):
                    continue  # truthiness tests are neutral
                if isinstance(parent, (ast.If, ast.While)) and getattr(
                    parent, "test", None
                ) is node:
                    continue
                # call argument, return/yield value, container element,
                # attribute/subscript store target... — the span leaves
                # this function's custody
                escapes = True
        if closed_by_with or end_in_finally or escapes or rebound:
            continue
        if not ends:
            yield (
                assign,
                f"span {var!r} is started but never ended (and never "
                f"used as a context manager) — it will not export; "
                f"use `with`, or end() in a finally:",
            )
            continue
        if end_unconditional is None:
            yield (
                assign,
                f"span {var!r} only ends on some paths (every end() is "
                f"inside a conditional branch) — an early exit leaks "
                f"it; move end() to a finally: or use `with`",
            )
            continue
        # straight-line end: flag early exits between start and end
        a_stmt = _stmt_of(assign, parents, fn)
        e_stmt = _stmt_of(end_unconditional, parents, fn)
        if a_stmt is None or e_stmt is None:
            continue
        # the end must live in the same statement list as the start for
        # the straight-line scan to mean anything
        holder = None
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(fn, field, None)
            if stmts and a_stmt in stmts:
                holder = stmts
        if holder is None or e_stmt not in holder:
            continue
        between = holder[holder.index(a_stmt) + 1 : holder.index(e_stmt)]
        for stmt in between:
            exits = [
                n for n in ast.walk(stmt) if isinstance(n, _EARLY_EXITS)
            ]
            if exits:
                yield (
                    exits[0],
                    f"path between {var!r}'s start and its end() can "
                    f"exit early here — the span leaks on that path; "
                    f"wrap in try/finally or use `with`",
                )
                break


@rule(
    "unclosed-span",
    "DL012",
    "span started but not ended on every path (leaks from traces on "
    "early exits); use `with` or end() in a finally",
)
def check(module: LintModule) -> Iterable[Tuple[ast.AST, str]]:
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from _check_function(node)
