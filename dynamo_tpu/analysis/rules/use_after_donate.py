"""DL201 use-after-donate: reading a buffer after it was passed in a
``donate_argnums`` position.

``jax.jit(..., donate_argnums=...)`` lets XLA alias an input buffer
into an output — the KV caches update in place instead of doubling HBM.
The contract is invisible to Python: after the dispatch the donated
array object still *looks* alive, but its buffer is gone; touching it
raises (TPU) or silently reads garbage (some backends/interpret mode).
The engine's sanctioned pattern is the **swap idiom** — rebind the
donated names from the call's outputs before anything else reads them:

    self.k_cache, self.v_cache = step_fn(params, self.k_cache,
                                         self.v_cache, ...)
    # or, equivalently, via an intermediate:
    out = step_fn(params, self.k_cache, self.v_cache, ...)
    self.k_cache, self.v_cache = out[-2], out[-1]

This rule runs a statement-ordered dataflow over every project
function: an argument in a donated position (a bare name, a
``self.attr``, or a subscript's base; ``*tuple``-packed argument lists
are expanded through same-frame tuple literals) is *poisoned* by the
call and stays poisoned until an assignment rebinds it.  Reads of a
poisoned value are findings.  Branches are analyzed independently and
merged conservatively (poisoned-in-either stays poisoned); loop bodies
get a second pass so loop-carried poison is seen.

Two escalations close the gaps a single frame can't see:

- **one-level inter-procedural**: a call to an ordinary function whose
  own body passes the corresponding parameter into a donated slot
  (``scatter_blocks`` -> ``_scatter``) poisons the caller's argument
  too — the message prints the ``wrapper -> jit`` hop;
- **attribute carryover** (the dispatch/harvest split): a ``self.``
  attribute donated and *never rebound in the same function* is
  reported at the donating call — the next frame to read it (the
  harvest half, the next step's dispatch) sees a freed buffer, and no
  intra-frame analysis there can know it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dynamo_tpu.analysis import jaxsem
from dynamo_tpu.analysis.program import LintProgram, program_rule
from dynamo_tpu.analysis.rules.common import dotted_name


class _PoisonInfo:
    __slots__ = ("label", "lineno", "node", "via")

    def __init__(self, label: str, lineno: int, node: ast.AST, via: str):
        self.label = label  # the donating callable, for the message
        self.lineno = lineno
        self.node = node  # the donating call (anchor for carryover)
        self.via = via  # "" or "wrapper -> jit" hop text


class _FunctionScan:
    def __init__(self, program: LintProgram, fn) -> None:
        self.program = program
        self.graph = program.graph
        self.inv = jaxsem.inventory_of(program)
        self.fn = fn
        self.findings: List[Tuple[ast.AST, str]] = []
        self.local_tuples: Dict[str, ast.Tuple] = {}
        self._reported: Set[Tuple[int, str]] = set()
        self._carryover: Dict[str, _PoisonInfo] = {}

    # -- entry -----------------------------------------------------------
    def run(self) -> List[Tuple[ast.AST, str]]:
        env: Dict[str, _PoisonInfo] = {}
        self._exec_body(self.fn.node.body, env)
        # attribute carryover: donated self-state never rebound here
        for key, info in env.items():
            if not key.startswith(("self.", "cls.")):
                continue
            if (info.lineno, "carry:" + key) in self._reported:
                continue
            self._reported.add((info.lineno, "carry:" + key))
            self.findings.append(
                (
                    info.node,
                    f"`{key}` is passed in a donated position of jitted "
                    f"`{info.label}`{info.via} but never rebound in this "
                    "function — the buffer is freed at dispatch, and the "
                    "next frame to read the attribute (the harvest half, "
                    "the next step) gets a deleted buffer; rebind with "
                    "the swap idiom `a, b = step_fn(a, b, ...)`",
                )
            )
        return self.findings

    # -- statement walk ---------------------------------------------------
    def _exec_body(self, body: List[ast.stmt], env: Dict) -> bool:
        """Process statements in order; True when the body *terminates*
        (return/raise/break/continue on every path) — a terminating
        branch's poison never reaches the fall-through code."""
        for stmt in body:
            if self._exec_stmt(stmt, env):
                return True
        return False

    def _exec_stmt(self, stmt: ast.stmt, env: Dict) -> bool:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return False  # nested frames analyze themselves
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._exec_assign(stmt, env)
        elif isinstance(stmt, ast.If):
            self._reads(stmt.test, env)
            then_env, else_env = dict(env), dict(env)
            then_ends = self._exec_body(stmt.body, then_env)
            else_ends = self._exec_body(stmt.orelse, else_env)
            env.clear()
            if not else_ends:
                env.update(else_env)
            if not then_ends:
                env.update(then_env)  # poisoned-in-either stays poisoned
            if then_ends and else_ends:
                return True
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._reads(stmt.iter, env)
            self._unbind(stmt.target, env)
            # two passes: the second sees poison carried around the
            # back edge (donate late in the body, read early next turn)
            self._exec_body(stmt.body, env)
            self._unbind(stmt.target, env)
            self._exec_body(stmt.body, env)
            self._exec_body(stmt.orelse, env)
        elif isinstance(stmt, ast.While):
            self._reads(stmt.test, env)
            self._exec_body(stmt.body, env)
            self._reads(stmt.test, env)
            self._exec_body(stmt.body, env)
            self._exec_body(stmt.orelse, env)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._reads(item.context_expr, env)
                if item.optional_vars is not None:
                    self._unbind(item.optional_vars, env)
            self._exec_body(stmt.body, env)
        elif isinstance(stmt, ast.Try):
            self._exec_body(stmt.body, env)
            for h in stmt.handlers:
                self._exec_body(h.body, env)
            self._exec_body(stmt.orelse, env)
            self._exec_body(stmt.finalbody, env)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._unbind(t, env)
        elif isinstance(stmt, (ast.Return, ast.Expr, ast.Raise,
                               ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                self._reads(child, env)
                self._poison_calls(child, env)
            if isinstance(stmt, (ast.Return, ast.Raise)):
                return True
        elif isinstance(stmt, (ast.Break, ast.Continue)):
            return True  # nothing after it in THIS body executes
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._reads(child, env)
        return False

    def _exec_assign(self, stmt: ast.stmt, env: Dict) -> None:
        if isinstance(stmt, ast.AugAssign):
            # x += ... reads the (possibly poisoned) target first
            self._reads(stmt.target, env)
            self._reads(stmt.value, env)
            self._poison_calls(stmt.value, env)
            self._unbind(stmt.target, env)
            return
        value = stmt.value
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        if value is not None:
            self._reads(value, env)
            self._poison_calls(value, env)
            # remember same-frame tuple packs for *args expansion
            if (
                isinstance(stmt, ast.Assign)
                and isinstance(value, ast.Tuple)
                and len(targets) == 1
                and isinstance(targets[0], ast.Name)
            ):
                self.local_tuples[targets[0].id] = value
        for t in targets:
            self._unbind(t, env)

    # -- reads / poison ----------------------------------------------------
    def _reads(self, expr: ast.AST, env: Dict) -> None:
        """Flag loads of poisoned keys anywhere under ``expr`` (nested
        function definitions excluded: closures run later, usually
        after the rebind — the walk prunes their whole subtree, which
        ``ast.walk`` cannot)."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # subtree pruned: the closure body never scans
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            key = dotted_name(node)
            if key is None or key not in env:
                continue
            # attribute chains report once, at the outermost match
            info = env.pop(key)
            if (node.lineno, key) in self._reported:
                continue
            self._reported.add((node.lineno, key))
            self.findings.append(
                (
                    node,
                    f"`{key}` was donated to jitted `{info.label}`"
                    f"{info.via} on line {info.lineno} and is read here "
                    "before being rebound — the donated buffer no longer "
                    "exists after dispatch; rebind it from the call's "
                    "outputs first (`a, b = step_fn(a, b, ...)`)",
                )
            )

    def _poison_calls(self, expr: ast.AST, env: Dict) -> None:
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # a closure's calls run later, not here
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            flows = jaxsem.donated_flows(
                self.inv, self.graph, self.fn, node
            )
            if flows is None:
                continue
            label, by_index = flows
            site = jaxsem.resolve_call_site(
                self.inv, self.graph, self.fn, node
            )
            via = ""
            if site is None or not site.donate:
                # one-level wrapper: show the hop
                first = next(iter(by_index.values()))
                via = f" (via `{label}` -> `{first.label}`)"
            args = jaxsem.effective_positional(node, self.local_tuples)
            for i in by_index:
                if i >= len(args) or args[i] is None:
                    continue
                key = jaxsem.value_key(args[i])
                if key is None:
                    continue
                env[key] = _PoisonInfo(label, node.lineno, node, via)

    def _unbind(self, target: ast.AST, env: Dict) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._unbind(el, env)
            return
        if isinstance(target, ast.Starred):
            self._unbind(target.value, env)
            return
        key = jaxsem.value_key(target)
        if key is not None:
            env.pop(key, None)


@program_rule(
    "use-after-donate",
    "DL201",
    "a buffer read after being passed in a jit donate_argnums position "
    "(freed at dispatch; rebind via the swap idiom first)",
)
def check(program: LintProgram):
    for fn in program.graph.functions.values():
        scan = _FunctionScan(program, fn)
        for node, message in scan.run():
            yield fn.path, node, message
