"""DL009 wall-clock-in-control-loop: code that HAS an injectable clock
must not bypass it inside its loops.

The planner, the admission token bucket, retry backoff, and the fleet
simulator all take an injectable ``Clock`` (``utils/clock.py``) so
control policy is testable on virtual time — a million simulated
requests, zero real sleeps, bit-identical replays. One stray
``time.monotonic()`` or ``asyncio.sleep()`` inside such a loop silently
splits the timeline: half the loop runs on simulated seconds, half on
wall seconds, and the simulation (or the test) drifts in ways that only
show up as flakes.

The rule is structural, not path-based: a function is "clock-bearing"
when it takes a ``clock`` parameter or belongs to a class that stores
one (``self.clock`` / ``self._clock`` assignment, or a ``clock``
parameter on any of its methods). Inside a clock-bearing function,
direct calls to ``time.monotonic`` / ``time.time`` / ``time.sleep`` /
``asyncio.sleep`` within any ``while``/``for`` loop body are flagged —
route them through the clock (``self.clock.monotonic()``,
``await self.clock.sleep(...)``) instead. Straight-line code (setup,
one-shot stamps) is not flagged; loops are where timelines diverge.
"""

from __future__ import annotations

import ast

from dynamo_tpu.analysis.registry import LintModule, rule
from dynamo_tpu.analysis.rules.common import dotted_name

WALL_CLOCK_CALLS = {
    "time.monotonic",
    "time.time",
    "time.sleep",
    "asyncio.sleep",
}


def _class_bears_clock(cls: ast.ClassDef) -> bool:
    """self.clock/self._clock assigned anywhere, or any method takes a
    ``clock`` parameter."""
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and t.attr in ("clock", "_clock")
                ):
                    return True
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            names = [
                a.arg
                for a in (
                    args.posonlyargs + args.args + args.kwonlyargs
                )
            ]
            if "clock" in names:
                return True
    return False


def _fn_bears_clock(fn) -> bool:
    args = fn.args
    return "clock" in [
        a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)
    ]


class _LoopScan(ast.NodeVisitor):
    """Wall-clock calls inside one loop body; nested defs scope apart
    (their loops are scanned when the walker reaches them)."""

    def __init__(self) -> None:
        self.hits: list[tuple[ast.AST, str]] = []

    def visit_FunctionDef(self, node) -> None:
        return

    def visit_AsyncFunctionDef(self, node) -> None:
        return

    def visit_While(self, node) -> None:
        return  # inner loops are scanned as their own entries

    def visit_For(self, node) -> None:
        return

    def visit_AsyncFor(self, node) -> None:
        return

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        if name in WALL_CLOCK_CALLS:
            self.hits.append((node, name))
        self.generic_visit(node)


@rule(
    "wall-clock-in-control-loop",
    "DL009",
    "loop in clock-injectable code calls time.*/asyncio.sleep directly, "
    "bypassing the injectable Clock (breaks simulation/driven mode)",
)
def check(module: LintModule):
    findings: list[tuple[ast.AST, str]] = []

    def own_loops(fn) -> list[ast.AST]:
        """Loops belonging to ``fn`` itself — nested defs scope apart
        (they're scanned on their own bearing status)."""
        loops: list[ast.AST] = []

        def walk(node) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    continue
                if isinstance(child, (ast.While, ast.For, ast.AsyncFor)):
                    loops.append(child)
                walk(child)

        walk(fn)
        return loops

    def scan_function(fn, bearing: bool) -> None:
        if not (bearing or _fn_bears_clock(fn)):
            return
        for node in own_loops(fn):
            scan = _LoopScan()
            # the loop's own repeated expressions first: a while
            # condition (`while time.monotonic() < deadline:`) or a for
            # iterable re-evaluates every iteration and splits the
            # timeline exactly like a call in the body would
            if isinstance(node, ast.While):
                scan.visit(node.test)
            else:
                scan.visit(node.iter)
            for stmt in node.body + node.orelse:
                scan.visit(stmt)
            for site, name in scan.hits:
                findings.append(
                    (
                        site,
                        f"`{name}(...)` inside a loop of clock-injectable "
                        "code — route time through the injectable Clock "
                        "(self.clock.monotonic() / await "
                        "self.clock.sleep(...)) so driven/simulated runs "
                        "stay on one timeline",
                    )
                )

    # direct methods inherit their class's clock-bearing status …
    direct_methods: set[ast.AST] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            bearing = _class_bears_clock(node)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    direct_methods.add(item)
                    scan_function(item, bearing)

    # … every other function — module level, nested in a function, OR
    # nested inside a method — is scoped on its own clock parameter
    for node in ast.walk(module.tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node not in direct_methods
        ):
            scan_function(node, bearing=False)
    return findings
