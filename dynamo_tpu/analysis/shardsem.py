"""Sharding-semantics layer for dynalint: the shard-site inventory the
DL3xx rules share.

ROADMAP item 1 moves serving onto real meshes (TP×PP×DP), and the mesh
code is exactly where Python can't help: a ``shard_map`` body is traced
once per shard, its collectives name mesh axes as *strings*, and its
in/out ``PartitionSpec``\\ s are checked against the wrapped function
only at trace time — on a multi-host pod, often only at deploy time.
The contracts the DL3xx rules enforce:

- a **host sync inside a shard body** serializes every device in the
  mesh, not one (DL301);
- a collective's ``axis_name`` must be among the enclosing shard
  site's **declared axes** (DL302);
- **donating** a buffer whose sharding differs from the jit site's
  declared sharding inserts a resharding copy that silently defeats
  the donation, and donating from inside a shard body frees per-shard
  views the other shards still alias (DL303);
- literal ``in_specs``/``out_specs`` must match the wrapped function's
  **arity** and the declared **axis set** (DL304).

This module builds, once per program pass, the inventory those rules
check against: every ``shard_map`` (native, ``jax.experimental``, or
the ``utils/jaxtools.py`` compat shim), ``pjit``/sharded-``jit``, and
``with_sharding_constraint`` site inside a function body, with

- the **wrapped callable** resolved to a call-graph qualname where
  possible (nested closures included — the house style wraps a local
  ``def``);
- the declared **manual axis set**: a literal ``axis_names=`` set, the
  complement of a literal ``auto=`` set against a statically-known
  mesh, or *all mesh axes* when neither is given (fully-manual
  shard_map);
- literal ``in_specs``/``out_specs`` parsed to per-argument
  PartitionSpec shapes, resolving ``P(...)`` bound to frame locals and
  module-level constants;
- per-function maps of ``x = with_sharding_constraint(x, P(...))``
  bindings, and jit/pjit sites that combine ``donate_argnums`` with
  literal ``in_shardings`` (the DL303 comparison endpoints).

Anything dynamic — a computed axis tuple, a spec built in a helper, a
mesh only a caller knows — degrades to a **counted miss** (the
``dynamic`` tally surfaced by ``--stats``), never a guessed value: the
jaxsem discipline, because a wrong axis index would make every DL3xx
finding suspect.

The inventory and the body-reachability map are memoized on the
:class:`LintProgram` instance so the four rules share one build.
Cache correctness is free: this file lives in the analysis package,
whose source bytes are folded into the rule-set signature
(``cache._package_hash``).
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from dynamo_tpu.analysis.astutil import dotted_name, walk_in_scope
from dynamo_tpu.analysis.callgraph import (
    SAME_CONTEXT,
    CallGraph,
    FunctionInfo,
    resolve_name,
)
from dynamo_tpu.analysis.jaxsem import _argnums, _resolves_to

# a spec/axis construct the parser could not reduce to literals —
# recorded as a counted miss, never guessed at
DYNAMIC = "<dynamic>"

_SHARD_MAP = (
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "dynamo_tpu.utils.jaxtools.shard_map",
)
_PJIT = ("jax.experimental.pjit.pjit", "jax.pjit")
_JIT = ("jax.jit",)
_CONSTRAINT = (
    "jax.lax.with_sharding_constraint",
    "jax.experimental.pjit.with_sharding_constraint",
)
_PSPEC = (
    "jax.sharding.PartitionSpec",
    "jax.experimental.pjit.PartitionSpec",
)
_MESH = ("jax.sharding.Mesh", "jax.experimental.maps.Mesh")

# collective -> positional index of its axis-name argument
COLLECTIVES: Dict[str, int] = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
    "jax.lax.axis_index": 0,
    "jax.lax.pcast": 1,
    "jax.lax.pbroadcast": 1,
    "jax.lax.pvary": 1,
    "dynamo_tpu.utils.jaxtools.pcast": 1,
}


def _matches(imports: Dict[str, str], name: str, targets) -> bool:
    return any(_resolves_to(imports, name, t) for t in targets)


def collective_axis_arg(
    imports: Dict[str, str], call: ast.Call
) -> Optional[Tuple[str, Optional[ast.AST]]]:
    """(collective name, axis-argument expression) when ``call`` is a
    recognized mesh collective, else None.  The axis expression is None
    when the call omits it (defaults to the enclosing binder)."""
    name = dotted_name(call.func)
    if name is None:
        return None
    for full, pos in COLLECTIVES.items():
        if _resolves_to(imports, name, full):
            axis: Optional[ast.AST] = None
            if len(call.args) > pos:
                axis = call.args[pos]
            for k in call.keywords:
                if k.arg in ("axis_name", "axis_names", "axis_index_groups"):
                    if k.arg != "axis_index_groups":
                        axis = k.value
            return full.rsplit(".", 1)[-1], axis
    return None


def parse_axis_set(node: Optional[ast.AST]) -> Optional[FrozenSet[str]]:
    """``{"pp"}`` / ``("ep", "tp")`` / ``"tp"`` / ``frozenset({...})``
    literal -> frozenset of axis names; None when dynamic."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
            else:
                return None
        return frozenset(out)
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in ("frozenset", "set", "tuple") and len(node.args) == 1:
            return parse_axis_set(node.args[0])
    return None


# -- PartitionSpec parsing -------------------------------------------------


def _spec_entry(node: ast.AST):
    """One P(...) argument: None | "axis" | ("a", "b") | DYNAMIC."""
    if isinstance(node, ast.Constant):
        if node.value is None or isinstance(node.value, str):
            return node.value
        return DYNAMIC
    if isinstance(node, (ast.Tuple, ast.List)):
        sub = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                sub.append(el.value)
            else:
                return DYNAMIC
        return tuple(sub)
    return DYNAMIC


def parse_partition_spec(
    node: ast.AST, imports: Dict[str, str]
) -> Optional[Tuple]:
    """``P("dp", None, ("ep", "tp"))`` -> parsed entry tuple; None when
    ``node`` is not a recognizable PartitionSpec constructor."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None or not _matches(imports, name, _PSPEC):
        return None
    return tuple(_spec_entry(a) for a in node.args)


def spec_axes(spec: Optional[Tuple]) -> FrozenSet[str]:
    """Literal axis names a parsed spec mentions (DYNAMIC entries
    contribute nothing — only what we can read gets checked)."""
    out = set()
    for entry in spec or ():
        if isinstance(entry, str) and entry != DYNAMIC:
            out.add(entry)
        elif isinstance(entry, tuple):
            out.update(entry)
    return frozenset(out)


# -- sites -----------------------------------------------------------------


@dataclass
class ShardSite:
    """One shard_map / sharded-jit / with_sharding_constraint site."""

    key: str  # "owner-qualname::<lineno>"
    path: str
    lineno: int
    kind: str  # "shard_map" | "jit-sharded" | "constraint"
    owner: str  # qualname of the function containing the site
    wrapped: Optional[str] = None  # wrapped callable's qualname
    axes: Optional[FrozenSet[str]] = None  # declared manual axes
    all_manual: bool = False  # no axis_names=: every mesh axis is manual
    mesh_axes: Optional[FrozenSet[str]] = None
    # literal tuple forms only; entries are parsed specs or DYNAMIC
    in_specs: Optional[Tuple] = None
    out_specs: Optional[Tuple] = None
    donate: Tuple[int, ...] = ()
    in_shardings: Optional[Tuple] = None
    spec_axes: FrozenSet[str] = frozenset()  # axes the specs mention
    dynamic: int = 0  # constructs that degraded to a counted miss
    node: Optional[ast.AST] = None  # the site call (finding anchor)

    @property
    def label(self) -> str:
        if self.wrapped:
            return self.wrapped.rsplit(".", 1)[-1].rsplit(":", 1)[-1]
        return f"{self.kind}@{self.lineno}"

    def declared_axes(self) -> Optional[FrozenSet[str]]:
        """The axis names collectives inside this site's body may use;
        None when not statically known (fully-manual with an opaque
        mesh, or a dynamic axis_names= value)."""
        if self.axes is not None:
            return self.axes
        if self.all_manual:
            return self.mesh_axes  # all of them — when we know them
        return None


@dataclass
class ShardInventory:
    sites: List[ShardSite] = field(default_factory=list)
    # wrapped-body qualname -> shard_map site (first site wins)
    body_sites: Dict[str, ShardSite] = field(default_factory=dict)
    # fn qualname -> {local name -> constrained spec} from
    # ``x = with_sharding_constraint(x, P(...))`` bindings
    constraints: Dict[str, Dict[str, Tuple]] = field(default_factory=dict)
    # donate+in_shardings jit/pjit sites, by binding
    jit_by_local: Dict[Tuple[str, str], ShardSite] = field(
        default_factory=dict
    )
    jit_by_qualname: Dict[str, ShardSite] = field(default_factory=dict)

    def stats(self) -> Dict[str, int]:
        kinds = {"shard_map": 0, "jit-sharded": 0, "constraint": 0}
        for s in self.sites:
            kinds[s.kind] = kinds.get(s.kind, 0) + 1
        return {
            "shard_map_sites": kinds["shard_map"],
            "jit_sharded_sites": kinds["jit-sharded"],
            "constraint_sites": kinds["constraint"],
            "resolved_bodies": len(self.body_sites),
            "dynamic_misses": sum(s.dynamic for s in self.sites),
        }


# -- build -----------------------------------------------------------------


def _module_consts(tree: ast.Module) -> Dict[str, ast.AST]:
    """Top-level ``NAME = <expr>`` bindings (module constants like the
    pipeline's ``_PP_ONLY_CACHE_SPEC``)."""
    out: Dict[str, ast.AST] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                out[t.id] = stmt.value
    return out


def _frame_resolver(
    fn: FunctionInfo, consts: Dict[str, ast.AST]
) -> Callable[[str], Optional[ast.AST]]:
    """name -> the expression assigned to it in this frame (last
    assignment wins) or at module top level."""
    local: Dict[str, ast.AST] = {}
    mutated = set()
    for node in walk_in_scope(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                # a rebound name is ambiguous — refuse, don't guess
                if t.id in local:
                    mutated.add(t.id)
                local[t.id] = node.value
        elif isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Name
        ):
            # `specs += (...)`: the literal we saw is not the value
            # the site receives (llama's conditional scale specs)
            mutated.add(node.target.id)

    def resolve(name: str) -> Optional[ast.AST]:
        if name in mutated:
            return None
        return local.get(name, consts.get(name))

    return resolve


def _deref(
    expr: ast.AST, resolver: Callable[[str], Optional[ast.AST]]
) -> ast.AST:
    """Follow Name bindings a few hops so ``spec = P(...)`` and
    ``mesh = Mesh(...)`` locals resolve to their constructors."""
    for _ in range(4):
        if not isinstance(expr, ast.Name):
            break
        nxt = resolver(expr.id)
        if nxt is None or nxt is expr:
            break
        expr = nxt
    return expr


def _mesh_axes(
    expr: Optional[ast.AST],
    resolver: Callable[[str], Optional[ast.AST]],
    imports: Dict[str, str],
) -> Optional[FrozenSet[str]]:
    """Axis names of a ``Mesh(devices, ("dp", "tp"))`` constructor the
    site's mesh= argument resolves to; None when the mesh is opaque
    (a parameter, a method call — the common case)."""
    if expr is None:
        return None
    expr = _deref(expr, resolver)
    if not isinstance(expr, ast.Call):
        return None
    name = dotted_name(expr.func)
    if name is None or not _matches(imports, name, _MESH):
        return None
    cand: Optional[ast.AST] = None
    if len(expr.args) > 1:
        cand = expr.args[1]
    for k in expr.keywords:
        if k.arg == "axis_names":
            cand = k.value
    return parse_axis_set(cand)


def _specs_field(
    node: Optional[ast.AST],
    resolver: Callable[[str], Optional[ast.AST]],
    imports: Dict[str, str],
) -> Tuple[Optional[Tuple], FrozenSet[str], int]:
    """Parse an ``in_specs=``/``out_specs=`` value.

    Returns ``(literal_tuple, axes_mentioned, dynamic_misses)``:
    ``literal_tuple`` is the per-argument parse (entries: parsed spec
    or DYNAMIC) when the value is a literal Tuple/List — the only form
    whose arity is checkable — else None.  A single bare spec still
    contributes its axes; anything else is a counted miss."""
    if node is None:
        return None, frozenset(), 0
    node = _deref(node, resolver)
    misses = 0
    axes: set = set()
    if isinstance(node, (ast.Tuple, ast.List)):
        entries = []
        for el in node.elts:
            spec = parse_partition_spec(_deref(el, resolver), imports)
            if spec is None:
                entries.append(DYNAMIC)
                misses += 1
            else:
                entries.append(spec)
                axes.update(spec_axes(spec))
                if DYNAMIC in spec:
                    misses += 1
        return tuple(entries), frozenset(axes), misses
    spec = parse_partition_spec(_deref(node, resolver), imports)
    if spec is None:
        return None, frozenset(), 1
    return None, spec_axes(spec), (1 if DYNAMIC in spec else 0)


def _resolve_wrapped(
    graph: CallGraph, fn: FunctionInfo, expr: Optional[ast.AST]
) -> Optional[str]:
    if expr is None or isinstance(expr, ast.Lambda):
        return None
    name = dotted_name(expr)
    if name is None:
        return None
    return resolve_name(graph, fn, name)


def _shard_map_site(
    call: ast.Call,
    fn: FunctionInfo,
    graph: CallGraph,
    resolver,
    imports: Dict[str, str],
) -> ShardSite:
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    dynamic = 0

    wrapped_expr = call.args[0] if call.args else kw.get("f")
    wrapped = _resolve_wrapped(graph, fn, wrapped_expr)
    if wrapped_expr is not None and wrapped is None:
        dynamic += 1

    mesh_axes = _mesh_axes(kw.get("mesh"), resolver, imports)

    axes: Optional[FrozenSet[str]] = None
    all_manual = False
    ax_node = kw.get("axis_names")
    auto_node = kw.get("auto")
    if ax_node is not None and not (
        isinstance(ax_node, ast.Constant) and ax_node.value is None
    ):
        axes = parse_axis_set(ax_node)
        if axes is None:
            dynamic += 1
    elif auto_node is not None:
        auto = parse_axis_set(auto_node)
        if auto is not None and mesh_axes is not None:
            axes = mesh_axes - auto
        else:
            dynamic += 1
    else:
        all_manual = True

    in_specs, in_axes, m_in = _specs_field(
        kw.get("in_specs"), resolver, imports
    )
    out_specs, out_axes, m_out = _specs_field(
        kw.get("out_specs"), resolver, imports
    )
    dynamic += m_in + m_out

    return ShardSite(
        key=f"{fn.qualname}::{call.lineno}",
        path=fn.path,
        lineno=call.lineno,
        node=call,
        kind="shard_map",
        owner=fn.qualname,
        wrapped=wrapped,
        axes=axes,
        all_manual=all_manual,
        mesh_axes=mesh_axes,
        in_specs=in_specs,
        out_specs=out_specs,
        spec_axes=in_axes | out_axes,
        dynamic=dynamic,
    )


def _jit_sharded_site(
    call: ast.Call,
    fn: FunctionInfo,
    graph: CallGraph,
    resolver,
    imports: Dict[str, str],
) -> Optional[ShardSite]:
    """A ``pjit``/``jax.jit`` call that declares ``in_shardings`` (the
    DL303 comparison endpoint); None when it declares no shardings."""
    name = dotted_name(call.func)
    if name is None or not _matches(imports, name, _PJIT + _JIT):
        return None
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    if "in_shardings" not in kw:
        return None
    in_shardings, _, misses = _specs_field(
        kw.get("in_shardings"), resolver, imports
    )
    return ShardSite(
        key=f"{fn.qualname}::{call.lineno}",
        path=fn.path,
        lineno=call.lineno,
        node=call,
        kind="jit-sharded",
        owner=fn.qualname,
        wrapped=_resolve_wrapped(
            graph, fn, call.args[0] if call.args else None
        ),
        donate=_argnums(kw.get("donate_argnums")),
        in_shardings=in_shardings,
        dynamic=misses,
    )


def build_inventory(program) -> ShardInventory:
    inv = ShardInventory()
    graph: CallGraph = program.graph
    consts_by_path: Dict[str, Dict[str, ast.AST]] = {}
    for path, mod in program.modules.items():
        consts_by_path[path] = _module_consts(mod.tree)

    for qn, fn in graph.functions.items():
        imports = graph.imports.get(fn.module, {})
        consts = consts_by_path.get(fn.path, {})
        resolver = _frame_resolver(fn, consts)

        # decorator-form sharded jit (`@pjit(... in_shardings=...)`)
        for deco in getattr(fn.node, "decorator_list", []):
            if isinstance(deco, ast.Call):
                site = _jit_sharded_site(deco, fn, graph, resolver, imports)
                if site is not None:
                    site.wrapped = qn
                    inv.sites.append(site)
                    inv.jit_by_qualname[qn] = site

        for node in walk_in_scope(fn.node):
            if isinstance(node, ast.Assign):
                val = node.value
                if not isinstance(val, ast.Call):
                    continue
                vname = dotted_name(val.func) or ""
                if _matches(imports, vname, _CONSTRAINT):
                    # x = with_sharding_constraint(x, P(...)) binding
                    if len(node.targets) == 1 and isinstance(
                        node.targets[0], ast.Name
                    ) and len(val.args) >= 2:
                        spec = parse_partition_spec(
                            _deref(val.args[1], resolver), imports
                        )
                        if spec is not None:
                            inv.constraints.setdefault(qn, {})[
                                node.targets[0].id
                            ] = spec
                else:
                    site = _jit_sharded_site(
                        val, fn, graph, resolver, imports
                    )
                    if site is not None:
                        inv.sites.append(site)
                        if site.wrapped:
                            inv.jit_by_qualname.setdefault(
                                site.wrapped, site
                            )
                        for t in node.targets:
                            tn = dotted_name(t)
                            if tn and "." not in tn:
                                inv.jit_by_local[(qn, tn)] = site
                continue
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            if _matches(imports, name, _SHARD_MAP):
                site = _shard_map_site(node, fn, graph, resolver, imports)
                inv.sites.append(site)
                if site.wrapped:
                    inv.body_sites.setdefault(site.wrapped, site)
            elif _matches(imports, name, _CONSTRAINT):
                inv.sites.append(
                    ShardSite(
                        key=f"{qn}::{node.lineno}",
                        path=fn.path,
                        lineno=node.lineno,
                        node=node,
                        kind="constraint",
                        owner=qn,
                    )
                )
    return inv


def inventory_of(program) -> ShardInventory:
    """The program's shard-site inventory, built once and memoized on
    the LintProgram instance (the four DL3xx rules share it)."""
    inv = getattr(program, "_shardsem_inventory", None)
    if inv is None:
        inv = build_inventory(program)
        program._shardsem_inventory = inv
    return inv


# -- body reachability -----------------------------------------------------


def in_closure_tree(root: str, qualname: str) -> bool:
    return qualname == root or qualname.startswith(root + ".<locals>.")


def body_reach(program) -> Dict[str, List[Tuple[ShardSite, List[str]]]]:
    """fn qualname -> [(shard site whose body reaches it, call chain
    from the wrapped body root)].  The wrapped function and its nested
    closures are depth 0; ordinary same-context calls extend the
    chain — what executes *per shard, inside the trace*.  Memoized
    alongside the inventory."""
    reach = getattr(program, "_shardsem_reach", None)
    if reach is not None:
        return reach
    inv = inventory_of(program)
    graph: CallGraph = program.graph
    reach = {}
    for root, site in sorted(inv.body_sites.items()):
        seen: Dict[str, List[str]] = {root: [root]}
        work = deque([root])
        # seed the closure tree: nested defs belong to the body frame
        for qn in graph.functions:
            if in_closure_tree(root, qn) and qn not in seen:
                seen[qn] = [root, qn] if qn != root else [root]
                work.append(qn)
        while work:
            cur = work.popleft()
            for e in graph.out_edges(cur):
                if e.kind not in SAME_CONTEXT or e.callee in seen:
                    continue
                if e.callee not in graph.functions:
                    continue
                seen[e.callee] = seen[cur] + [e.callee]
                work.append(e.callee)
        for qn, chain in seen.items():
            reach.setdefault(qn, []).append((site, chain))
    program._shardsem_reach = reach
    return reach
