"""Dataflow over the call graph: three taints the DL1xx rules consume.

Each taint is a property of a *function* ("code reachable from X runs
in context Y"), propagated along same-context call/ref edges of the
:class:`~dynamo_tpu.analysis.callgraph.CallGraph` with a worklist BFS.
Every tainted function remembers the shortest call chain that tainted
it, so a finding two levels deep can print the path a human needs to
believe it.

- **async-context** — reachable from a coroutine body without crossing
  a thread handoff: a blocking call anywhere in this set stalls the
  event loop (DL101). Seeds: every ``async def``. Propagation stops at
  spawn edges (``run_in_executor`` / ``to_thread`` / ``Thread(target=
  ...)`` — the callee runs elsewhere, blocking there is the *fix*) and
  at functions explicitly declared ``@thread_affinity`` for a
  non-"loop" domain (a declared engine/planner function reached from
  async code is a different bug — DL103's).

- **step-loop** — reachable from the engine step loop's entry points
  (config ``step-loop-functions`` + any function whose name contains
  ``step_loop``): a device->host sync anywhere in this set
  re-serializes the overlapped decode pipeline (DL102). Propagation
  stops at harvest-named functions (the sanctioned sync points, same
  convention as DL010) and spawn edges.

- **thread-affinity** — which domain's thread executes this function:
  seeded from ``@thread_affinity("engine"|"loop"|"planner")``
  declarations, config ``affinity-entry-points`` (``qualname=domain``),
  and every ``async def`` (coroutines run on the event loop).
  Propagates along same-context edges; spawn-to-loop edges
  (``call_soon_threadsafe`` / ``run_coroutine_threadsafe``) retarget
  the callee to "loop"; spawn-to-other edges stop propagation (a fresh
  thread is no declared domain). A function's own declaration always
  wins over anything propagated into it. Functions reachable from
  several domains carry the full set — shared code, judged by DL103 at
  its write sites.
"""

from __future__ import annotations

import fnmatch
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from dynamo_tpu.analysis.callgraph import (
    CallGraph,
    Edge,
    FunctionInfo,
    SAME_CONTEXT,
    SPAWN_LOOP,
)

LOOP_DOMAIN = "loop"


@dataclass
class Taints:
    """qualname -> shortest seeding chain (list of qualnames, seed
    first, tainted function last)."""

    async_ctx: Dict[str, List[str]] = field(default_factory=dict)
    step_loop: Dict[str, List[str]] = field(default_factory=dict)
    # qualname -> {domain -> chain}
    affinity: Dict[str, Dict[str, List[str]]] = field(default_factory=dict)

    def domains(self, qualname: str) -> Set[str]:
        return set(self.affinity.get(qualname, {}))


def _is_harvest(fn: FunctionInfo) -> bool:
    return "harvest" in fn.name.lower()


def _declared_affinity(graph: CallGraph, fn: FunctionInfo) -> Optional[str]:
    """@thread_affinity on the function, else on its class."""
    if fn.affinity:
        return fn.affinity
    if fn.cls is not None:
        cls = graph.classes.get(fn.cls)
        if cls is not None and cls.affinity:
            return cls.affinity
    return None


def _bfs(
    graph: CallGraph,
    seeds: Dict[str, List[str]],
    *,
    stop: Optional[callable] = None,
) -> Dict[str, List[str]]:
    """Propagate seeds along same-context edges; ``stop(fn)`` prunes a
    function from *receiving and forwarding* the taint (it keeps its
    own seed if it is one)."""
    out: Dict[str, List[str]] = dict(seeds)
    # FIFO worklist = true BFS: with first-writer-wins, the recorded
    # chain is genuinely the shortest — a LIFO here would print a
    # 5-deep path for a function also reachable at depth 1
    work = deque(seeds)
    while work:
        cur = work.popleft()
        chain = out[cur]
        for e in graph.out_edges(cur):
            if e.kind not in SAME_CONTEXT:
                continue
            callee = graph.functions.get(e.callee)
            if callee is None or e.callee in out:
                continue
            if stop is not None and stop(callee):
                continue
            out[e.callee] = chain + [e.callee]
            work.append(e.callee)
    return out


def compute_async_taint(graph: CallGraph) -> Dict[str, List[str]]:
    seeds = {
        qn: [qn]
        for qn, fn in graph.functions.items()
        if fn.is_async
    }

    def stop(fn: FunctionInfo) -> bool:
        decl = _declared_affinity(graph, fn)
        return decl is not None and decl != LOOP_DOMAIN

    return _bfs(graph, seeds, stop=stop)


def compute_step_loop_taint(
    graph: CallGraph, config: dict
) -> Dict[str, List[str]]:
    names = set(config.get("step-loop-functions", []))
    seeds: Dict[str, List[str]] = {}
    for qn, fn in graph.functions.items():
        if fn.name in names or "step_loop" in fn.name:
            if not _is_harvest(fn):
                seeds[qn] = [qn]
    return _bfs(graph, seeds, stop=_is_harvest)


def _entry_point_seeds(
    graph: CallGraph, config: dict
) -> List[Tuple[str, str]]:
    """config ``affinity-entry-points = ["pat=domain", ...]`` where pat
    is an fnmatch over qualnames (or a bare function name)."""
    out: List[Tuple[str, str]] = []
    for entry in config.get("affinity-entry-points", []):
        pat, _, domain = str(entry).partition("=")
        pat, domain = pat.strip(), domain.strip()
        if not pat or not domain:
            continue
        for qn, fn in graph.functions.items():
            if fn.name == pat or fnmatch.fnmatch(qn, pat):
                out.append((qn, domain))
    return out


def compute_affinity_taint(
    graph: CallGraph, config: dict
) -> Dict[str, Dict[str, List[str]]]:
    # declared functions are pinned: they hold exactly their declared
    # domain and nothing propagates in
    declared: Dict[str, str] = {}
    for qn, fn in graph.functions.items():
        d = _declared_affinity(graph, fn)
        if d is not None:
            declared[qn] = d
    for qn, domain in _entry_point_seeds(graph, config):
        declared.setdefault(qn, domain)

    result: Dict[str, Dict[str, List[str]]] = {}

    def add(qn: str, domain: str, chain: List[str]) -> bool:
        slot = result.setdefault(qn, {})
        if domain in slot:
            return False
        slot[domain] = chain
        return True

    work: deque[Tuple[str, str]] = deque()
    for qn, domain in declared.items():
        add(qn, domain, [qn])
        work.append((qn, domain))
    # coroutines run on the event loop (unless explicitly declared)
    for qn, fn in graph.functions.items():
        if fn.is_async and qn not in declared:
            if add(qn, LOOP_DOMAIN, [qn]):
                work.append((qn, LOOP_DOMAIN))

    while work:
        cur, domain = work.popleft()
        chain = result[cur][domain]
        for e in graph.out_edges(cur):
            callee = graph.functions.get(e.callee)
            if callee is None:
                continue
            if e.kind in SAME_CONTEXT:
                new_domain = domain
            elif e.kind == SPAWN_LOOP:
                new_domain = LOOP_DOMAIN
            else:  # spawn-other: a fresh/pool thread, no domain
                continue
            if e.callee in declared:
                continue  # declaration wins; no propagation in
            if add(e.callee, new_domain, chain + [e.callee]):
                work.append((e.callee, new_domain))
    return result


def compute_taints(graph: CallGraph, config: dict) -> Taints:
    return Taints(
        async_ctx=compute_async_taint(graph),
        step_loop=compute_step_loop_taint(graph, config),
        affinity=compute_affinity_taint(graph, config),
    )


def format_chain(chain: List[str]) -> str:
    """Human-readable call chain: short names with the seed marked."""
    def short(qn: str) -> str:
        mod, _, sym = qn.partition(":")
        return f"{mod.rsplit('.', 1)[-1]}.{sym}" if sym else qn

    return " -> ".join(short(q) for q in chain)
