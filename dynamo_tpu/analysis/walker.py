"""File walker: discover sources, run rules, apply suppressions.

Suppression syntax (scanned per physical line, flake8-noqa style):

    x = do_thing()  # dynalint: disable=blocking-call-in-async — one-shot CLI
    y = other()     # dynalint: disable=bare-except,await-while-locked — why
    # dynalint: disable-file=bare-except   (first 10 lines: whole file)

``disable=all`` waives every rule on that line. Findings anchored to the
first line of a multi-line statement honor a comment on that line.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import glob
import hashlib
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Optional

from dynamo_tpu.analysis.findings import Finding
from dynamo_tpu.analysis.registry import LintModule, Rule, all_rules

# rule names only: the match stops at whitespace that isn't around a
# comma, so a trailing justification ("... — why" or "... - why") can't
# be swallowed into the rule list
_RULE_LIST = r"([\w-]+(?:\s*,\s*[\w-]+)*)"
_SUPPRESS_RE = re.compile(r"#\s*dynalint:\s*disable=" + _RULE_LIST)
_SUPPRESS_FILE_RE = re.compile(r"#\s*dynalint:\s*disable-file=" + _RULE_LIST)
_FILE_SCOPE_LINES = 10  # disable-file must appear near the top


def _parse_rule_list(raw: str, known: set[str]) -> set[str]:
    """Comma-separated rule names; a token that isn't a known rule ends
    the list (it's justification prose: `disable=rule, kept for X`). The
    *first* token is kept even when unknown so a typo'd rule name is
    reported instead of silently waiving nothing."""
    names: set[str] = set()
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if part in known:
            names.add(part)
        else:
            if not names:
                names.add(part)  # leading typo: surfaced as bad-suppression
            break
    return names


def scan_suppressions(
    source: str, known: set[str]
) -> tuple[dict[int, set[str]], set[str], list[tuple[int, str]]]:
    """(line -> waived rule names, file-wide waived names, problems).

    Only real COMMENT tokens count — a directive quoted inside a string
    or docstring (e.g. documentation showing the syntax) must not waive
    anything, or any file could silently disable rules via prose.
    ``problems`` are directives that have no effect (misplaced
    disable-file), reported as findings so they fail loudly."""
    per_line: dict[int, set[str]] = {}
    per_file: set[str] = set()
    problems: list[tuple[int, str]] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return per_line, per_file, problems  # unparseable: DL000 reports it
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        i = tok.start[0]
        m = _SUPPRESS_RE.search(tok.string)
        if m:
            per_line.setdefault(i, set()).update(
                _parse_rule_list(m.group(1), known)
            )
        m = _SUPPRESS_FILE_RE.search(tok.string)
        if m:
            if i <= _FILE_SCOPE_LINES:
                per_file.update(_parse_rule_list(m.group(1), known))
            else:
                problems.append(
                    (
                        i,
                        "disable-file directive past line "
                        f"{_FILE_SCOPE_LINES} has no effect; move it to "
                        "the top of the file",
                    )
                )
    return per_line, per_file, problems


def _suppressed(finding: Finding, per_line: dict[int, set[str]],
                per_file: set[str]) -> bool:
    names = per_file | per_line.get(finding.line, set())
    return finding.rule in names or "all" in names


def known_rule_names() -> set[str]:
    """Every rule name suppressions may legitimately reference —
    per-file AND whole-program rules."""
    from dynamo_tpu.analysis.program import all_program_rules

    return (
        {r.name for r in all_rules()}
        | {r.name for r in all_program_rules()}
        | {"all"}
    )


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Iterable[Rule]] = None,
    config: Optional[dict] = None,
    *,
    tree: Optional[ast.Module] = None,
) -> list[Finding]:
    """Lint one source string with the per-file rules. Syntax errors
    surface as a pseudo-finding (code DL000) rather than crashing the
    walk. Pass ``tree`` to reuse an already-parsed AST (``lint_paths``
    does, so a cold run parses each file once, not twice). (Whole-
    program DL1xx rules need the project view — see ``lint_paths`` /
    ``lint_sources_program``.)"""
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            return [
                Finding(
                    rule="parse-error",
                    code="DL000",
                    path=path,
                    line=exc.lineno or 1,
                    col=(exc.offset or 1) - 1,
                    message=f"file does not parse: {exc.msg}",
                )
            ]
    config = config or {}
    module = LintModule(path=path, source=source, tree=tree, config=config)
    if rules is None:
        # config `disable` applies to every entry point (CLI, pytest
        # gate, API) — not just the CLI — or the gates would disagree
        disabled = set(config.get("disable", []))
        rules = [r for r in all_rules() if r.name not in disabled]
    # validated against the full registry, not the enabled subset, so
    # running one rule doesn't flag waivers that belong to the others
    known = known_rule_names()
    per_line, per_file, problems = scan_suppressions(source, known)
    findings: list[Finding] = []
    # an ineffective directive (misplaced disable-file) or a suppression
    # naming a rule that doesn't exist (typo) would otherwise waive
    # nothing *silently* — surface both as findings
    for line_no, message in problems:
        findings.append(
            Finding(
                rule="bad-suppression",
                code="DL000",
                path=path,
                line=line_no,
                col=0,
                message=message,
            )
        )
    suppression_sites = [(1, per_file)] if per_file else []
    suppression_sites += sorted(per_line.items(), key=lambda kv: kv[0])
    for line_no, names in suppression_sites:
        for name in sorted(names - known):
            findings.append(
                Finding(
                    rule="bad-suppression",
                    code="DL000",
                    path=path,
                    line=line_no,
                    col=0,
                    message=f"suppression names unknown rule {name!r} "
                    "and waives nothing (typo?)",
                )
            )
    for r in rules:
        for node, message in r.check(module):
            f = Finding(
                rule=r.name,
                code=r.code,
                path=path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0),
                message=message,
            )
            if _suppressed(f, per_line, per_file):
                f = dataclasses.replace(f, suppressed=True)
            findings.append(f)
    return findings


def _excluded(path_str: str, exclude: list[str]) -> bool:
    """True when any exclude pattern matches the path. Patterns are
    directory prefixes ("dynamo_tpu/native") or fnmatch globs; matching
    is segment-aligned and works for absolute and relative paths alike."""
    posix = path_str.replace("\\", "/")
    wrapped = "/" + posix.strip("/") + "/"
    for pat in exclude:
        pat = pat.strip("/")
        if "/" + pat + "/" in wrapped:
            return True
        if (
            fnmatch.fnmatch(posix, pat)
            or fnmatch.fnmatch(posix, "*/" + pat)
            or fnmatch.fnmatch(posix, pat + "/*")
            or fnmatch.fnmatch(posix, "*/" + pat + "/*")
        ):
            return True
    return False


def iter_files(
    paths: Iterable[str], exclude: Optional[list[str]] = None
) -> list[Path]:
    """Expand files/directories/globs into a sorted .py file list."""
    exclude = exclude or []
    out: set[Path] = set()
    expanded: list[str] = []
    for p in paths:
        # include entries may be globs ("dynamo_tpu/*"); a literal path
        # with no glob chars passes through untouched
        if any(ch in str(p) for ch in "*?["):
            expanded.extend(glob.glob(str(p), recursive=True))
        else:
            expanded.append(str(p))
    for p in expanded:
        root = Path(p)
        if root.is_file():
            if root.suffix == ".py" and not _excluded(str(root), exclude):
                out.add(root)
        elif root.is_dir():
            for f in root.rglob("*.py"):
                if not _excluded(str(f), exclude):
                    out.add(f)
    return sorted(out)


def _program_findings(
    modules: dict[str, LintModule],
    prog_rules: list,
    config: dict,
    stats_out: Optional[dict] = None,
) -> list[Finding]:
    """Run the whole-program rules over parsed modules, applying the
    same per-line/per-file suppression machinery as the file pass."""
    from dynamo_tpu.analysis.program import build_program

    if not modules or not prog_rules:
        return []
    program = build_program(modules, config)
    if stats_out is not None:
        from dynamo_tpu.analysis import shardsem

        stats_out["callgraph"] = program.graph.stats()
        stats_out["shardsem"] = shardsem.inventory_of(program).stats()
    known = known_rule_names()
    suppression_cache: dict[str, tuple] = {}
    findings: list[Finding] = []
    for r in prog_rules:
        for path, node, message in r.check(program):
            f = Finding(
                rule=r.name,
                code=r.code,
                path=path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0),
                message=message,
            )
            if path not in suppression_cache:
                mod = modules.get(path)
                suppression_cache[path] = (
                    scan_suppressions(mod.source, known)[:2]
                    if mod
                    else ({}, set())
                )
            per_line, per_file = suppression_cache[path]
            if _suppressed(f, per_line, per_file):
                f = dataclasses.replace(f, suppressed=True)
            findings.append(f)
    return findings


def lint_sources_program(
    sources: dict[str, str],
    rules: Optional[list] = None,
    config: Optional[dict] = None,
) -> list[Finding]:
    """Whole-program lint over in-memory sources ({path: source}) —
    the fixture/test entry point for the DL1xx rules."""
    from dynamo_tpu.analysis.program import all_program_rules

    config = config or {}
    if rules is None:
        disabled = set(config.get("disable", []))
        rules = [
            r for r in all_program_rules() if r.name not in disabled
        ]
    modules: dict[str, LintModule] = {}
    for path, source in sources.items():
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue  # DL000 belongs to the per-file pass
        modules[path] = LintModule(
            path=path, source=source, tree=tree, config=config
        )
    return _program_findings(modules, list(rules), config)


def lint_paths(
    paths: Iterable[str],
    rules: Optional[Iterable[Rule]] = None,
    config: Optional[dict] = None,
    files: Optional[list[Path]] = None,
    *,
    program_rules: Optional[list] = None,
    cache=None,
    stats_out: Optional[dict] = None,
) -> list[Finding]:
    """Lint every .py file under ``paths`` (honoring config excludes):
    the per-file rules, then the whole-program DL1xx pass.

    Pass ``files`` to reuse an already-computed ``iter_files`` walk.
    ``rules``/``program_rules`` restrict each pass; an explicit
    ``rules`` selection alone also turns the program pass off (asking
    for one rule means that rule, not that rule plus DL1xx).
    ``cache`` is an ``analysis.cache.LintCache``: per-file results key
    on each file's sha, the program result keys on every sha, so a
    warm unchanged repo lints without parsing a single file.
    """
    from dynamo_tpu.analysis.cache import LintCache, rule_signature
    from dynamo_tpu.analysis.program import all_program_rules

    config = config or {}
    disabled = set(config.get("disable", []))
    file_rules = (
        list(rules)
        if rules is not None
        else [r for r in all_rules() if r.name not in disabled]
    )
    if program_rules is not None:
        prog_rules = list(program_rules)
    elif rules is not None:
        prog_rules = []
    else:
        prog_rules = [
            r for r in all_program_rules() if r.name not in disabled
        ]
    findings: list[Finding] = []
    if files is None:
        files = iter_files(paths, exclude=list(config.get("exclude", [])))

    file_sig = prog_sig = None
    if cache is not None:
        file_sig = rule_signature([r.name for r in file_rules], config)
        prog_sig = rule_signature(
            ["program"] + [r.name for r in prog_rules], config
        )

    shas: dict[str, str] = {}
    sources: dict[str, str] = {}
    trees: dict[str, ast.Module] = {}  # parsed once, shared by passes
    pending_file_keys: dict[str, str] = {}  # path -> cache key
    for f in files:
        path = str(f)
        try:
            raw = f.read_bytes()
        except OSError as exc:
            findings.append(
                Finding(
                    rule="read-error",
                    code="DL000",
                    path=path,
                    line=1,
                    col=0,
                    message=f"unreadable: {exc}",
                )
            )
            continue
        source = raw.decode("utf-8", errors="replace")
        sources[path] = source
        if cache is not None:
            sha = hashlib.sha256(raw).hexdigest()
            shas[path] = sha
            key = LintCache.file_key(path, sha, file_sig)
            cached = cache.get(key)
            if cached is not None:
                findings.extend(cached)
                continue
            pending_file_keys[path] = key
        try:
            trees[path] = ast.parse(source, filename=path)
        except SyntaxError:
            pass  # lint_source below emits the DL000
        file_findings = lint_source(
            source, path=path, rules=file_rules, config=config,
            tree=trees.get(path),
        )
        findings.extend(file_findings)
        if cache is not None:
            cache.put(pending_file_keys[path], file_findings)

    # -- whole-program pass ----------------------------------------------
    if prog_rules and sources:
        prog_key = None
        if cache is not None and len(shas) == len(sources):
            prog_key = LintCache.program_key(shas, prog_sig)
            cached = cache.get(prog_key)
            if cached is not None:
                if stats_out is not None:
                    stats_out["callgraph"] = "cached"
                findings.extend(cached)
                cache.save()
                return findings
        modules: dict[str, LintModule] = {}
        for path, source in sources.items():
            tree = trees.get(path)
            if tree is None:  # cache-hit or syntax-error file
                try:
                    tree = ast.parse(source, filename=path)
                except SyntaxError:
                    continue  # already a DL000 finding from the file pass
            modules[path] = LintModule(
                path=path, source=source, tree=tree, config=config
            )
        prog_findings = _program_findings(
            modules, prog_rules, config, stats_out=stats_out
        )
        findings.extend(prog_findings)
        if cache is not None and prog_key is not None:
            cache.put(prog_key, prog_findings)
    if cache is not None:
        cache.save()
    return findings
