"""Backend post-processing: incremental detokenize + stop triggers.

Analogue of the reference's Backend operator (reference:
lib/llm/src/backend.rs:63-496 — Decoder/DecodeStream wrapping, StopTrigger
for hidden/visible stop tokens and max-token limits, and the "jail" that
holds back text while it partially matches a stop string).

Sits between the preprocessor and the engine/router: forward passes the
``PreprocessedRequest`` through; backward maps the engine's token-delta
stream into a text-delta stream, terminating it the moment a stop
condition fires (and telling the engine to stop via the context).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.pipeline import Operator
from dynamo_tpu.tokenizer import DecodeStream, Tokenizer


def _longest_partial_suffix(text: str, stops: list[str]) -> int:
    """Length of the longest suffix of ``text`` that is a proper prefix of
    any stop string — the portion that must stay jailed."""
    best = 0
    for stop in stops:
        max_k = min(len(text), len(stop) - 1)
        for k in range(max_k, 0, -1):
            if text.endswith(stop[:k]):
                best = max(best, k)
                break
    return best


@dataclass
class SequenceState:
    """Per-request detok/stop state (≈ reference backend.rs SeqResult)."""

    decode: DecodeStream
    stop_strings: list[str]
    hidden_stop_ids: set[int]
    max_tokens: Optional[int]
    min_tokens: Optional[int]
    jailed: str = ""
    completion_tokens: int = 0
    finish: Optional[FinishReason] = None
    # logprobs requested: emit token chunks even when their text is
    # empty (e.g. a final token decoding to "" before a stop fires) so
    # every generated token's logprob entry reaches the client
    want_logprobs: bool = False

    def step(self, token_ids: list[int]) -> tuple[str, Optional[FinishReason]]:
        """Feed engine token deltas; returns (text_to_emit, finish_reason)."""
        out_parts: list[str] = []
        for tid in token_ids:
            if self.finish is not None:
                break
            self.completion_tokens += 1
            past_min = (
                self.min_tokens is None or self.completion_tokens >= self.min_tokens
            )
            if tid in self.hidden_stop_ids and past_min:
                # hidden stop (eos): stop now, do not emit its text
                self.finish = FinishReason.STOP
                break
            text = self.decode.step(tid)
            if text:
                emit, fin = self._apply_stop_strings(text, past_min)
                if emit:
                    out_parts.append(emit)
                if fin is not None:
                    self.finish = fin
                    break
            if self.max_tokens is not None and self.completion_tokens >= self.max_tokens:
                self.finish = FinishReason.LENGTH
                break
        return "".join(out_parts), self.finish

    def _apply_stop_strings(
        self, new_text: str, past_min: bool
    ) -> tuple[str, Optional[FinishReason]]:
        if not self.stop_strings:
            return new_text, None
        pending = self.jailed + new_text
        if past_min:
            # cut at the earliest occurrence across all stop strings
            hits = [i for s in self.stop_strings if (i := pending.find(s)) != -1]
            if hits:
                emit = pending[: min(hits)]
                self.jailed = ""
                return emit, FinishReason.STOP
        # jail the longest tail that could still become a stop string
        hold = _longest_partial_suffix(pending, self.stop_strings)
        emit = pending[: len(pending) - hold] if hold else pending
        self.jailed = pending[len(pending) - hold :] if hold else ""
        return emit, None

    def flush(self) -> str:
        """Release any jailed text at end-of-stream (no stop matched)."""
        out, self.jailed = self.jailed, ""
        return out


class Backend(Operator):
    """Token-stream → text-stream operator."""

    def __init__(self, tokenizer: Tokenizer, eos_token_ids: Optional[list[int]] = None):
        self.tokenizer = tokenizer
        self.eos_token_ids = set(eos_token_ids or [])

    async def forward(
        self, request: PreprocessedRequest, context: Context
    ) -> tuple[PreprocessedRequest, SequenceState]:
        stop = request.stop.apply_ignore_eos()
        hidden = set(stop.stop_token_ids_hidden)
        if not stop.ignore_eos:
            hidden |= self.eos_token_ids
        state = SequenceState(
            decode=self.tokenizer.decode_stream(
                skip_special_tokens=request.output.skip_special_tokens
            ),
            stop_strings=list(stop.stop),
            hidden_stop_ids=hidden,
            max_tokens=stop.max_tokens,
            min_tokens=stop.min_tokens,
            want_logprobs=request.output.logprobs is not None,
        )
        return request, state

    async def backward(
        self,
        stream: AsyncIterator[Any],
        state: SequenceState,
        context: Context,
    ) -> AsyncIterator[LLMEngineOutput]:
        async for raw in stream:
            item = (
                raw
                if isinstance(raw, LLMEngineOutput)
                else LLMEngineOutput.model_validate(raw)
            )
            before = state.completion_tokens
            text, finish = state.step(item.token_ids)
            # fused multi-step decode delivers multi-token bursts: when a
            # stop fires mid-burst, tokens past it (and a hidden stop
            # token itself) must not leak to token-stream consumers
            consumed = state.completion_tokens - before
            kept_ids = item.token_ids[:consumed]
            if finish is not None and kept_ids and kept_ids[-1] in state.hidden_stop_ids:
                kept_ids = kept_ids[:-1]
            kept_lps = (
                item.log_probs[: len(kept_ids)] if item.log_probs else item.log_probs
            )
            kept_tops = (
                item.top_logprobs[: len(kept_ids)]
                if item.top_logprobs
                else item.top_logprobs
            )
            if text or (state.want_logprobs and kept_ids) or (
                item.finish_reason is None and finish is None
            ):
                yield LLMEngineOutput(
                    request_id=item.request_id,
                    token_ids=kept_ids,
                    text=text,
                    cum_log_probs=item.cum_log_probs,
                    log_probs=kept_lps,
                    top_logprobs=kept_tops,
                    index=item.index,
                )
            if finish is not None:
                # our stop fired first: tell the engine to stop generating
                context.stop_generating()
                yield LLMEngineOutput(
                    request_id=item.request_id,
                    finish_reason=finish,
                    prompt_tokens=item.prompt_tokens,
                    completion_tokens=state.completion_tokens,
                )
                return
            if item.finish_reason is not None:
                # engine-side finish (e.g. its own length accounting)
                tail = state.flush()
                yield LLMEngineOutput(
                    request_id=item.request_id,
                    text=tail or None,
                    finish_reason=item.finish_reason,
                    prompt_tokens=item.prompt_tokens,
                    completion_tokens=state.completion_tokens,
                )
                return
        # stream ended without an explicit finish: treat as cancelled
        tail = state.flush()
        yield LLMEngineOutput(
            text=tail or None,
            finish_reason=FinishReason.CANCELLED,
            completion_tokens=state.completion_tokens,
        )
