"""``dynamo-tpu autopsy <rid>`` — render one request's timeline.

Fetches ``/debug/request/{rid}`` from a frontend (or worker metrics
server) and prints the record as an ASCII waterfall: each attributed
stage as a bar positioned on the request's wall-clock span, followed
by the router decisions, engine/prefill segments, and discrete events
(shed, fault firings, migration splice, …). The footer checks that
the attributed stages explain the request's wall time — a coverage
gap means a stage nobody instrumented, which is itself the finding.

With ``--json`` the raw record is printed instead (scriptable). When
the record carries a trace id the footer prints the matching
``dynamo-tpu trace export --rid`` invocation so the operator can jump
from the waterfall to the full Perfetto span tree.
"""

from __future__ import annotations

import json
import sys
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Optional, TextIO

from dynamo_tpu.telemetry.autopsy import waterfall

FETCH_TIMEOUT_S = 5.0
BAR_WIDTH = 40


def fetch_record(base_url: str, rid: str) -> tuple[Optional[dict], str]:
    """GET the record; returns (record, "") or (None, error-reason)."""
    url = base_url.rstrip("/") + "/debug/request/" + urllib.parse.quote(rid)
    try:
        with urllib.request.urlopen(url, timeout=FETCH_TIMEOUT_S) as resp:
            return json.loads(resp.read().decode()), ""
    except urllib.error.HTTPError as exc:
        if exc.code == 404:
            try:
                detail = json.loads(exc.read().decode()).get("error", "")
            except Exception:
                detail = ""
            return None, detail or f"no record for {rid!r}"
        return None, f"HTTP {exc.code} from {url}"
    except (urllib.error.URLError, OSError, ValueError) as exc:
        return None, f"cannot reach {url}: {exc}"


def _bar(start_ms: float, dur_ms: float, total_ms: float) -> str:
    """One waterfall lane: offset spaces, then a bar sized to share of
    the total. Zero-duration stages still get one visible tick."""
    if total_ms <= 0:
        return ""
    lo = int(round(BAR_WIDTH * max(0.0, start_ms) / total_ms))
    n = int(round(BAR_WIDTH * max(0.0, dur_ms) / total_ms))
    lo = min(lo, BAR_WIDTH - 1)
    n = max(1, min(n, BAR_WIDTH - lo))
    return " " * lo + "#" * n


def render(record: dict, out: TextIO) -> None:
    rid = record.get("rid", "?")
    flags = record.get("flags") or []
    out.write(f"request {rid}  endpoint={record.get('endpoint', '?')}  "
              f"status={record.get('status', '?')}"
              f"{'  [in-flight]' if not record.get('finished') else ''}\n")
    if flags:
        out.write(f"flags: {', '.join(flags)}  "
                  f"(retained: {record.get('retained', '?')})\n")
    wf = waterfall(record)
    total = wf["total_ms"]
    if wf["rows"]:
        name_w = max(len(r["name"]) for r in wf["rows"])
        out.write(f"\n{'stage':<{name_w}}  {'start':>10} {'dur':>10}\n")
        for r in wf["rows"]:
            out.write(
                f"{r['name']:<{name_w}}  {r['start_ms']:>8.1f}ms "
                f"{r['dur_ms']:>8.1f}ms |"
                f"{_bar(r['start_ms'], r['dur_ms'], total):<{BAR_WIDTH}}|\n"
            )
        mark = "OK" if wf["covered"] else "GAP"
        out.write(
            f"[{mark}] wall {total:.1f}ms, attributed "
            f"{wf['explained_ms']:.1f}ms "
            f"({wf['coverage'] * 100:.1f}% coverage)\n"
        )
    router = record.get("router") or []
    if router:
        out.write("\nrouter:\n")
        for d in router:
            bits = [f"worker={d.get('worker', '?')}",
                    f"mode={d.get('mode', '?')}"]
            if d.get("total_blocks"):
                bits.append(
                    f"overlap={d.get('overlap_blocks', 0)}/"
                    f"{d['total_blocks']} blocks"
                )
            if d.get("fleet_blocks"):
                bits.append(f"fleet={d['fleet_blocks']}")
            if d.get("resume"):
                bits.append("RESUME")
            out.write(f"  {' '.join(bits)}\n")
    segments = record.get("segments") or []
    if segments:
        out.write("\nsegments:\n")
        for s in segments:
            src = s.get("source", "?")
            rest = {k: v for k, v in s.items() if k != "source"}
            out.write(f"  [{src}] " + " ".join(
                f"{k}={json.dumps(v)}" for k, v in sorted(rest.items())
            ) + "\n")
    events = record.get("events") or []
    if events:
        out.write("\nevents:\n")
        for e in events:
            kind = e.get("kind", "?")
            t = e.get("t_ms")
            t_s = f"{t:>8.1f}ms" if isinstance(t, (int, float)) else "       --"
            rest = {k: v for k, v in e.items() if k not in ("kind", "t_ms")}
            out.write(f"  {t_s}  {kind}  " + " ".join(
                f"{k}={json.dumps(v)}" for k, v in sorted(rest.items())
            ) + "\n")
    trace_id = record.get("trace_id")
    if trace_id:
        out.write(
            f"\ntrace_id: {trace_id}\n"
            f"  spans: dynamo-tpu trace export <span-log.jsonl ...> "
            f"--rid {rid} -o trace.json\n"
        )
    out.flush()


def cmd_autopsy(args: Any) -> int:
    record, err = fetch_record(args.url, args.rid)
    if record is None:
        print(f"autopsy: {err}", file=sys.stderr)
        return 1
    if getattr(args, "json", False):
        print(json.dumps(record, indent=1))
        return 0
    render(record, sys.stdout)
    return 0
