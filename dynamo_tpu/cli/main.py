"""dynamo-tpu CLI: run/serve/store/models.

Analogue of the reference's launch binaries (reference:
launch/dynamo-run/src/{lib.rs:45-278, opt.rs:23-216, flags.rs:1-205} —
the in×out matrix; launch/llmctl — model registration ctl;
components/http — standalone frontend).

  dynamo-tpu run --in {http|text|stdin|batch:F|dyn://NS.COMP.EP} \
                 --out {echo_core|echo_full|jax|pystr:F|dyn://NS.COMP.EP|subproc:CMD} \
                 [--model-path DIR] [--model-name NAME] ...

  dynamo-tpu store            # run the coordinator (replaces etcd+NATS)
  dynamo-tpu models list      # ≈ llmctl
"""

from __future__ import annotations

import argparse
import asyncio
import atexit
import logging
import os
import sys
from typing import Any, Optional

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.logging import init_logging
from dynamo_tpu.utils.tasks import spawn

log = logging.getLogger("dynamo_tpu.cli")

DYN_SCHEME = "dyn://"


from dynamo_tpu.runtime.component import parse_dyn_path  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamo-tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run an input×output engine pairing")
    run.add_argument("--in", dest="in_mode", default="http",
                     help="http | text | stdin | batch:FILE.jsonl | "
                          "dyn://ns.comp.ep (serve as worker)")
    run.add_argument("--out", dest="out_mode", default="echo_full",
                     help="echo_core | echo_full | jax | pystr:FILE.py | "
                          "dyn://ns.comp.ep | subproc:CMD (spawn CMD as "
                          "a child engine that registers on a generated "
                          "{endpoint}; placeholders {endpoint} "
                          "{store_host} {store_port} {model_path} "
                          "{model_name} are substituted)")
    run.add_argument("--batch-output", default=None,
                     help="output path for --in batch: (default "
                          "INPUT.output.jsonl)")
    run.add_argument("--model-path", default=None,
                     help="local model directory (tokenizer/config/weights)")
    run.add_argument("--model-name", default=None)
    run.add_argument("--http-host", default="0.0.0.0")
    run.add_argument("--http-port", type=int, default=8000)
    run.add_argument("--store-host", default=None)
    run.add_argument("--store-port", type=int, default=None)
    run.add_argument("--static", action="store_true",
                     help="single-process mode: no coordinator needed")
    run.add_argument("--max-tokens-default", type=int, default=None)
    # engine knobs (reference: flags.rs)
    run.add_argument("--quantization", default=None, choices=["int8"],
                     help="weight-only quantization applied at load "
                          "(halves weight HBM traffic)")
    run.add_argument("--remote-kv-bucket", default="",
                     help="G4 KV tier: bucket in the coordinator's "
                          "object plane shared across workers "
                          "(requires --host-kv-blocks > 0)")
    run.add_argument("--decode-steps", type=int, default=1,
                     help="fused decode window: tokens per device "
                          "dispatch (amortizes dispatch latency; tokens "
                          "stream in bursts of this size)")
    run.add_argument("--spec-decode", default="",
                     help="speculative decoding drafter (needs "
                          "--decode-steps 1): ngram[:N] = prompt-lookup "
                          "self-drafting, bigram:PATH = static table; "
                          "empty disables (docs/speculative_decoding.md)")
    run.add_argument("--spec-tokens", type=int, default=4,
                     help="max draft tokens verified per sequence per "
                          "step (K); each decode step then emits 1..K+1 "
                          "tokens per sequence")
    run.add_argument("--prewarm-guided", action="store_true",
                     help="prewarm the guided-decoding (allow-mask) "
                          "step variants (needs --decode-steps 1): "
                          "keeps structured-output traffic free of "
                          "mid-serve compiles (docs/guided_decoding.md)")
    run.add_argument("--no-overlap", action="store_true",
                     help="disable the overlapped decode pipeline "
                          "(docs/performance.md): restores the fully "
                          "serial plan -> dispatch -> sync -> emit step "
                          "loop. Escape hatch + A/B baseline; greedy "
                          "output is bit-identical either way")
    run.add_argument("--mixed-prefill-rows", type=int, default=8,
                     help="mixed continuous batching (needs "
                          "--decode-steps > 1): pending prefill chunks "
                          "ride the decode window's dispatch in a fixed "
                          "[rows, len] rectangle; 0 disables")
    run.add_argument("--mixed-prefill-len", type=int, default=256,
                     help="per-row token cap of the mixed prefill "
                          "rectangle")
    run.add_argument("--mixed-prefill-wide-len", type=int, default=1024,
                     help="adaptive WIDE mixed rectangle: at low decode "
                          "occupancy the mixed window swaps to "
                          "[rows*len/wide_len, wide_len] (same token "
                          "budget, fewer rows) so long prompts stop "
                          "trickling at --mixed-prefill-len per window; "
                          "0 disables")
    run.add_argument("--mixed-wide-max-running", type=int, default=None,
                     help="decode-occupancy ceiling for the wide "
                          "rectangle (default: none — the wide and "
                          "narrow rectangles cost the same padded "
                          "budget, so the swap is free at any "
                          "occupancy when few prompts are prefilling)")
    run.add_argument("--tensor-parallel-size", type=int, default=1)
    run.add_argument("--pipeline-parallel-size", type=int, default=1,
                     help="GPipe stage rotation over a pp mesh axis")
    run.add_argument("--sequence-parallel-size", type=int, default=1,
                     help="prefill role only: shard the prompt over an "
                          "sp mesh axis (ring attention)")
    run.add_argument("--sp-attn", default="ring", choices=["ring", "ulysses"])
    # multimodal (vision-language) serving
    run.add_argument("--vision-config", default=None,
                     help="VisionConfig JSON: enables image_url content "
                          "parts (ViT encode + embedding injection)")
    run.add_argument("--vision-weights", default=None,
                     help=".npz vision tower weights (default: random)")
    run.add_argument("--image-token", default="<image>",
                     help="placeholder token for image patches")
    run.add_argument("--num-nodes", type=int, default=1)
    run.add_argument("--node-rank", type=int, default=0)
    run.add_argument("--leader-addr", default="")
    run.add_argument("--extra-engine-args", default=None,
                     help="JSON file with engine-specific settings")
    run.add_argument("--router-mode", default="round_robin",
                     choices=["random", "round_robin", "kv"])
    # disaggregated prefill/decode (reference: docs/disagg_serving.md)
    run.add_argument("--role", default="decode", choices=["decode", "prefill"],
                     help="worker role when disaggregation is enabled")
    run.add_argument("--disagg", action="store_true",
                     help="decode workers ship long prefills to the queue")
    run.add_argument("--namespace", default="dynamo",
                     help="namespace for prefill-role workers (no --in)")
    run.add_argument("--max-local-prefill-length", type=int, default=512)
    run.add_argument("--max-prefill-queue-size", type=int, default=16)
    run.add_argument("--advertise-host", default="127.0.0.1",
                     help="address prefill workers use to reach this "
                          "worker's KV transfer server")
    # robustness (docs/robustness.md: deadlines + load shedding; fault
    # injection is enabled via the DYN_FAULTS env var, never a flag)
    run.add_argument("--default-deadline-ms", type=float, default=None,
                     help="deadline budget applied to requests without "
                          "an X-Request-Timeout-Ms header; expired "
                          "requests are cancelled at every stage "
                          "(queue, prefill, decode) and their KV blocks "
                          "freed (default: no deadline)")
    run.add_argument("--shed-queue-depth", type=int, default=0,
                     help="admission control: reject requests 429 + "
                          "Retry-After when the engine's queue depth "
                          "(waiting + prefilling) reaches this "
                          "(--in http with a local engine; 0 disables)")
    run.add_argument("--shed-kv-usage", type=float, default=0.0,
                     help="admission control: shed when the device KV "
                          "pool usage fraction reaches this (e.g. 0.95; "
                          "0 disables)")
    run.add_argument("--drain-timeout-s", type=float, default=None,
                     help="graceful-drain budget for worker mode: on "
                          "SIGTERM (or a worker.drain control call) "
                          "in-flight streams are handed off to healthy "
                          "peers and the worker exits 0 once idle or "
                          "this deadline passes (default: "
                          "DYN_DRAIN_TIMEOUT_S, else 30)")
    # observability (docs/observability.md: SLO + flight recorder)
    run.add_argument("--slo-ttft-ms", type=float, default=None,
                     help="TTFT target evaluated per finished request "
                          "(engine-side submit -> first token); feeds "
                          "dynamo_slo_attainment / "
                          "dynamo_goodput_tokens_total")
    run.add_argument("--slo-itl-ms", type=float, default=None,
                     help="mean inter-token-latency target per request")
    run.add_argument("--slow-step-ms", type=float, default=None,
                     help="slow-step watchdog: a device step longer "
                          "than this dumps the flight-recorder ring to "
                          "JSONL (default: DYN_SLOW_STEP_MS, else off)")
    run.add_argument("--flight-recorder-steps", type=int, default=256,
                     help="flight-recorder ring capacity (last N engine "
                          "steps kept for /debug/state + anomaly dumps; "
                          "0 disables)")
    run.add_argument("--flight-dump-dir", default="",
                     help="where flight-recorder JSONL dumps land "
                          "(default: DYN_FLIGHT_DIR or the tmp dir)")
    # KV offload tiers
    run.add_argument("--subproc-ready-timeout", type=float, default=1800.0,
                     help="startup budget for --out subproc: children "
                          "(a real engine's AOT prewarm is minutes over "
                          "a chip tunnel)")
    run.add_argument("--host-kv-blocks", type=int, default=0)
    run.add_argument("--disk-kv-blocks", type=int, default=0)
    run.add_argument("--disk-kv-path", default="")

    store = sub.add_parser("store", help="run the coordinator store")
    store.add_argument("--host", default="0.0.0.0")
    store.add_argument("--port", type=int, default=4222)
    store.add_argument("--native", action="store_true",
                       help="run the C++ coordinator (native/store; built "
                            "on demand, wire-identical to the python one)")
    store.add_argument("--persist-path", default=None,
                       help="durability: WAL + snapshot at this path — "
                           "model registrations, queues, and the object "
                           "plane survive a coordinator restart, incl. a "
                           "hard kill (leased liveness keys stay "
                           "ephemeral, like etcd). Both servers append "
                           "each acked mutation to a flushed WAL "
                           "(process-crash durable; host/power-crash "
                           "durability needs --fsync-wal on the native "
                           "server) and fold it into snapshots")
    store.add_argument("--fsync-wal", action="store_true",
                       help="(--native) fsync every WAL record before "
                            "acking: power-loss durable, like etcd's "
                            "raft-log fsync, at per-op fsync cost")

    serve = sub.add_parser("serve", help="serve a @service graph "
                           "(≈ reference `dynamo serve`)")
    serve.add_argument("service", nargs="?", default=None,
                       help="module:Attr of the entry DynamoService")
    serve.add_argument("--package", default=None,
                       help="serve a pushed package instead: name[:version]")
    serve.add_argument("-f", "--config-file", default=None,
                       help="YAML/JSON per-component overrides")
    serve.add_argument("--store-host", default="127.0.0.1")
    serve.add_argument("--store-port", type=int, default=4222)

    build = sub.add_parser("build", help="package a @service graph into a "
                           "versioned artifact (≈ reference `dynamo build`)")
    build.add_argument("service", help="module:Attr of the entry DynamoService")
    build.add_argument("--name", default=None,
                       help="package name (default: entry attr, lowered)")
    build.add_argument("-f", "--config-file", default=None,
                       help="YAML per-component overrides to embed")
    build.add_argument("--deployment-spec", default=None,
                       help="GraphDeploymentSpec YAML to embed")
    build.add_argument("-o", "--output", default=None,
                       help="archive path (default NAME-VERSION.tar.gz)")
    build.add_argument("--push", action="store_true",
                       help="push to the coordinator's package registry")
    build.add_argument("--store-host", default="127.0.0.1")
    build.add_argument("--store-port", type=int, default=4222)

    router = sub.add_parser("router", help="standalone KV-aware router "
                            "service (≈ reference components/router)")
    router.add_argument("--namespace", default="dynamo")
    router.add_argument("--component", default="backend",
                        help="worker component to route over")
    router.add_argument("--router-component", default="kv_aware_router",
                        help="component name this service registers as")
    router.add_argument("--block-size", type=int, default=16)
    router.add_argument("--store-host", default="127.0.0.1")
    router.add_argument("--store-port", type=int, default=4222)

    metrics = sub.add_parser("metrics", help="metrics aggregation service")
    metrics.add_argument("--namespace", default="dynamo")
    metrics.add_argument("--component", default="backend")
    metrics.add_argument("--port", type=int, default=9091)
    metrics.add_argument("--store-host", default="127.0.0.1")
    metrics.add_argument("--store-port", type=int, default=4222)

    planner = sub.add_parser("planner", help="autoscaling planner")
    planner.add_argument("--namespace", default="dynamo")
    planner.add_argument("--component", default="backend")
    planner.add_argument("--prefill-component", default="prefill")
    planner.add_argument("--metric-interval", type=float, default=5.0)
    planner.add_argument("--adjustment-interval", type=float, default=30.0)
    planner.add_argument("--min-decode", type=int, default=1)
    planner.add_argument("--max-decode", type=int, default=8)
    planner.add_argument("--min-prefill", type=int, default=0)
    planner.add_argument("--max-prefill", type=int, default=8)
    planner.add_argument("--grace-cycles", type=int, default=2,
                         help="consecutive breach cycles before acting")
    planner.add_argument("--slo-target", type=float, default=0.0,
                         help="scale decode up when slo_attainment_mean "
                              "stays below this (0 = watermark-only)")
    planner.add_argument("--slo-headroom", type=float, default=0.03,
                         help="extra attainment above --slo-target "
                              "required before scaling down")
    planner.add_argument("--reconcile-cycles", type=int, default=3,
                         help="adjustment cycles a worker may go missing "
                              "before reconciliation replaces it (0 = off)")
    planner.add_argument("--spawn-grace-cycles", type=int, default=10,
                         help="adjustment cycles an ordered worker may "
                              "take to start reporting before it is "
                              "presumed dead and replaced")
    planner.add_argument("--degrade-max-level", type=int, default=3,
                         help="graceful-degradation ladder ceiling "
                              "(0 disables the ladder)")
    planner.add_argument("--store-host", default="127.0.0.1")
    planner.add_argument("--store-port", type=int, default=4222)
    planner.add_argument("--log-dir", default=None,
                         help="write planner metrics JSONL (+ TensorBoard "
                              "events when torch is available) here")

    deploy = sub.add_parser("deploy", help="graph deployment ctl "
                            "(≈ DynamoGraphDeployment CRs)")
    deploy.add_argument("action",
                        choices=["apply", "status", "delete", "manifests"])
    deploy.add_argument("target", nargs="?",
                        help="spec YAML (apply/manifests) or deployment "
                             "name (delete)")
    deploy.add_argument("--namespace", default="dynamo")
    deploy.add_argument("--store-host", default="127.0.0.1")
    deploy.add_argument("--store-port", type=int, default=4222)
    deploy.add_argument("--image", default=None,
                        help="container image for generated manifests")
    deploy.add_argument("--output", "-o", default=None,
                        help="manifests: write YAML here (default stdout)")
    deploy.add_argument("--include-crd", action="store_true",
                        help="manifests: prepend the CRD definition")

    operator = sub.add_parser("operator", help="deployment reconciler "
                              "(≈ the K8s operator, local mode)")
    operator.add_argument("--namespace", default="dynamo")
    operator.add_argument("--interval", type=float, default=10.0)
    operator.add_argument("--api-port", type=int, default=8190,
                          help="api-store REST port (0 disables)")
    operator.add_argument("--store-host", default="127.0.0.1")
    operator.add_argument("--store-port", type=int, default=4222)
    operator.add_argument("--backend", default="local",
                          choices=["local", "kubectl"],
                          help="actuation: supervisor control subject "
                               "(local) or real cluster Deployments "
                               "(kubectl scale)")
    operator.add_argument("--k8s-namespace", default="default")
    operator.add_argument("--watch-k8s", action="store_true",
                          help="in-cluster mode: watch "
                          "DynamoGraphDeployment CRs via the k8s API "
                          "(kubectl) as the source of desired state and "
                          "write reconcile status back to each CR")
    operator.add_argument("--kubectl", default="kubectl",
                          help="kubectl binary for --backend=kubectl / "
                          "--watch-k8s")
    operator.add_argument("--state-dir", default=None,
                          help="persist applied specs here (survive "
                               "coordinator restarts)")

    # static analysis: `dynamo-tpu lint` (dynamo_tpu/analysis — dynalint)
    from dynamo_tpu.analysis.cli import add_lint_parser

    add_lint_parser(sub)

    # observability: `dynamo-tpu trace export` (dynamo_tpu/telemetry)
    trace = sub.add_parser(
        "trace", help="span-log tooling (DYN_TRACE_FILE JSONL)"
    )
    trace.add_argument("action", choices=["export"],
                       help="export: JSONL span logs -> Chrome-trace/"
                            "Perfetto JSON (open in ui.perfetto.dev)")
    trace.add_argument("files", nargs="+",
                       help="one or more DYN_TRACE_FILE JSONL logs "
                            "(one per process in a disaggregated fleet)")
    trace.add_argument("--output", "-o", default=None,
                       help="output path (default stdout)")
    trace.add_argument("--trace-id", default=None,
                       help="filter to one trace (id prefix is enough)")
    trace.add_argument("--rid", default=None,
                       help="filter to the trace(s) of one request id "
                            "(X-Request-Id) — resolved by scanning span "
                            "attrs; pairs with `dynamo-tpu autopsy`")

    # observability: `dynamo-tpu top` (live fleet view over /debug/state)
    top = sub.add_parser(
        "top", help="live fleet view: poll /debug/state and render a "
                    "terminal table (batch occupancy, KV usage, tok/s, "
                    "SLO attainment, HBM)"
    )
    top.add_argument("urls", nargs="*",
                     help="debug endpoint base URLs (default "
                          "http://127.0.0.1:8000); frontends and worker "
                          "metrics servers both qualify")
    top.add_argument("--interval", type=float, default=2.0,
                     help="seconds between polls")
    top.add_argument("--iterations", type=int, default=None,
                     help="stop after N frames (default: run forever)")
    top.add_argument("--once", action="store_true",
                     help="render one frame and exit")
    top.add_argument("--raw", action="store_true",
                     help="print JSON rows instead of the table")
    top.add_argument("--no-clear", action="store_true",
                     help="don't clear the screen between frames")
    top.add_argument("--watch-roofline", action="store_true",
                     help="sort workers by roofline_frac ascending — "
                          "the worker losing the most throughput to "
                          "its loss buckets renders first")

    # observability: `dynamo-tpu autopsy <rid>` (per-request timeline)
    autopsy_p = sub.add_parser(
        "autopsy", help="fetch one request's autopsy record "
                        "(/debug/request/{rid}) and render an ASCII "
                        "waterfall with a wall-clock coverage check"
    )
    autopsy_p.add_argument("rid", help="request id (X-Request-Id)")
    autopsy_p.add_argument("--url", default="http://127.0.0.1:8000",
                           help="frontend or metrics-server base URL")
    autopsy_p.add_argument("--json", action="store_true",
                           help="print the raw record instead of the "
                                "waterfall")

    models = sub.add_parser("models", help="model registry ctl (≈ llmctl)")
    models.add_argument("action", choices=["list", "register", "remove"])
    models.add_argument("name", nargs="?")
    models.add_argument("--model-path", help="local model dir (register)")
    models.add_argument("--endpoint", help="dyn://ns.comp.ep (register)")
    models.add_argument(
        "--model-type",
        default="chat_completion",
        choices=["chat", "completion", "chat_completion"],
    )
    models.add_argument("--store-host", default="127.0.0.1")
    models.add_argument("--store-port", type=int, default=4222)

    # lifecycle: `dynamo-tpu drain <worker>` (docs/robustness.md
    # "Graceful drain & rolling restarts")
    drain_p = sub.add_parser(
        "drain", help="gracefully drain a worker: it stops admitting, "
                      "hands in-flight streams to healthy peers, "
                      "deregisters, and exits 0"
    )
    drain_p.add_argument("worker",
                         help="instance id in hex (as shown by "
                              "`models list` or `top`)")
    drain_p.add_argument("--namespace", default="dynamo")
    drain_p.add_argument("--store-host", default="127.0.0.1")
    drain_p.add_argument("--store-port", type=int, default=4222)
    drain_p.add_argument("--timeout", type=float, default=45.0,
                         help="how long to wait for the worker to "
                              "deregister before giving up (exit 1)")
    return p


def _load_model_assets(args: Any):
    """Load tokenizer + optional chat template from --model-path."""
    from dynamo_tpu.preprocessor import PromptFormatter
    from dynamo_tpu.tokenizer import Tokenizer

    if not args.model_path:
        raise SystemExit(f"--out {args.out_mode} requires --model-path")
    if args.model_path.endswith(".gguf"):
        # GGUF single-file model: embedded tokenizer + chat template
        from dynamo_tpu.gguf import GGUFReader, tokenizer_from_gguf

        with GGUFReader(args.model_path) as r:
            tokenizer = tokenizer_from_gguf(r)
            template = r.metadata.get("tokenizer.chat_template")
            toks = r.metadata.get("tokenizer.ggml.tokens") or []

            def _tok_str(key: str) -> str:
                i = r.metadata.get(f"tokenizer.ggml.{key}")
                return toks[i] if i is not None and i < len(toks) else ""

            bos_str, eos_str = _tok_str("bos_token_id"), _tok_str("eos_token_id")
        formatter = None
        if template:
            try:
                formatter = PromptFormatter(
                    template, bos_token=bos_str, eos_token=eos_str
                )
            except Exception:
                log.warning("GGUF chat template failed to parse", exc_info=True)
        if formatter is None:
            log.warning("no chat template in GGUF; chat requests will fail")
    else:
        tokenizer = Tokenizer.from_file(args.model_path)
        try:
            formatter = PromptFormatter.from_model_dir(args.model_path)
        except Exception:
            formatter = None
            log.warning("no chat template found; chat requests will fail")
    from dynamo_tpu.model_card import default_model_name

    model_name = args.model_name or default_model_name(args.model_path)
    return tokenizer, formatter, model_name


def _wrap_pipeline(args: Any, core, eos_ids: list[int]):
    """preprocessor → backend → core engine."""
    from dynamo_tpu.backend import Backend
    from dynamo_tpu.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.runtime.pipeline import build_pipeline

    tokenizer, formatter, model_name = _load_model_assets(args)
    if getattr(args, "vision_config", None):
        pre = _build_mm_preprocessor(args, tokenizer, formatter, model_name)
    elif _is_vlm_checkpoint(getattr(args, "model_path", None)):
        # REAL VLM checkpoint (LLaVA layout): tower + projector load
        # straight from the model dir, no --vision-config needed
        pre = _build_mm_preprocessor_from_checkpoint(
            args, tokenizer, formatter, model_name
        )
    else:
        pre = OpenAIPreprocessor(tokenizer, formatter, model_name=model_name)
    backend = Backend(tokenizer, eos_token_ids=eos_ids)
    from dynamo_tpu.preprocessor.fanout import ChoiceFanout

    # fanout sits between the preprocessor and the (backend -> engine)
    # tail: n>1 becomes n single-choice engine streams, each with its
    # own detokenizer/stop state, merged with choice indices
    return model_name, build_pipeline(
        pre, ChoiceFanout(build_pipeline(backend, core))
    )


def _build_mm_preprocessor(args: Any, tokenizer, formatter, model_name: str):
    """Vision-language pipeline head: ViT encode + placeholder splicing
    (reference: examples/multimodal encode worker + processor)."""
    import json

    from dynamo_tpu.models.vision import VisionConfig, load_vision_params

    with open(args.vision_config) as f:
        vcfg = VisionConfig.from_dict(json.load(f))
    vparams = None
    if args.vision_weights:
        vparams = load_vision_params(vcfg, args.vision_weights)
    else:
        log.warning("vision tower using RANDOM weights (no --vision-weights)")
    return _mm_preprocessor(
        args, tokenizer, formatter, model_name, vcfg, vparams, None
    )


def _mm_preprocessor(
    args: Any, tokenizer, formatter, model_name: str, vcfg, vparams,
    image_token_id,
):
    """Shared tail of both multimodal pipeline heads: encoder + token-id
    resolution + preprocessor wiring (one copy, two entry points)."""
    from dynamo_tpu.multimodal import MultimodalPreprocessor, VisionEncoder

    encoder = VisionEncoder(vcfg, params=vparams)
    if image_token_id is None:
        image_token_id = tokenizer.token_to_id(args.image_token)
    if image_token_id is None:
        raise SystemExit(
            f"tokenizer has no {args.image_token!r} token; pass --image-token"
        )
    return MultimodalPreprocessor(
        tokenizer,
        formatter,
        encode=encoder.encode_urls,
        image_token_id=int(image_token_id),
        tokens_per_image=encoder.tokens_per_image,
        model_name=model_name,
    )


def _is_vlm_checkpoint(model_path: Any) -> bool:
    """True when the model dir is a VLM checkpoint WE can serve
    multimodal: config.json carries a vision_config AND the weights use
    the LLaVA layout (vision_tower.vision_model.*). Other VLM layouts
    (Qwen2-VL, mllama, ...) fall back to text-only serving with a
    warning rather than crashing at startup."""
    import json

    if not model_path or not os.path.isdir(str(model_path)):
        return False
    cfg_path = os.path.join(str(model_path), "config.json")
    if not os.path.exists(cfg_path):
        return False
    try:
        with open(cfg_path) as f:
            if json.load(f).get("vision_config") is None:
                return False
        from dynamo_tpu.models.loader import _ShardedCheckpoint

        names = _ShardedCheckpoint(str(model_path)).names()
        if any(n.startswith("vision_tower.vision_model.") for n in names):
            return True
        log.warning(
            "%s has a vision_config but not the LLaVA weight layout; "
            "serving TEXT-ONLY (supported VLM layout: "
            "vision_tower.vision_model.* + multi_modal_projector.*)",
            model_path,
        )
        return False
    except Exception:
        return False


def _build_mm_preprocessor_from_checkpoint(
    args: Any, tokenizer, formatter, model_name: str
):
    """Vision-language pipeline head from a REAL VLM checkpoint: the
    tower + projector weights come from the model dir's safetensors
    (models/vision.py load_vision_hf); the image token id comes from
    the config's image_token_index (or the tokenizer)."""
    import json

    from dynamo_tpu.models.vision import load_vision_hf

    vcfg, vparams = load_vision_hf(args.model_path)
    with open(os.path.join(args.model_path, "config.json")) as f:
        raw = json.load(f)
    log.info(
        "VLM checkpoint: vision tower %d layers (feature-selected)",
        vcfg.num_hidden_layers,
    )
    return _mm_preprocessor(
        args, tokenizer, formatter, model_name, vcfg, vparams,
        raw.get("image_token_index"),
    )


async def _build_core_engine(args: Any):
    """The tokens-in/tokens-out core engine for out={echo_core,jax}.

    Returns (async_engine, eos_token_ids, jax_engine_or_None).
    """
    if args.out_mode == "echo_core":
        from dynamo_tpu.engines import EchoEngineCore

        return EchoEngineCore(), [], None
    try:
        from dynamo_tpu.engine import JaxEngine, load_engine_config
    except ImportError as exc:
        raise SystemExit(f"jax engine unavailable: {exc}")
    config = load_engine_config(args)
    engine = await JaxEngine.launch(config)
    return engine.as_async_engine(), engine.eos_token_ids, engine


async def _build_local_pipeline(args: Any):
    """Returns (model_name, pipeline, jax_engine_or_None) — the engine
    handle feeds frontend admission control when serving locally."""
    core, eos_ids, jax_engine = await _build_core_engine(args)
    name, pipeline = _wrap_pipeline(args, core, eos_ids)
    return name, pipeline, jax_engine


async def _connect_remote(
    args: Any, path: str, wait_timeout: Optional[float] = None, alive=None
):
    """Build the local pre/post pipeline around remote worker(s) at
    ``path``, behind a push router honoring --router-mode.

    ``wait_timeout`` None = DYN_DISCOVERY_TIMEOUT (default 300 s). The
    wait itself is event-driven (a store-prefix watch sets an
    asyncio.Event — runtime/component.py), so a generous budget costs
    nothing when workers are fast; the budget exists only to fail a
    fleet whose workers never come up. 30 s proved too tight for a
    worker that must JIT-compile its model while a loaded machine
    contends for cores (the r3/r4 full-suite discovery flakes — each
    passed isolated, timed out under load). ``alive``
    (optional) is polled while waiting for the first instance and may
    raise to abort early (the subproc adapter passes a child-process
    liveness check)."""
    import time as _time

    from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    if wait_timeout is None:
        wait_timeout = float(os.environ.get("DYN_DISCOVERY_TIMEOUT", "300"))
    ns, comp, ep = parse_dyn_path(path)
    cfg = _runtime_config(args)
    drt = await DistributedRuntime.create(config=cfg)
    component = drt.namespace(ns).component(comp)
    client = await component.endpoint(ep).client()
    deadline = _time.monotonic() + wait_timeout
    while True:
        if alive is not None:
            alive()
        step = min(5.0, max(0.1, deadline - _time.monotonic()))
        try:
            await client.wait_for_instances(step)
            break
        except asyncio.TimeoutError:
            if _time.monotonic() >= deadline:
                raise
    if args.router_mode == "kv":
        from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter

        kv_router = await KvRouter.create(component, client)
        router = KvPushRouter(kv_router)
    else:
        mode = (
            RouterMode.ROUND_ROBIN
            if args.router_mode == "round_robin"
            else RouterMode.RANDOM
        )
        router = PushRouter(client, mode)
    # remote workers speak PreprocessedRequest: wrap with local pre/post
    return _wrap_pipeline(args, router, [])


async def cmd_run(args: Any) -> None:
    from dynamo_tpu.http.service import HttpService, ModelManager

    out = args.out_mode
    in_mode = args.in_mode
    worker_mode = in_mode.startswith(DYN_SCHEME)

    if args.role == "prefill":
        await _run_prefill_worker(args)
        return
    if args.disagg and not worker_mode:
        raise SystemExit("--disagg applies to workers (--in dyn://...)")

    # ---- output side: build the engine -----------------------------------
    jax_engine = None
    if out in ("echo_core", "jax"):
        if worker_mode:
            # workers serve the core tokens-in/tokens-out engine; pre/post
            # runs at the frontend (reference: subprocess engine pattern)
            model_name = args.model_name or "worker"
            engine, _, jax_engine = await _build_core_engine(args)
        else:
            model_name, engine, jax_engine = await _build_local_pipeline(args)
    elif out == "echo_full":
        from dynamo_tpu.engines import EchoEngineFull

        model_name = args.model_name or "echo"
        engine = EchoEngineFull()
    elif out.startswith("pystr:"):
        # user python file hosted as a text-in/text-out engine
        from dynamo_tpu.engines import PythonStrEngine

        path = out[len("pystr:"):]
        model_name = args.model_name or os.path.splitext(os.path.basename(path))[0]
        engine = PythonStrEngine(path)
    elif out.startswith(DYN_SCHEME):
        # remote worker(s) behind a push router
        model_name, engine = await _connect_remote(args, out)
    elif out.startswith("subproc:"):
        # subprocess engine adapter (reference: launch/dynamo-run/src/
        # subprocess.rs — spawn the engine as a child process that
        # connects BACK over the endpoint plane, then serve through it;
        # the reference embeds vllm/sglang python scripts this way).
        # The command line may reference {endpoint}, {store_host},
        # {store_port}, {model_path}, {model_name}; the same values are
        # exported as DYN_SUBPROC_* env vars. Anything able to serve
        # PreprocessedRequest -> LLMEngineOutput on the endpoint plane
        # qualifies — e.g.:
        #   --out "subproc:python -m dynamo_tpu.cli.main run
        #          --in {endpoint} --out jax --model-path {model_path}
        #          --store-port {store_port}"
        import shlex
        import subprocess

        ep_path = f"{DYN_SCHEME}internal.subproc{os.getpid()}.generate"
        # resolve the store address the way the parent itself connects
        # (flags > env > config file > defaults) — raw args would hand
        # the child port "0" whenever the flag is omitted
        _rt_cfg = _runtime_config(args)
        subs = {
            "endpoint": ep_path,
            "store_host": _rt_cfg.store_host,
            "store_port": str(_rt_cfg.store_port),
            "model_path": args.model_path or "",
            "model_name": args.model_name or "",
        }
        cmdline = out[len("subproc:"):]

        def _sub(token: str) -> str:
            # targeted placeholder substitution (str.format would choke
            # on unrelated braces, e.g. inline JSON engine args)
            for k, v in subs.items():
                token = token.replace("{" + k + "}", v)
            return token

        argv = [_sub(a) for a in shlex.split(cmdline)]
        env = dict(
            os.environ,
            **{f"DYN_SUBPROC_{k.upper()}": v for k, v in subs.items()},
        )
        child = subprocess.Popen(argv, env=env)
        print(f"subprocess engine: pid={child.pid} endpoint={ep_path}",
              flush=True)

        def _reap_child() -> None:
            if child.poll() is None:
                child.terminate()
                try:
                    child.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    child.kill()

        atexit.register(_reap_child)
        # SIGTERM's default action skips atexit — convert it to a normal
        # exit so the child engine is reaped when the adapter is stopped
        import signal as _sig

        def _on_term(signum, frame):
            _reap_child()
            sys.exit(0)

        for _s in (_sig.SIGTERM, _sig.SIGINT):
            try:
                _sig.signal(_s, _on_term)
            except (ValueError, OSError):
                pass  # non-main thread or unsupported platform
        def _child_alive() -> None:
            if child.poll() is not None:
                raise SystemExit(
                    f"subprocess engine exited during startup "
                    f"(rc={child.returncode})"
                )

        try:
            # startup budget covers a real engine's AOT prewarm
            # (multi-minute over a chip tunnel)
            model_name, engine = await _connect_remote(
                args, ep_path,
                wait_timeout=args.subproc_ready_timeout,
                alive=_child_alive,
            )
        except BaseException:
            _reap_child()
            raise
    elif out == "auto":
        # discovery-driven frontend: serve whatever models workers register
        # (reference: components/http standalone frontend + ModelWatcher)
        if in_mode != "http":
            raise SystemExit("--out auto requires --in http")
        from dynamo_tpu.http.discovery import ModelWatcher
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        drt = await DistributedRuntime.create(config=_runtime_config(args))
        drt.runtime.install_signal_handlers()
        manager = ModelManager()
        # no local engine -> no load signal, so caps can't bind here
        # (deadlines still propagate to workers over the endpoint wire)
        # — but the planner's degradation ladder can: rung 3 sheds this
        # frontend to the probe trickle via force_shed
        if args.shed_queue_depth or args.shed_kv_usage:
            log.warning(
                "--shed-* flags need a local jax engine for load "
                "signals; load-based admission control disabled"
            )
        from dynamo_tpu.http.admission import (
            AdmissionConfig,
            AdmissionController,
        )
        from dynamo_tpu.planner.degradation import (
            ServingDegradation,
            watch_degradation,
        )

        admission = AdmissionController(
            AdmissionConfig(
                max_queue_depth=args.shed_queue_depth,
                max_kv_usage=args.shed_kv_usage,
            ),
            load_fn=lambda: None,  # fail open until the ladder says shed
        )
        # routers built by the watcher report migration resumes through
        # admission.check(resume=True) — never shed, but on the books
        watcher = ModelWatcher(
            drt, manager, router_mode=args.router_mode, admission=admission
        )
        await watcher.start()
        spawn(
            watch_degradation(
                drt.store, args.namespace,
                ServingDegradation(admission=admission),
            ),
            name="degradation-watch",
        )
        service = HttpService(
            manager, host=args.http_host, port=args.http_port,
            admission=admission,
            default_deadline_ms=args.default_deadline_ms,
        )
        await service.start()
        print(f"listening on http://{args.http_host}:{service.port}", flush=True)
        await drt.runtime.wait_shutdown()
        await watcher.close()
        await service.stop()
        await drt.shutdown()
        return
    else:
        raise SystemExit(f"unknown --out {out!r}")

    # ---- input side ------------------------------------------------------
    if in_mode == "http":
        manager = ModelManager()
        manager.add_chat_model(model_name, engine)
        manager.add_completion_model(model_name, engine)
        admission = None
        if (args.shed_queue_depth or args.shed_kv_usage) and jax_engine is not None:
            from dynamo_tpu.http.admission import (
                AdmissionConfig,
                AdmissionController,
                engine_load_fn,
            )

            admission = AdmissionController(
                AdmissionConfig(
                    max_queue_depth=args.shed_queue_depth,
                    max_kv_usage=args.shed_kv_usage,
                ),
                engine_load_fn(jax_engine),
                on_shed=jax_engine.slo.note_shed,
            )
            print(
                f"admission control: queue<{args.shed_queue_depth or '-'} "
                f"kv<{args.shed_kv_usage or '-'}", flush=True,
            )
        elif args.shed_queue_depth or args.shed_kv_usage:
            log.warning(
                "--shed-* flags need a local jax engine for load "
                "signals; admission control disabled"
            )
        service = HttpService(
            manager, host=args.http_host, port=args.http_port,
            admission=admission,
            default_deadline_ms=args.default_deadline_ms,
        )
        await service.start()
        print(f"listening on http://{args.http_host}:{service.port}", flush=True)
        await asyncio.Event().wait()
    elif in_mode == "text":
        await _interactive_text(engine, model_name)
    elif in_mode == "stdin":
        await _stdin_once(engine, model_name, args.max_tokens_default)
    elif in_mode.startswith("batch:"):
        await _batch_file(engine, model_name, in_mode[len("batch:"):],
                          args.batch_output, args.max_tokens_default)
    elif in_mode.startswith(DYN_SCHEME):
        # worker mode: serve the core engine on an endpoint
        from dynamo_tpu.runtime.runtime import DistributedRuntime

        ns, comp, ep = parse_dyn_path(in_mode)
        cfg = _runtime_config(args)
        drt = await DistributedRuntime.create(config=cfg)
        drt.runtime.install_signal_handlers()
        component = drt.namespace(ns).component(comp)
        endpoint = component.endpoint(ep)
        if args.disagg:
            if jax_engine is None:
                raise SystemExit("--disagg requires --out jax (worker mode)")
            from dynamo_tpu.disagg.protocols import DisaggConfig
            from dynamo_tpu.disagg.worker import DisaggDecodeEngine

            engine = await DisaggDecodeEngine.create(
                jax_engine,
                drt.store,
                ns,
                worker_id=drt.primary_lease_id,
                lease_id=drt.primary_lease_id,
                conf=DisaggConfig(
                    enabled=True,
                    max_local_prefill_length=args.max_local_prefill_length,
                    max_prefill_queue_size=args.max_prefill_queue_size,
                ),
                advertise_host=args.advertise_host,
            )
            print("disaggregation enabled (decode role)", flush=True)
        # KV event + load-metrics publication must be wired BEFORE the
        # instance becomes discoverable, or blocks cached in the window
        # between serve() and wiring never reach the router's index
        if jax_engine is not None:
            from dynamo_tpu.kv_router.publisher import (
                KvEventPublisher,
                KvMetricsPublisher,
            )

            kv_pub = KvEventPublisher(
                component,
                worker_id=drt.primary_lease_id,
                block_size=jax_engine.config.block_size,
            )
            jax_engine.kv_event_sink = kv_pub.sink
            metrics_pub = KvMetricsPublisher(
                component, drt.primary_lease_id, jax_engine.stats
            )
            metrics_pub.start()
            if (
                getattr(args, "remote_kv_bucket", "")
                and jax_engine.kvbm is not None
                and hasattr(jax_engine.kvbm, "attach_remote")
                # multihost ShardedKvOffload has no remote tier
            ):
                # G4 remote tier rides the coordinator's object plane.
                # attach via executor: the initial index refresh blocks
                # on THIS loop (calling it here would deadlock)
                from dynamo_tpu.kvbm.remote import StoreObjectAdapter

                adapter = StoreObjectAdapter(
                    drt.store, args.remote_kv_bucket,
                    asyncio.get_running_loop(),
                )
                await asyncio.get_running_loop().run_in_executor(
                    None, jax_engine.kvbm.attach_remote, adapter
                )
        if jax_engine is not None:
            # planner degradation ladder (docs/autoscaling.md): follow
            # the published rung; rung 2+ suspends spec decode here
            from dynamo_tpu.planner.degradation import (
                ServingDegradation,
                watch_degradation,
            )

            spawn(
                watch_degradation(
                    drt.store, ns, ServingDegradation(engine=jax_engine)
                ),
                name="degradation-watch",
            )
        instance = await endpoint.serve(engine)
        if args.model_path and args.model_path.endswith(".gguf"):
            # ModelDeploymentCard artifacts (tokenizer.json etc.) come
            # from model directories; a GGUF worker would register a
            # card discovery frontends can't build a pipeline from
            log.warning(
                "GGUF models are not registered for discovery frontends; "
                "serve them with a local pipeline (--in http) instead"
            )
        elif args.model_path and out in ("echo_core", "jax"):
            # publish the deployment card + this instance's ModelEntry so
            # discovery-driven frontends (--out auto) pick the model up
            # (reference: register_llm / llmctl http add). Only core
            # (PreprocessedRequest) engines register: that's the contract
            # discovery frontends build their pipelines against.
            from dynamo_tpu.model_card import default_model_name, register_llm

            await register_llm(
                drt.store,
                args.model_path,
                args.model_name or default_model_name(args.model_path),
                in_mode,
                drt.primary_lease_id,
            )
        print(f"worker serving {in_mode}", flush=True)
        # lifecycle (docs/robustness.md "Graceful drain"): a
        # worker.drain control call converges onto the same shutdown
        # event SIGTERM sets; either way the drain runs before the
        # lease is revoked, so departure is planned, not discovered
        from dynamo_tpu.runtime.drain import (
            DrainCoordinator,
            serve_drain_control,
        )

        spawn(
            serve_drain_control(drt, ns, instance, drt.runtime),
            name="drain-control",
        )
        await drt.runtime.wait_shutdown()
        await DrainCoordinator(
            drt, component, endpoint, instance,
            engine=jax_engine,
            timeout_s=args.drain_timeout_s,
        ).drain()
        await drt.shutdown()
    else:
        raise SystemExit(f"unknown --in {in_mode!r}")


async def _run_prefill_worker(args: Any) -> None:
    """Dedicated prefill worker: consumes the namespace's prefill queue
    (reference: examples/llm/components/prefill_worker.py)."""
    from dynamo_tpu.disagg.worker import run_prefill_worker
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    if args.out_mode != "jax":
        raise SystemExit("--role prefill requires --out jax")
    ns = (
        parse_dyn_path(args.in_mode)[0]
        if args.in_mode.startswith(DYN_SCHEME)
        else args.namespace
    )
    if getattr(args, "sequence_parallel_size", 1) > 1:
        await _run_sp_prefill_worker(args, ns)
        return
    _, _, jax_engine = await _build_core_engine(args)
    assert jax_engine is not None
    drt = await DistributedRuntime.create(config=_runtime_config(args))
    drt.runtime.install_signal_handlers()
    print(f"prefill worker consuming {ns}_prefill_queue", flush=True)
    shutdown = asyncio.Event()

    async def _watch_shutdown() -> None:
        await drt.runtime.wait_shutdown()
        shutdown.set()

    watcher = spawn(_watch_shutdown(), name="cli-shutdown-watch")
    await run_prefill_worker(jax_engine, drt.store, ns, shutdown)
    watcher.cancel()
    await jax_engine.shutdown()
    await drt.shutdown()


async def _run_sp_prefill_worker(args: Any, ns: str) -> None:
    """Sequence-parallel prefill worker: the prompt shards over an sp
    mesh with ring/Ulysses attention (parallel/long_context.py) and the
    resulting KV blocks ship over the normal disagg transfer plane."""
    import jax

    from dynamo_tpu.disagg.worker import run_prefill_worker
    from dynamo_tpu.engine import load_engine_config
    from dynamo_tpu.models import loader
    from dynamo_tpu.parallel.long_context import LongContextPrefiller
    from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    ecfg = load_engine_config(args)
    sp = args.sequence_parallel_size
    mesh = build_mesh(MeshConfig(sp=sp), jax.devices()[:sp])
    mc, params = loader.resolve_model(
        ecfg.model_path, random_weights=ecfg.random_weights, seed=ecfg.seed
    )
    prefiller = LongContextPrefiller(
        mc, params, mesh, block_size=ecfg.resolve_block_size(),
        attn=args.sp_attn, kv_dtype=ecfg.wire_kv_dtype(),
    )
    drt = await DistributedRuntime.create(config=_runtime_config(args))
    drt.runtime.install_signal_handlers()
    print(
        f"sp-prefill worker (sp={sp}, {args.sp_attn}) consuming "
        f"{ns}_prefill_queue",
        flush=True,
    )
    shutdown = asyncio.Event()

    async def _watch_shutdown() -> None:
        await drt.runtime.wait_shutdown()
        shutdown.set()

    watcher = spawn(_watch_shutdown(), name="cli-shutdown-watch")
    await run_prefill_worker(prefiller, drt.store, ns, shutdown)
    watcher.cancel()
    await drt.shutdown()


async def _interactive_text(engine: Any, model_name: str) -> None:
    """REPL chat (reference: dynamo-run in=text)."""
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.engine import Context

    messages: list[dict] = []
    print(f"chatting with {model_name}; /clear resets, ctrl-d exits", flush=True)
    loop = asyncio.get_running_loop()
    while True:
        try:
            line = await loop.run_in_executor(None, lambda: input("> "))
        except (EOFError, KeyboardInterrupt):
            return
        if not line.strip():
            continue
        if line.strip() == "/clear":
            messages.clear()
            continue
        messages.append({"role": "user", "content": line})
        req = ChatCompletionRequest.model_validate(
            {"model": model_name, "messages": messages, "stream": True}
        )
        reply_parts: list[str] = []
        async for chunk in engine.generate(req, Context()):
            for choice in chunk.choices:
                if choice.delta.content:
                    reply_parts.append(choice.delta.content)
                    print(choice.delta.content, end="", flush=True)
        print()
        messages.append({"role": "assistant", "content": "".join(reply_parts)})


async def _stdin_once(engine: Any, model_name: str,
                      max_tokens: Optional[int] = None) -> None:
    """Read all of stdin as one prompt, stream the completion, exit
    (reference: dynamo-run in=stdin)."""
    from dynamo_tpu.protocols.openai import CompletionRequest
    from dynamo_tpu.runtime.engine import Context

    loop = asyncio.get_running_loop()
    prompt = await loop.run_in_executor(None, sys.stdin.read)
    if not prompt.strip():
        raise SystemExit("empty prompt on stdin")
    body = {"model": model_name, "prompt": prompt, "stream": True}
    if max_tokens is not None:
        body["max_tokens"] = max_tokens
    req = CompletionRequest.model_validate(body)
    async for chunk in engine.generate(req, Context()):
        for choice in chunk.choices:
            if choice.text:
                print(choice.text, end="", flush=True)
    print()


async def _batch_file(engine: Any, model_name: str, path: str,
                      out_path: Optional[str],
                      max_tokens: Optional[int]) -> None:
    """Run a JSONL batch of prompts and write responses + timings
    (reference: dynamo-run in=batch: — input/batch.rs; lines are
    {"text": ...}, output lines add response/tokens/latency)."""
    import json
    import time

    from dynamo_tpu.protocols.openai import CompletionRequest
    from dynamo_tpu.runtime.engine import Context

    with open(path) as f:
        prompts = [json.loads(line) for line in f if line.strip()]
    if not prompts:
        raise SystemExit(f"no prompts in {path}")
    for i, entry in enumerate(prompts):
        if not isinstance(entry, dict) or not isinstance(entry.get("text"), str):
            raise SystemExit(
                f"{path} line {i + 1}: expected {{\"text\": \"...\"}}"
            )
    out_path = out_path or path + ".output.jsonl"
    sem = asyncio.Semaphore(32)

    async def one(i: int, entry: dict) -> dict:
        async with sem:
            # clock starts only once a slot is held: timings report engine
            # latency, not client-side queue wait
            body = {"model": model_name, "prompt": entry["text"], "stream": True}
            if max_tokens is not None:
                body["max_tokens"] = max_tokens
            req = CompletionRequest.model_validate(body)
            parts: list[str] = []
            n_chunks = 0
            t0 = time.monotonic()
            t_first = None
            async for chunk in engine.generate(req, Context()):
                for choice in chunk.choices:
                    if choice.text:
                        if t_first is None:
                            t_first = time.monotonic()
                        parts.append(choice.text)
                        n_chunks += 1
            t1 = time.monotonic()
        return {
            "index": i,
            "text": entry["text"],
            "response": "".join(parts),
            "chunks": n_chunks,
            "ttft_ms": round(((t_first or t1) - t0) * 1000, 1),
            "total_ms": round((t1 - t0) * 1000, 1),
        }

    t0 = time.monotonic()
    results = await asyncio.gather(
        *[one(i, e) for i, e in enumerate(prompts)],
        return_exceptions=True,
    )
    wall = time.monotonic() - t0
    n_err = 0
    with open(out_path, "w") as f:
        for i, r in enumerate(results):
            if isinstance(r, BaseException):
                n_err += 1
                r = {"index": i, "text": prompts[i]["text"], "error": str(r)}
            f.write(json.dumps(r) + "\n")
    done = [r for r in results if not isinstance(r, BaseException)]
    total_chunks = sum(r["chunks"] for r in done)
    print(
        f"batch done: {len(done)}/{len(results)} prompts "
        f"({n_err} errors), {total_chunks} chunks, "
        f"{wall:.2f}s -> {out_path}",
        flush=True,
    )
    if n_err:
        raise SystemExit(1)


def _exec_native_store(args: Any) -> None:
    """Replace this process with the C++ coordinator (building it first
    if needed); falls through to the python server on build failure."""
    import importlib.util
    import socket

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    build_py = os.path.join(repo, "native", "build.py")
    binary = os.path.join(repo, "dynamo_tpu", "native", "dynamo_store")
    if not os.path.exists(binary) and os.path.exists(build_py):
        spec = importlib.util.spec_from_file_location("native_build", build_py)
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
            mod.build_store()  # errors print to stderr inside
        except Exception:
            log.warning("native store build failed", exc_info=True)
    if os.path.exists(binary):
        # the binary only accepts numeric addresses (inet_pton falls back
        # to INADDR_ANY): resolve hostnames here so --host localhost stays
        # loopback-only
        try:
            host = socket.gethostbyname(args.host)
        except OSError:
            raise SystemExit(f"cannot resolve --host {args.host!r}")
        argv = [binary, "--host", host, "--port", str(args.port)]
        if getattr(args, "persist_path", None):
            argv += ["--persist-path", args.persist_path]
        if getattr(args, "fsync_wal", False):
            argv += ["--fsync-wal"]
        os.execv(binary, argv)
    if getattr(args, "fsync_wal", False):
        raise SystemExit(
            "--fsync-wal needs the native store binary, which is "
            "unavailable; refusing to silently serve with the python "
            "server's weaker (flush-only) WAL durability"
        )
    log.warning("native store binary unavailable; using the python server")


def _runtime_config(args: Any) -> RuntimeConfig:
    overrides: dict[str, Any] = {}
    if getattr(args, "static", False):
        overrides["static"] = True
    if getattr(args, "store_host", None):
        overrides["store_host"] = args.store_host
    if getattr(args, "store_port", None):
        overrides["store_port"] = args.store_port
    return RuntimeConfig.from_settings(**overrides)


async def cmd_build(args: Any) -> None:
    """Package a graph (reference: sdk/cli/bentos.py build + push)."""
    import sys

    from dynamo_tpu.deploy.build import build_package, push_package

    sys.path.insert(0, os.getcwd())
    deployment = None
    if args.deployment_spec:
        from dynamo_tpu.deploy import GraphDeploymentSpec

        deployment = GraphDeploymentSpec.from_yaml_file(
            args.deployment_spec
        ).to_dict()
    path, manifest = build_package(
        args.service, name=args.name, config_file=args.config_file,
        deployment_spec=deployment, out_path=args.output,
    )
    print(f"built {manifest.name}:{manifest.version} -> {path} "
          f"({len(manifest.files)} files)")
    if args.push:
        from dynamo_tpu.store.client import StoreClient

        client = await StoreClient.connect(args.store_host, args.store_port)
        try:
            await push_package(client, path)
            print(f"pushed {manifest.name}:{manifest.version}")
        finally:
            await client.close()


async def cmd_serve(args: Any) -> None:
    """Supervise a @service graph (reference: cli/serving.py:163-300)."""
    import importlib

    from dynamo_tpu.sdk.service import DynamoService
    from dynamo_tpu.sdk.serving import Supervisor
    from dynamo_tpu.store.client import StoreClient

    from dynamo_tpu.sdk.runner import load_service

    if args.package:
        # pull + verify + unpack, then serve the embedded entry
        import sys

        from dynamo_tpu.deploy.build import pull_package, unpack_package

        name, _, version = args.package.partition(":")
        client = await StoreClient.connect(args.store_host, args.store_port)
        try:
            blob, version = await pull_package(client, name, version or None)
        finally:
            await client.close()
        dest_root = os.environ.get(
            "DYN_PACKAGE_DIR",
            os.path.join(os.path.expanduser("~"), ".dynamo_tpu", "packages"),
        )
        dest, manifest = unpack_package(blob, dest_root)
        src = os.path.join(dest, "src")
        if src not in sys.path:
            sys.path.insert(0, src)
        # the supervisor's per-component CHILD processes import the
        # graph themselves: without this export they'd only find it if
        # the sources happened to be independently importable (e.g. a
        # repo checkout) — on a package-only machine they'd crash
        os.environ["PYTHONPATH"] = src + (
            os.pathsep + os.environ["PYTHONPATH"]
            if os.environ.get("PYTHONPATH") else ""
        )
        # ...and for `-m` launches the child's CWD precedes PYTHONPATH
        # on sys.path, so a conflicting package under the operator's
        # working directory (a stale checkout) would silently shadow
        # the pulled artifact: serve from inside the package dir, which
        # contains no importable top-level packages
        os.chdir(dest)
        args.service = manifest.entry
        if not args.config_file and "config.yaml" in manifest.files:
            args.config_file = os.path.join(dest, "config.yaml")
        print(f"serving package {manifest.name}:{version} "
              f"(entry {manifest.entry})", flush=True)
    if not args.service:
        raise SystemExit("serve requires module:Attr or --package")
    entry = load_service(args.service)
    mod = importlib.import_module(args.service.partition(":")[0])
    specs = {
        obj.name: f"{mod.__name__}:{attr}"
        for attr, obj in vars(mod).items()
        if isinstance(obj, DynamoService)
    }
    overrides: dict[str, dict] = {}
    if args.config_file:
        with open(args.config_file) as f:
            text = f.read()
        try:
            import yaml

            overrides = yaml.safe_load(text) or {}
        except ImportError:
            import json as _json

            overrides = _json.loads(text)
    store = await StoreClient.connect(args.store_host, args.store_port)
    sup = Supervisor(
        entry=entry,
        store=store,
        namespace=entry.config.namespace,
        store_host=args.store_host,
        store_port=args.store_port,
        overrides=overrides,
        service_specs=specs,
    )
    await sup.start()
    print(f"serving graph {entry.name}: {list(specs)}", flush=True)
    stop = asyncio.Event()
    import signal as _signal

    loop = asyncio.get_running_loop()
    for sig in (_signal.SIGINT, _signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover
            pass
    await stop.wait()
    await sup.shutdown()
    await store.close()


async def cmd_router(args: Any) -> None:
    """Standalone KV-aware router: one shared index/scheduler multiple
    frontends consult (reference: components/router/src/main.rs:23-60 —
    the KvRouter served over an endpoint). Serves two endpoints on the
    router component:

      generate  — full proxy: requests stream through the chosen worker
      schedule  — decision only: {token_ids} -> {worker_id,
                  prefix_hit_rate, matched_blocks}; frontends dispatch
                  direct and share the index without proxy overhead
    """
    from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
    from dynamo_tpu.runtime.engine import AsyncEngine, Context, FnEngine
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    drt = await DistributedRuntime.create(config=_runtime_config(args))
    drt.runtime.install_signal_handlers()
    workers = drt.namespace(args.namespace).component(args.component)
    client = await workers.endpoint("generate").client()
    router = await KvRouter.create(workers, client, block_size=args.block_size)

    svc = drt.namespace(args.namespace).component(args.router_component)
    await svc.endpoint("generate").serve(KvPushRouter(router))

    async def schedule(request, ctx: Context):
        await client.wait_for_instances()
        decision = router.schedule(list(request["token_ids"]))
        yield {
            "worker_id": decision.worker_id,
            "prefix_hit_rate": decision.prefix_hit_rate,
            "overlap_blocks": decision.overlap_blocks,
            "total_blocks": decision.total_blocks,
        }

    await svc.endpoint("schedule").serve(FnEngine(schedule))
    print(
        f"kv router on dyn://{args.namespace}.{args.router_component}."
        f"{{generate,schedule}} over {args.component}",
        flush=True,
    )
    await drt.runtime.wait_shutdown()
    await router.close()
    await drt.shutdown()


async def cmd_metrics(args: Any) -> None:
    from dynamo_tpu.metrics.service import MetricsService
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    drt = await DistributedRuntime.create(config=_runtime_config(args))
    drt.runtime.install_signal_handlers()
    component = drt.namespace(args.namespace).component(args.component)
    svc = MetricsService(component, port=args.port)
    await svc.start()
    print(f"metrics on :{svc.port}/metrics", flush=True)
    await drt.runtime.wait_shutdown()
    await svc.close()
    await drt.shutdown()


async def cmd_planner(args: Any) -> None:
    from dynamo_tpu.planner.connector import LocalConnector
    from dynamo_tpu.planner.degradation import StoreDegradation
    from dynamo_tpu.planner.planner import Planner, PlannerConfig
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.utils import affinity

    # a planner process's event loop IS the planner domain (in-process
    # planners driven from tests stay on their host's "loop" binding)
    affinity.register_thread("planner")
    drt = await DistributedRuntime.create(config=_runtime_config(args))
    drt.runtime.install_signal_handlers()
    component = drt.namespace(args.namespace).component(args.component)
    planner = Planner(
        drt.store,
        component,
        LocalConnector(drt.store, args.namespace),
        # ladder rungs publish to the store; workers' watch_degradation
        # tasks apply them (admission caps, spec suspend)
        degradation=(
            StoreDegradation(drt.store, args.namespace)
            if args.degrade_max_level > 0
            else None
        ),
        config=PlannerConfig(
            decode_component=args.component,
            prefill_component=args.prefill_component,
            metric_interval_s=args.metric_interval,
            adjustment_interval_s=args.adjustment_interval,
            min_decode=args.min_decode,
            max_decode=args.max_decode,
            min_prefill=args.min_prefill,
            max_prefill=args.max_prefill,
            grace_cycles=args.grace_cycles,
            slo_target=args.slo_target,
            slo_headroom=args.slo_headroom,
            reconcile_cycles=args.reconcile_cycles,
            spawn_grace_cycles=args.spawn_grace_cycles,
            degrade_max_level=args.degrade_max_level,
        ),
    )
    mlog = None
    if args.log_dir:
        from dynamo_tpu.planner.metrics_log import MetricsLogger

        mlog = MetricsLogger(args.log_dir)
        planner.on_metrics = mlog
    try:
        await planner.start()
        print("planner running", flush=True)
        await drt.runtime.wait_shutdown()
        await planner.close()
    finally:
        if mlog is not None:
            mlog.close()  # flush buffered TensorBoard events
    await drt.shutdown()


async def cmd_deploy(args: Any) -> None:
    import json

    from dynamo_tpu.deploy import GraphDeploymentSpec, Reconciler
    from dynamo_tpu.store.client import StoreClient

    if args.action == "manifests":
        # offline: spec YAML -> real K8s objects, no store needed
        from dynamo_tpu.deploy.manifests import (
            DEFAULT_IMAGE,
            crd_manifest,
            graph_manifests,
            render_yaml,
            validate_k8s_doc,
        )

        if not args.target:
            raise SystemExit("deploy manifests requires a spec YAML path")
        spec = GraphDeploymentSpec.from_yaml_file(args.target)
        docs = graph_manifests(spec, image=args.image or DEFAULT_IMAGE)
        if args.include_crd:
            docs.insert(0, crd_manifest())
        for d in docs:
            validate_k8s_doc(d)
        text = render_yaml(docs)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text)
            print(f"wrote {len(docs)} manifests to {args.output}")
        else:
            print(text)
        return

    client = await StoreClient.connect(args.store_host, args.store_port)
    rec = Reconciler(client, args.namespace)
    try:
        if args.action == "apply":
            if not args.target:
                raise SystemExit("deploy apply requires a spec YAML path")
            spec = GraphDeploymentSpec.from_yaml_file(args.target)
            await rec.apply(spec)
            print(f"applied {spec.name} ({len(spec.services)} services)")
        elif args.action == "status":
            print(json.dumps(await rec.status(), indent=2))
        elif args.action == "delete":
            if not args.target:
                raise SystemExit("deploy delete requires a deployment name")
            if await rec.delete(args.target):
                print(f"deleted {args.target}")
            else:
                raise SystemExit(f"no deployment {args.target!r}")
    finally:
        await client.close()


async def cmd_operator(args: Any) -> None:
    from dynamo_tpu.deploy import ApiStore, Reconciler
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    drt = await DistributedRuntime.create(config=_runtime_config(args))
    drt.runtime.install_signal_handlers()
    factory = None
    if getattr(args, "backend", "local") == "kubectl":
        from dynamo_tpu.deploy.operator import KubectlConnector

        factory = lambda spec: KubectlConnector(  # noqa: E731
            spec.name, k8s_namespace=args.k8s_namespace,
            kubectl=getattr(args, "kubectl", "kubectl"),
        )
    rec = Reconciler(drt.store, args.namespace, interval_s=args.interval,
                     connector_factory=factory,
                     state_dir=getattr(args, "state_dir", None))
    if rec.state_dir:
        restored = await rec.restore_state()
        if restored:
            print(f"restored {restored} deployments from {rec.state_dir}",
                  flush=True)
    api = None
    if args.api_port:
        api = ApiStore(rec, port=args.api_port)
        await api.start()
        print(f"api-store on :{api.port}", flush=True)
    cr_task = None
    if getattr(args, "watch_k8s", False):
        from dynamo_tpu.deploy.operator import CrWatcher

        cr = CrWatcher(
            rec, k8s_namespace=args.k8s_namespace,
            kubectl=getattr(args, "kubectl", "kubectl"),
        )
        rec.on_results = cr.write_status
        print("watching DynamoGraphDeployment CRs (in-cluster mode)",
              flush=True)
    print("operator reconciling", flush=True)
    shutdown = asyncio.Event()

    async def _watch() -> None:
        await drt.runtime.wait_shutdown()
        shutdown.set()

    watcher = spawn(_watch(), name="operator-shutdown-watch")
    if getattr(args, "watch_k8s", False):
        cr_task = spawn(cr.run(shutdown), name="operator-cr-watch")
    await rec.run(shutdown)
    watcher.cancel()
    if cr_task is not None:
        cr_task.cancel()
    if api is not None:
        await api.stop()
    await drt.shutdown()


async def cmd_drain(args: Any) -> int:
    """Issue the worker.drain control call and poll discovery until the
    instance key disappears (the worker deletes it as its last act)."""
    from dynamo_tpu.runtime.drain import request_drain
    from dynamo_tpu.store.client import StoreClient

    client = await StoreClient.connect(args.store_host, args.store_port)
    try:
        print(f"draining {args.worker} in {args.namespace!r}...", flush=True)
        ok = await request_drain(
            client, args.namespace, args.worker, timeout_s=args.timeout
        )
    finally:
        await client.close()
    if ok:
        print(f"worker {args.worker} drained and deregistered")
        return 0
    print(f"worker {args.worker} still registered after {args.timeout}s "
          "(is it alive? did the control call reach it?)")
    return 1


async def cmd_models(args: Any) -> None:
    from dynamo_tpu.model_card import list_entries, register_llm, unregister_model
    from dynamo_tpu.store.client import StoreClient

    client = await StoreClient.connect(args.store_host, args.store_port)
    try:
        if args.action == "list":
            for entry in await list_entries(client):
                print(
                    f"{entry.name}\t{entry.model_type}\t{entry.endpoint}"
                    f"\tlease={entry.lease_id:x}"
                )
            instances = await client.kv_get_prefix("instances/")
            for e in instances:
                print(e.key)
        elif args.action == "register":
            # llmctl http add: manual registration for engines that don't
            # self-register (the card stays until `models remove`)
            if not (args.name and args.model_path and args.endpoint):
                raise SystemExit(
                    "models register requires NAME --model-path and --endpoint"
                )
            await register_llm(
                client,
                args.model_path,
                args.name,
                args.endpoint,
                lease_id=0,
                model_type=args.model_type,
            )
            print(f"registered {args.name} -> {args.endpoint}")
        elif args.action == "remove":
            if not args.name:
                raise SystemExit("models remove requires a name")
            n = await unregister_model(client, args.name)
            print(f"removed {n} entries")
    finally:
        await client.close()


def cmd_trace(args: Any) -> int:
    """Span-log export (pure file transform: no logging/jax setup)."""
    from dynamo_tpu.telemetry.export import export_chrome_trace

    # tolerate missing logs: a fleet role that never emitted a span
    # never creates its DYN_TRACE_FILE — warn and export the rest
    files = []
    for path in args.files:
        if os.path.exists(path):
            files.append(path)
        else:
            print(f"warning: no span log at {path}", file=sys.stderr)
    if not files:
        print("error: none of the span logs exist", file=sys.stderr)
        return 1
    trace_id = args.trace_id
    if getattr(args, "rid", None):
        if trace_id:
            print("error: --rid and --trace-id are mutually exclusive",
                  file=sys.stderr)
            return 1
        from dynamo_tpu.telemetry.export import trace_ids_for_request

        ids = trace_ids_for_request(files, args.rid)
        if not ids:
            print(f"error: no spans carry request_id={args.rid!r} "
                  "(was the frontend started with DYN_TRACE_FILE?)",
                  file=sys.stderr)
            return 1
        if len(ids) > 1:
            print(f"warning: rid {args.rid!r} matched {len(ids)} traces; "
                  f"exporting {ids[0]}", file=sys.stderr)
        trace_id = ids[0]
    if args.output:
        with open(args.output, "w") as f:
            n = export_chrome_trace(files, f, trace_id=trace_id)
        print(f"exported {n} spans -> {args.output}", file=sys.stderr)
    else:
        n = export_chrome_trace(files, sys.stdout, trace_id=trace_id)
        print(f"exported {n} spans", file=sys.stderr)
    return 0 if n else 1


def main(argv: Optional[list[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if args.command == "lint":
        # pure static analysis: no logging/jax setup, exit code gates CI
        from dynamo_tpu.analysis.cli import cmd_lint

        sys.exit(cmd_lint(args))
    if args.command == "trace":
        sys.exit(cmd_trace(args))
    if args.command == "top":
        # pure HTTP polling: no logging/jax setup
        from dynamo_tpu.cli.top import cmd_top

        sys.exit(cmd_top(args))
    if args.command == "autopsy":
        # one HTTP GET + terminal render: no logging/jax setup
        from dynamo_tpu.cli.autopsy import cmd_autopsy

        sys.exit(cmd_autopsy(args))
    init_logging()
    from dynamo_tpu.utils.jaxtools import configure_from_env

    configure_from_env()
    # deterministic fault injection (docs/robustness.md): DYN_FAULTS
    # activates a plan for THIS process; unset = every hook is a no-op
    from dynamo_tpu import faults

    faults.init_from_env()
    if args.command == "run":
        try:
            asyncio.run(cmd_run(args))
        except KeyboardInterrupt:
            pass
    elif args.command == "store":
        if args.native:
            _exec_native_store(args)
        from dynamo_tpu.store.memory import MemoryStore
        from dynamo_tpu.store.server import StoreServer

        server = StoreServer(
            store=MemoryStore(persist_path=args.persist_path),
            host=args.host,
            port=args.port,
        )
        try:
            asyncio.run(server.serve_forever())
        except KeyboardInterrupt:
            pass
    elif args.command == "build":
        asyncio.run(cmd_build(args))
    elif args.command == "router":
        asyncio.run(cmd_router(args))
    elif args.command == "serve":
        try:
            asyncio.run(cmd_serve(args))
        except KeyboardInterrupt:
            pass
    elif args.command == "metrics":
        asyncio.run(cmd_metrics(args))
    elif args.command == "planner":
        asyncio.run(cmd_planner(args))
    elif args.command == "models":
        asyncio.run(cmd_models(args))
    elif args.command == "drain":
        sys.exit(asyncio.run(cmd_drain(args)))
    elif args.command == "deploy":
        asyncio.run(cmd_deploy(args))
    elif args.command == "operator":
        try:
            asyncio.run(cmd_operator(args))
        except KeyboardInterrupt:
            pass
    else:  # pragma: no cover
        sys.exit(2)


if __name__ == "__main__":
    main()
