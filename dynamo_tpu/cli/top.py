"""``dynamo-tpu top`` — live fleet view over ``/debug/state``.

Polls one or more debug endpoints (HTTP frontends and/or worker
metrics servers) and renders a terminal table: batch occupancy, queue
depth, KV-pool usage, token throughput (derived from successive
snapshots), SLO attainment, and HBM in use — the operator's "what is
this worker doing RIGHT NOW" answer without attaching a profiler.

Plumbing notes: snapshots come from ``/debug/state`` verbatim (the
engine's provider, telemetry/debug.py); token rates are derived
client-side from ``engine.tokens_generated_total`` deltas between
polls, so the first frame shows ``-``. ``--once`` renders a single
frame and exits (scriptable / testable); ``--raw`` prints the JSON
instead of the table.

Host-plane columns (telemetry/hostplane.py, polled best-effort from
``/debug/hostplane``): LAG99 = the frontend event loop's lag p99 in
ms, STRM = open SSE streams, RPS = finished requests/sec derived from
``ledger.requests_total`` deltas (same ``-`` rule as TOK/S: first
poll, zero poll gap, and counter rewinds render absence, not 0.0).

SLOW counts the endpoint's retained autopsy exemplars (telemetry/
autopsy.py, best-effort from ``/debug/requests``): requests kept by
tail sampling because they were flagged (SLO miss, migrated, faulted,
shed, …) or landed in the p99 latency tail — a rising SLOW with a flat
SSTEP (flight-recorder slow steps) points the operator at the host/
fleet path rather than the device loop.
"""

from __future__ import annotations

import asyncio
import json
import sys
import time
from typing import Any, Optional, TextIO

import aiohttp

POLL_TIMEOUT_S = 5.0


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"  # 0 is real data ("0B"); only absence renders "-"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return "-"


def _pct(v: Optional[float]) -> str:
    return f"{v * 100:5.1f}%" if isinstance(v, (int, float)) else "    -"


async def fetch_state(
    session: aiohttp.ClientSession, base_url: str
) -> dict[str, Any]:
    url = base_url.rstrip("/") + "/debug/state"
    async with session.get(url, timeout=aiohttp.ClientTimeout(
        total=POLL_TIMEOUT_S
    )) as resp:
        resp.raise_for_status()
        return await resp.json()


async def fetch_hostplane(
    session: aiohttp.ClientSession, base_url: str
) -> Optional[dict[str, Any]]:
    """Best-effort /debug/hostplane poll: an endpoint without the host
    data plane (worker-only metrics server from an older build) is not
    an error — its host columns just render ``-``."""
    url = base_url.rstrip("/") + "/debug/hostplane"
    try:
        async with session.get(url, timeout=aiohttp.ClientTimeout(
            total=POLL_TIMEOUT_S
        )) as resp:
            if resp.status != 200:
                return None
            return await resp.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
        return None


async def fetch_requests(
    session: aiohttp.ClientSession, base_url: str
) -> Optional[dict[str, Any]]:
    """Best-effort /debug/requests poll (request-autopsy exemplar
    index). An endpoint predating the autopsy plane renders ``-`` in
    the SLOW column rather than erroring the row."""
    url = base_url.rstrip("/") + "/debug/requests"
    try:
        async with session.get(url, timeout=aiohttp.ClientTimeout(
            total=POLL_TIMEOUT_S
        )) as resp:
            if resp.status != 200:
                return None
            return await resp.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
        return None


def _autopsy_cols(ap: Optional[dict]) -> dict:
    """SLOW column from a /debug/requests payload: the count of
    retained exemplars. Absence (no autopsy plane, error stanza, or a
    malformed payload) renders ``-``; an empty exemplar ring is real
    data and renders 0."""
    cols: dict[str, Any] = {"slow_requests": None}
    coll = (ap or {}).get("collector")
    if isinstance(coll, dict):
        ex = coll.get("exemplars")
        if isinstance(ex, list):
            cols["slow_requests"] = len(ex)
    return cols


def _hostplane_cols(
    hp: Optional[dict], prev_hp: Optional[dict],
    now: float, prev_ts: Optional[float],
) -> dict:
    """Host-plane columns (LAG99 / STRM / RPS) from a /debug/hostplane
    payload. RPS derives from ``ledger.requests_total`` deltas under
    the same rule as TOK/S: no prior poll, a zero/negative poll gap, or
    a counter that went backwards (frontend restart) all render the
    absence marker, never a fabricated 0.0."""
    cols: dict[str, Any] = {
        "loop_lag_p99_ms": None, "streams_open": None, "rps": None,
    }
    fe = (hp or {}).get("frontend") or {}
    lag = (fe.get("loop") or {}).get("lag") or {}
    ledger = fe.get("ledger") or {}
    if "p99_ms" in lag:
        cols["loop_lag_p99_ms"] = lag["p99_ms"]
    if "streams_open" in ledger:
        cols["streams_open"] = ledger["streams_open"]
    total = ledger.get("requests_total")
    if prev_hp is not None and prev_ts is not None and total is not None:
        prev_total = (
            ((prev_hp.get("frontend") or {}).get("ledger") or {})
            .get("requests_total")
        )
        dt = now - prev_ts
        if prev_total is not None and dt > 0 and total >= prev_total:
            cols["rps"] = (total - prev_total) / dt
    return cols


def _engine_row(url: str, state: dict, prev: Optional[dict],
                now: float, prev_ts: Optional[float]) -> dict:
    """Flatten one /debug/state payload into the table row."""
    eng = state.get("engine") or {}
    sched = eng.get("scheduler") or {}
    pool = eng.get("kv_pool") or {}
    slo = eng.get("slo") or {}
    hbm = eng.get("hbm") or {}
    load = eng.get("load") or {}
    rec = eng.get("flight_recorder") or {}
    attr = (eng.get("attribution") or {}).get("window") or {}
    tok_rate: Optional[float] = None
    # tokens_generated_total counts ALL generated tokens (goodput only
    # counts SLO-met ones and stays 0 when no targets are configured).
    # No prior snapshot, a zero/negative poll gap, or a counter that
    # went BACKWARDS (worker restart) all mean "no delta yet" — render
    # the absence marker, never a fabricated 0.0 rate.
    toks = eng.get("tokens_generated_total")
    if prev is not None and prev_ts is not None and toks is not None:
        prev_toks = (prev.get("engine") or {}).get("tokens_generated_total")
        dt = now - prev_ts
        if prev_toks is not None and dt > 0 and toks >= prev_toks:
            tok_rate = (toks - prev_toks) / dt
    return {
        "url": url,
        "model": eng.get("model") or "-",
        # lifecycle state from the engine's drain flag; workers
        # predating the field (or frontends) report None → '-'
        "draining": eng.get("draining"),
        "running": sched.get("running"),
        "waiting": sched.get("queue_depth"),
        "max_batch": eng.get("max_batch_size"),
        "kv_usage": pool.get("usage"),
        "kv_active": pool.get("active_blocks"),
        "kv_total": pool.get("total_blocks"),
        "tok_s": tok_rate,
        "slo": slo.get("attainment") if slo.get("enabled") else None,
        # perf attribution (telemetry/attribution.py): live roofline
        # fraction + the window's dominant loss bucket per worker
        "roofline": attr.get("roofline_frac"),
        "loss_bucket": attr.get("top_loss_bucket") or None,
        "hbm": hbm.get("bytes_in_use"),
        "slow_steps": rec.get("slow_steps"),
        "preemptions": sched.get("preemptions"),
        "error": None,
    }


HEADER = (
    f"{'WORKER':<28} {'MODEL':<12} {'STATE':>5} {'RUN':>5} {'WAIT':>5} "
    f"{'KV%':>7} {'TOK/S':>8} {'ROOF%':>7} {'LOSS':>10} {'SLO%':>7} "
    f"{'HBM':>9} {'SSTEP':>5} {'SLOW':>5} {'PREEMPT':>7} "
    f"{'LAG99':>7} {'STRM':>6} {'RPS':>7}"
)


def render_frame(rows: list[dict], out: TextIO) -> None:
    out.write(HEADER + "\n")
    for r in rows:
        if r.get("error"):
            out.write(f"{r['url']:<28} !! {r['error']}\n")
            continue
        run = r["running"]
        mb = r["max_batch"]
        run_s = f"{run}/{mb}" if run is not None and mb else (
            str(run) if run is not None else "-"
        )
        tok = f"{r['tok_s']:8.1f}" if r["tok_s"] is not None else "       -"
        lag = r.get("loop_lag_p99_ms")
        lag_s = f"{lag:7.1f}" if lag is not None else "      -"
        strm = r.get("streams_open")
        rps = r.get("rps")
        rps_s = f"{rps:7.1f}" if rps is not None else "      -"
        dr = r.get("draining")
        state_s = "-" if dr is None else ("DRAIN" if dr else "up")
        out.write(
            f"{r['url']:<28} {str(r['model'])[:12]:<12} {state_s:>5} "
            f"{run_s:>5} "
            f"{str(r['waiting'] if r['waiting'] is not None else '-'):>5} "
            f"{_pct(r['kv_usage']):>7} {tok} "
            f"{_pct(r.get('roofline')):>7} "
            f"{str(r.get('loss_bucket') or '-')[:10]:>10} "
            f"{_pct(r['slo']):>7} "
            f"{_fmt_bytes(r['hbm']):>9} "
            f"{str(r['slow_steps'] if r['slow_steps'] is not None else '-'):>5} "
            f"{str(r['slow_requests'] if r.get('slow_requests') is not None else '-'):>5} "
            f"{str(r['preemptions'] if r['preemptions'] is not None else '-'):>7} "
            f"{lag_s} {str(strm if strm is not None else '-'):>6} {rps_s}\n"
        )
    out.flush()


async def run_top(
    urls: list[str],
    interval: float = 2.0,
    iterations: Optional[int] = None,
    raw: bool = False,
    clear: bool = True,
    out: TextIO = sys.stdout,
    watch_roofline: bool = False,
) -> int:
    """Poll ``urls`` and render frames until ``iterations`` runs out
    (None = forever). Returns an exit code (1 when EVERY worker errored
    on the final frame — a dead fleet should fail scripts).
    ``watch_roofline`` sorts the table by roofline_frac ascending —
    the worker bleeding the most throughput floats to the top (workers
    without a decode window sort last; errored rows stay last)."""
    prev: dict[str, tuple[dict, float]] = {}
    prev_hp: dict[str, Optional[dict]] = {}
    n = 0
    all_failed = False
    async with aiohttp.ClientSession() as session:
        while True:
            now = time.monotonic()
            results = await asyncio.gather(
                *[fetch_state(session, u) for u in urls],
                return_exceptions=True,
            )
            hp_results = await asyncio.gather(
                *[fetch_hostplane(session, u) for u in urls]
            )
            ap_results = await asyncio.gather(
                *[fetch_requests(session, u) for u in urls]
            )
            rows: list[dict] = []
            all_failed = True
            for url, res, hp, ap in zip(
                urls, results, hp_results, ap_results
            ):
                if isinstance(res, BaseException):
                    rows.append({"url": url, "error": str(res) or
                                 type(res).__name__})
                    continue
                all_failed = False
                p = prev.get(url)
                row = _engine_row(
                    url, res, p[0] if p else None, now,
                    p[1] if p else None,
                )
                row.update(_hostplane_cols(
                    hp, prev_hp.get(url), now, p[1] if p else None,
                ))
                row.update(_autopsy_cols(ap))
                rows.append(row)
                prev[url] = (res, now)
                prev_hp[url] = hp
            if watch_roofline:
                rows.sort(key=lambda r: (
                    "error" in r and r.get("error") is not None,
                    r.get("roofline") is None,
                    r.get("roofline") if r.get("roofline") is not None
                    else 0.0,
                ))
            if raw:
                payload = {
                    r["url"] if "url" in r else urls[i]: r
                    for i, r in enumerate(rows)
                }
                out.write(json.dumps(payload) + "\n")
                out.flush()
            else:
                if clear and n > 0:
                    out.write("\x1b[2J\x1b[H")
                out.write(time.strftime("dynamo-tpu top  %H:%M:%S\n"))
                render_frame(rows, out)
            n += 1
            if iterations is not None and n >= iterations:
                break
            await asyncio.sleep(interval)
    return 1 if all_failed else 0


def cmd_top(args: Any) -> int:
    urls = args.urls or ["http://127.0.0.1:8000"]
    try:
        return asyncio.run(run_top(
            urls,
            interval=args.interval,
            iterations=1 if args.once else args.iterations,
            raw=args.raw,
            clear=not args.no_clear,
            watch_roofline=getattr(args, "watch_roofline", False),
        ))
    except KeyboardInterrupt:
        return 0
