"""Deploy tier: graph-deployment specs, the operator-lite reconciler,
and the deployment api-store (reference: deploy/cloud/operator — the Go
K8s operator with DynamoGraphDeployment CRDs; deploy/cloud/api-store)."""

from dynamo_tpu.deploy.spec import GraphDeploymentSpec, ServiceSpec
from dynamo_tpu.deploy.operator import Reconciler
from dynamo_tpu.deploy.api_store import ApiStore

__all__ = ["ApiStore", "GraphDeploymentSpec", "Reconciler", "ServiceSpec"]
