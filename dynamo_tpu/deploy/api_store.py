"""Deployment api-store: REST CRUD for graph-deployment specs.

The native analogue of the reference's cloud api-store (reference:
deploy/cloud/api-store/ai_dynamo_store — FastAPI service storing graph
artifacts; here aiohttp, consistent with http/service.py since fastapi
is not in the image). Specs land in the coordinator store under
``{ns}/deployments/{name}`` where the operator-lite reconciler
(operator.py) picks them up.

  GET    /api/v1/deployments            list
  GET    /api/v1/deployments/{name}     fetch
  PUT    /api/v1/deployments/{name}     create/update (JSON body = CRD doc)
  DELETE /api/v1/deployments/{name}     remove
  GET    /api/v1/status                 desired-vs-actual per deployment
  GET    /healthz
"""

from __future__ import annotations

import logging
from typing import Optional

from aiohttp import web

from dynamo_tpu.deploy.operator import Reconciler
from dynamo_tpu.deploy.spec import GraphDeploymentSpec, deployment_key

log = logging.getLogger("dynamo_tpu.deploy.api_store")

MAX_BODY = 1 << 20


class ApiStore:
    """Durable desired state lives in the RECONCILER's state mirror
    (operator.py: restore_state + per-pass sync) — the api-store is a
    thin REST surface over it (reference: the api-store's database
    persistence, deploy/cloud/api-store/ai_dynamo_store/models/).
    ``state_dir`` here forwards onto the reconciler for convenience."""

    def __init__(self, reconciler: Reconciler,
                 host: str = "0.0.0.0", port: int = 8190,
                 state_dir: Optional[str] = None):
        self.reconciler = reconciler
        if state_dir:
            reconciler.state_dir = state_dir
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None
        app = web.Application(client_max_size=MAX_BODY)
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/api/v1/deployments", self._list)
        app.router.add_get("/api/v1/deployments/{name}", self._get)
        app.router.add_put("/api/v1/deployments/{name}", self._put)
        app.router.add_delete("/api/v1/deployments/{name}", self._delete)
        app.router.add_get("/api/v1/status", self._status)
        self.app = app

    async def start(self) -> None:
        if self.reconciler.state_dir:
            await self.reconciler.restore_state()
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            # public API (no aiohttp private internals): the runner
            # exposes every site's bound (host, port)
            self.port = self._runner.addresses[0][1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    # -- handlers ----------------------------------------------------------
    async def _healthz(self, request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    async def _list(self, request: web.Request) -> web.Response:
        specs = await self.reconciler.list_deployments()
        return web.json_response({"items": [s.to_dict() for s in specs]})

    async def _get(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        entry = await self.reconciler.store.kv_get(
            deployment_key(self.reconciler.namespace, name)
        )
        if entry is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(
            GraphDeploymentSpec.from_bytes(entry.value).to_dict()
        )

    async def _put(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        try:
            raw = await request.json()
            spec = GraphDeploymentSpec.from_dict(raw)
        except Exception as exc:
            return web.json_response({"error": str(exc)}, status=400)
        if spec.name != name:
            return web.json_response(
                {"error": f"body name {spec.name!r} != path {name!r}"},
                status=400,
            )
        try:
            await self.reconciler.apply(spec)
        except ValueError as exc:  # e.g. namespace mismatch
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response(spec.to_dict())

    async def _delete(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        deleted = await self.reconciler.delete(name)
        if not deleted:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"deleted": name})

    async def _status(self, request: web.Request) -> web.Response:
        return web.json_response(await self.reconciler.status())
