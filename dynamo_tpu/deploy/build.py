"""Build/packaging pipeline: @service graph → versioned, pushable artifact.

The native analogue of the reference's bento build + cloud push
(reference: deploy/sdk/src/dynamo/sdk/cli/bentos.py builds a versioned
archive of the service graph; deployment.py pushes/pulls it through the
api-store). A package is a tar.gz:

    manifest.json     name, content-derived version, entry module:Attr,
                      per-file sha256, optional component config and an
                      embedded GraphDeploymentSpec document
    src/...           the graph's python source (module file, or the
                      package directory it lives in)
    config.yaml       optional per-component overrides (-f)

Versions are content hashes (first 12 hex of the manifest-core sha256),
so rebuilding identical sources yields the identical version — pushes
are idempotent. Artifacts live in the coordinator store's object plane
under bucket ``packages`` with a ``latest`` pointer in the KV plane;
``dynamo-tpu serve --package name[:version]`` pulls, verifies hashes,
unpacks, and serves the entry.
"""

from __future__ import annotations

import hashlib
import importlib
import io
import json
import os
import tarfile
from dataclasses import dataclass
from typing import Any, Optional

PACKAGES_BUCKET = "packages"


def latest_key(name: str) -> str:
    return f"packages/{name}/latest"


@dataclass
class PackageManifest:
    name: str
    version: str
    entry: str  # "module:Attr"
    files: dict[str, str]  # relpath -> sha256
    config: dict[str, Any]
    deployment: Optional[dict[str, Any]] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "schema": "dynamo-tpu/package.v1",
            "name": self.name,
            "version": self.version,
            "entry": self.entry,
            "files": self.files,
            "config": self.config,
            "deployment": self.deployment,
        }

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "PackageManifest":
        if raw.get("schema") != "dynamo-tpu/package.v1":
            raise ValueError(f"not a dynamo-tpu package: {raw.get('schema')!r}")
        return cls(
            name=raw["name"], version=raw["version"], entry=raw["entry"],
            files=dict(raw["files"]), config=dict(raw.get("config") or {}),
            deployment=raw.get("deployment"),
        )


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _collect_sources(entry: str) -> dict[str, bytes]:
    """Resolve the entry's module to source files. A bare module packs
    one file; a module inside a package packs the package's .py tree
    (what the reference's bento build does with the service's project
    dir)."""
    module_name = entry.split(":")[0]
    mod = importlib.import_module(module_name)
    mod_file = getattr(mod, "__file__", None)
    if not mod_file:
        raise ValueError(f"module {module_name} has no source file")
    files: dict[str, bytes] = {}
    top = module_name.split(".")[0]
    top_mod = importlib.import_module(top)
    top_file = getattr(top_mod, "__file__", "")
    if os.path.basename(top_file) == "__init__.py":
        root = os.path.dirname(top_file)
        base = os.path.dirname(root)
        for dirpath, _dirs, names in os.walk(root):
            for n in sorted(names):
                if n.endswith(".py"):
                    p = os.path.join(dirpath, n)
                    rel = os.path.relpath(p, base)
                    with open(p, "rb") as f:
                        files[rel.replace(os.sep, "/")] = f.read()
    else:
        with open(mod_file, "rb") as f:
            files[os.path.basename(mod_file)] = f.read()
    return files


def build_package(
    entry: str,
    name: Optional[str] = None,
    config_file: Optional[str] = None,
    deployment_spec: Optional[dict[str, Any]] = None,
    out_path: Optional[str] = None,
) -> tuple[str, PackageManifest]:
    """Build the archive; returns (path, manifest). Importing the entry
    validates the graph before anything is packaged."""
    module_name, _, attr = entry.partition(":")
    if not attr:
        raise ValueError("entry must be module:Attr")
    mod = importlib.import_module(module_name)
    svc = getattr(mod, attr)  # raises if absent
    if not hasattr(svc, "graph"):
        raise ValueError(f"{entry} is not a DynamoService (no .graph())")
    graph = svc.graph()

    sources = _collect_sources(entry)
    config: dict[str, Any] = {}
    config_bytes = None
    if config_file:
        import yaml

        with open(config_file, "rb") as f:
            config_bytes = f.read()
        config = yaml.safe_load(config_bytes) or {}

    files = {f"src/{rel}": _sha256(data) for rel, data in sources.items()}
    if config_bytes is not None:
        files["config.yaml"] = _sha256(config_bytes)

    name = name or (attr.lower() if attr else module_name.rsplit(".", 1)[-1])
    core = json.dumps(
        {"name": name, "entry": entry, "files": files, "config": config,
         "deployment": deployment_spec},
        sort_keys=True,
    ).encode()
    version = _sha256(core)[:12]
    manifest = PackageManifest(
        name=name, version=version, entry=entry, files=files,
        config=config, deployment=deployment_spec,
    )

    out_path = out_path or f"{name}-{version}.tar.gz"
    import gzip

    # fully deterministic bytes: zero gzip mtime, no embedded filename,
    # zero tar mtimes — identical sources => identical archive => pushes
    # are idempotent at the blob level too
    with open(out_path, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0, filename="") as gz:
            with tarfile.open(fileobj=gz, mode="w") as tar:

                def add(relname: str, data: bytes) -> None:
                    info = tarfile.TarInfo(relname)
                    info.size = len(data)
                    info.mtime = 0
                    tar.addfile(info, io.BytesIO(data))

                add("manifest.json",
                    json.dumps(manifest.to_dict(), indent=1).encode())
                for rel, data in sources.items():
                    add(f"src/{rel}", data)
                if config_bytes is not None:
                    add("config.yaml", config_bytes)
    return out_path, manifest


def read_manifest(path: str) -> PackageManifest:
    with tarfile.open(path, "r:gz") as tar:
        f = tar.extractfile("manifest.json")
        assert f is not None
        return PackageManifest.from_dict(json.load(f))


async def push_package(store, path: str) -> PackageManifest:
    """Archive → store object plane + latest pointer."""
    manifest = read_manifest(path)
    with open(path, "rb") as f:
        blob = f.read()
    await store.obj_put(
        PACKAGES_BUCKET, f"{manifest.name}:{manifest.version}", blob
    )
    await store.kv_put(latest_key(manifest.name), manifest.version.encode())
    return manifest


async def pull_package(store, name: str,
                       version: Optional[str] = None) -> tuple[bytes, str]:
    """-> (archive bytes, resolved version)."""
    if version is None:
        entry = await store.kv_get(latest_key(name))
        if entry is None:
            raise KeyError(f"no package {name!r}")
        version = entry.value.decode()
    blob = await store.obj_get(PACKAGES_BUCKET, f"{name}:{version}")
    if blob is None:
        raise KeyError(f"no package {name}:{version}")
    return blob, version


def unpack_package(blob: bytes, dest_root: str) -> tuple[str, PackageManifest]:
    """Extract + verify hashes → ({dest_root}/{name}-{version}, manifest).
    The src/ dir inside is importable (add to sys.path)."""
    with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tar:
        mf = tar.extractfile("manifest.json")
        assert mf is not None
        manifest = PackageManifest.from_dict(json.load(mf))
        dest = os.path.join(dest_root, f"{manifest.name}-{manifest.version}")
        os.makedirs(dest, exist_ok=True)
        seen: set[str] = set()
        for member in tar.getmembers():
            if not member.isfile():
                continue
            rel = member.name
            # refuse traversal; verify integrity against the manifest
            if rel.startswith(("/", "..")) or ".." in rel.split("/"):
                raise ValueError(f"unsafe member path {rel!r}")
            f = tar.extractfile(member)
            assert f is not None
            data = f.read()
            if rel != "manifest.json":
                want = manifest.files.get(rel)
                if want is None or _sha256(data) != want:
                    raise ValueError(f"package integrity: {rel} hash mismatch")
                seen.add(rel)
            target = os.path.join(dest, rel)
            os.makedirs(os.path.dirname(target), exist_ok=True)
            with open(target, "wb") as out:
                out.write(data)
        # a truncated/pruned archive with only valid members must not
        # pass: every manifest-listed file has to be present
        missing = set(manifest.files) - seen
        if missing:
            raise ValueError(
                f"package integrity: missing files {sorted(missing)[:5]}"
            )
    return dest, manifest
