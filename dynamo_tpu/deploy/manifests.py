"""K8s manifest generation: GraphDeploymentSpec → real cluster objects.

The native analogue of the reference operator's rendering path
(reference: deploy/cloud/operator/internal/controller/
dynamographdeployment_controller.go builds Deployments/Services from the
DynamoGraphDeployment CR; config/crd/bases/
nvidia.com_dynamographdeployments.yaml defines the CRD). Here the same
round trip is a library + CLI (`dynamo-tpu deploy manifests`): the CRD
document, one apps/v1 Deployment + (where it listens) a Service per
component, a ConfigMap carrying per-component engine config, and the
coordinator-store Deployment/Service — all plain YAML a cluster accepts
(`kubectl apply --dry-run=client`-shaped; no cluster needed to render).

TPU resources use the GKE resource name ``google.com/tpu`` plus the
standard node selectors for topology, replacing the reference's
``nvidia.com/gpu``.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from dynamo_tpu.deploy.spec import API_VERSION, KIND, GraphDeploymentSpec

GROUP = API_VERSION.split("/")[0]
PLURAL = "dynamographdeployments"
DEFAULT_IMAGE = "dynamo-tpu:latest"
STORE_PORT = 4222
HTTP_PORT = 8000

# components whose role implies a listening port worth a Service
_HTTP_ROLES = ("frontend", "http", "processor")


def crd_manifest() -> dict[str, Any]:
    """CustomResourceDefinition for DynamoGraphDeployment (reference:
    config/crd/bases/nvidia.com_dynamographdeployments.yaml)."""
    service_schema = {
        "type": "object",
        "properties": {
            "replicas": {"type": "integer", "minimum": 0},
            "resources": {
                "type": "object",
                "properties": {"tpu": {"type": "integer", "minimum": 0}},
            },
            "config": {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            },
        },
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{PLURAL}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": f"{KIND}List",
                "plural": PLURAL,
                "singular": "dynamographdeployment",
                "shortNames": ["dgd"],
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": API_VERSION.split("/")[1],
                    "served": True,
                    "storage": True,
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "spec": {
                                    "type": "object",
                                    "properties": {
                                        "services": {
                                            "type": "object",
                                            "additionalProperties": service_schema,
                                        }
                                    },
                                },
                                "status": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields": True,
                                },
                            },
                        }
                    },
                    "subresources": {"status": {}},
                }
            ],
        },
    }


def _labels(spec: GraphDeploymentSpec, component: Optional[str] = None) -> dict:
    labels = {
        "app.kubernetes.io/name": "dynamo-tpu",
        "app.kubernetes.io/instance": spec.name,
        "app.kubernetes.io/managed-by": "dynamo-tpu-operator",
    }
    if component:
        labels["dynamo-tpu/component"] = component
    return labels


def store_manifests(
    spec: GraphDeploymentSpec, image: str = DEFAULT_IMAGE
) -> list[dict[str, Any]]:
    """The coordinator store (the native replacement for etcd+NATS) as a
    single-replica Deployment + stable Service."""
    name = f"{spec.name}-store"
    labels = _labels(spec, "store")
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": spec.namespace,
                         "labels": labels},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {
                        "containers": [
                            {
                                "name": "store",
                                "image": image,
                                "command": [
                                    "python", "-m", "dynamo_tpu.cli.main",
                                    "store", "--host", "0.0.0.0",
                                    "--port", str(STORE_PORT),
                                ],
                                "ports": [{"containerPort": STORE_PORT}],
                            }
                        ]
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": spec.namespace,
                         "labels": labels},
            "spec": {
                "selector": labels,
                "ports": [{"port": STORE_PORT, "targetPort": STORE_PORT}],
            },
        },
    ]


def _component_command(spec: GraphDeploymentSpec, component: str,
                       svc_cfg: dict) -> list[str]:
    """The container command for one component. Components carry their
    CLI role in config["command"] (list) or config["role"]; default is
    a dyn:// worker serving the component's endpoint."""
    if svc_cfg.get("command"):
        return list(svc_cfg["command"])
    role = svc_cfg.get("role", "worker")
    store = f"{spec.name}-store"
    base = [
        "python", "-m", "dynamo_tpu.cli.main", "run",
        "--store-host", store, "--store-port", str(STORE_PORT),
    ]
    if role == "frontend":
        return base + ["--in", "http", "--out", "auto",
                       "--http-host", "0.0.0.0",
                       "--http-port", str(HTTP_PORT)]
    out = svc_cfg.get("out", "jax")
    return base + [
        "--in", f"dyn://{spec.namespace}.{component}.generate",
        "--out", out,
        *(["--model-path", svc_cfg["model_path"]]
          if svc_cfg.get("model_path") else []),
    ]


def graph_manifests(
    spec: GraphDeploymentSpec,
    image: str = DEFAULT_IMAGE,
    include_store: bool = True,
    include_cr: bool = True,
) -> list[dict[str, Any]]:
    """All K8s documents for one graph deployment."""
    spec.validate()
    docs: list[dict[str, Any]] = []
    if include_cr:
        docs.append(spec.to_dict())  # the CR itself (operator input)
    if include_store:
        docs.extend(store_manifests(spec, image))
    # one ConfigMap holds every component's engine config
    docs.append(
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {
                "name": f"{spec.name}-config",
                "namespace": spec.namespace,
                "labels": _labels(spec),
            },
            "data": {
                f"{comp}.json": json.dumps(svc.config, indent=1)
                for comp, svc in spec.services.items()
            },
        }
    )
    for comp, svc in spec.services.items():
        labels = _labels(spec, comp)
        container: dict[str, Any] = {
            "name": comp,
            "image": image,
            "command": _component_command(spec, comp, svc.config),
            "env": [
                {"name": "DYN_NAMESPACE", "value": spec.namespace},
                {"name": "DYN_STORE_HOST", "value": f"{spec.name}-store"},
                {"name": "DYN_STORE_PORT", "value": str(STORE_PORT)},
            ],
            "volumeMounts": [
                {"name": "config", "mountPath": "/etc/dynamo-tpu"}
            ],
        }
        pod: dict[str, Any] = {
            "containers": [container],
            "volumes": [
                {
                    "name": "config",
                    "configMap": {"name": f"{spec.name}-config"},
                }
            ],
        }
        role = svc.config.get("role", comp)
        if role in _HTTP_ROLES or svc.config.get("role") == "frontend":
            container["ports"] = [{"containerPort": HTTP_PORT}]
        if svc.tpu_chips > 0:
            container["resources"] = {
                "limits": {"google.com/tpu": svc.tpu_chips},
                "requests": {"google.com/tpu": svc.tpu_chips},
            }
            # GKE TPU scheduling: accelerator + topology node selectors
            topo = svc.config.get("tpu_topology")
            pod["nodeSelector"] = {
                "cloud.google.com/gke-tpu-accelerator": svc.config.get(
                    "tpu_accelerator", "tpu-v5-lite-podslice"
                ),
                **(
                    {"cloud.google.com/gke-tpu-topology": topo}
                    if topo else {}
                ),
            }
        docs.append(
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {
                    "name": f"{spec.name}-{comp}",
                    "namespace": spec.namespace,
                    "labels": labels,
                },
                "spec": {
                    "replicas": svc.replicas,
                    "selector": {"matchLabels": labels},
                    "template": {"metadata": {"labels": labels}, "spec": pod},
                },
            }
        )
        if "ports" in container:
            docs.append(
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": {
                        "name": f"{spec.name}-{comp}",
                        "namespace": spec.namespace,
                        "labels": labels,
                    },
                    "spec": {
                        "selector": labels,
                        "ports": [{"port": HTTP_PORT,
                                   "targetPort": HTTP_PORT}],
                    },
                }
            )
    return docs


def render_yaml(docs: list[dict[str, Any]]) -> str:
    import yaml

    return "---\n".join(
        yaml.safe_dump(d, sort_keys=False, default_flow_style=False)
        for d in docs
    )


def validate_k8s_doc(doc: dict[str, Any]) -> None:
    """Structural validation kubectl's client-side dry run performs:
    apiVersion/kind/metadata.name present, selectors match template
    labels, container commands are string lists."""
    for key in ("apiVersion", "kind"):
        if not doc.get(key):
            raise ValueError(f"manifest missing {key}: {doc}")
    meta = doc.get("metadata") or {}
    if not meta.get("name"):
        raise ValueError(f"{doc['kind']}: metadata.name missing")
    if doc["kind"] == "Deployment":
        spec = doc["spec"]
        sel = spec["selector"]["matchLabels"]
        tmpl_labels = spec["template"]["metadata"]["labels"]
        if any(tmpl_labels.get(k) != v for k, v in sel.items()):
            raise ValueError(f"{meta['name']}: selector ⊄ template labels")
        for c in spec["template"]["spec"]["containers"]:
            if not all(isinstance(x, str) for x in c.get("command", [])):
                raise ValueError(f"{meta['name']}: non-string command args")
