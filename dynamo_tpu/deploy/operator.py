"""Operator-lite: reconciles graph-deployment specs onto running
supervisors.

The native analogue of the reference's K8s operator controllers
(reference: deploy/cloud/operator/internal/controller/
dynamographdeployment_controller.go): level-triggered reconciliation —
read desired state (deployment specs under ``{ns}/deployments/`` in the
coordinator store), observe actual state (supervisor-published replica
counts), and converge by issuing add/remove commands over the
supervisor control subject (the same lever the planner uses,
sdk/serving.py). Scaling remains cooperative: the planner adjusts
replicas *within* a deployment's bounds at runtime; the operator
enforces the declared baseline when specs change or workers die.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.deploy.spec import GraphDeploymentSpec, deployment_key
from dynamo_tpu.planner.connector import LocalConnector
from dynamo_tpu.store.base import Store

log = logging.getLogger("dynamo_tpu.deploy.operator")


@dataclass
class ReconcileResult:
    deployment: str
    actions: list[str] = field(default_factory=list)
    converged: bool = True
    errors: list[str] = field(default_factory=list)


class Reconciler:
    """One reconciler per namespace; drives every deployment under it."""

    def __init__(self, store: Store, namespace: str,
                 interval_s: float = 10.0, max_actions_per_pass: int = 8):
        self.store = store
        self.namespace = namespace
        self.interval_s = interval_s
        # bound convergence speed: a wild spec change scales gradually,
        # and one pass can't wedge the supervisor with a command storm
        self.max_actions = max_actions_per_pass
        self.connector = LocalConnector(store, namespace)
        self._task: Optional[asyncio.Task] = None

    # -- desired/actual ----------------------------------------------------
    async def list_deployments(self) -> list[GraphDeploymentSpec]:
        prefix = deployment_key(self.namespace, "")
        entries = await self.store.kv_get_prefix(prefix)
        specs = []
        for entry in entries:
            try:
                specs.append(GraphDeploymentSpec.from_bytes(entry.value))
            except Exception as exc:
                log.warning("skipping bad deployment spec %s: %s", entry.key, exc)
        return specs

    async def reconcile_once(self) -> list[ReconcileResult]:
        results = []
        for spec in await self.list_deployments():
            results.append(await self._reconcile_deployment(spec))
        return results

    async def _reconcile_deployment(
        self, spec: GraphDeploymentSpec
    ) -> ReconcileResult:
        res = ReconcileResult(deployment=spec.name)
        budget = self.max_actions
        for component, svc in spec.services.items():
            actual = await self.connector.replicas(component)
            if actual is None:
                res.errors.append(f"{component}: no supervisor state")
                res.converged = False
                continue
            delta = svc.replicas - actual
            while delta > 0 and budget > 0:
                ok = await self.connector.add_component(component)
                if not ok:
                    res.errors.append(f"{component}: add failed")
                    res.converged = False
                    break
                res.actions.append(f"+{component}")
                delta -= 1
                budget -= 1
            while delta < 0 and budget > 0:
                ok = await self.connector.remove_component(component)
                if not ok:
                    res.errors.append(f"{component}: remove failed")
                    res.converged = False
                    break
                res.actions.append(f"-{component}")
                delta += 1
                budget -= 1
            if delta != 0:
                res.converged = False  # out of budget this pass
        if res.actions or res.errors:
            log.info(
                "reconciled %s: actions=%s errors=%s",
                spec.name, res.actions, res.errors,
            )
        return res

    # -- loop --------------------------------------------------------------
    async def run(self, shutdown: Optional[asyncio.Event] = None) -> None:
        shutdown = shutdown or asyncio.Event()
        while not shutdown.is_set():
            try:
                await self.reconcile_once()
            except Exception:
                log.exception("reconcile pass failed")
            try:
                await asyncio.wait_for(shutdown.wait(), timeout=self.interval_s)
            except asyncio.TimeoutError:
                pass

    # -- spec CRUD (shared by api-store and the deploy CLI) ---------------
    async def apply(self, spec: GraphDeploymentSpec) -> None:
        spec.validate()
        if spec.namespace != self.namespace:
            # a prod spec applied through a dynamo-namespace reconciler
            # would land where no operator watches it — reject loudly
            raise ValueError(
                f"spec namespace {spec.namespace!r} != reconciler "
                f"namespace {self.namespace!r}"
            )
        await self.store.kv_put(
            deployment_key(self.namespace, spec.name), spec.to_bytes()
        )

    async def delete(self, name: str) -> bool:
        return await self.store.kv_delete(deployment_key(self.namespace, name))

    async def status(self) -> dict:
        """Desired vs actual for every deployment (the CLI's view)."""
        out: dict = {}
        for spec in await self.list_deployments():
            comp_status = {}
            for component, svc in spec.services.items():
                actual = await self.connector.replicas(component)
                comp_status[component] = {
                    "desired": svc.replicas,
                    "actual": actual,
                }
            out[spec.name] = comp_status
        return out
