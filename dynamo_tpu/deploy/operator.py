"""Operator-lite: reconciles graph-deployment specs onto running
supervisors.

The native analogue of the reference's K8s operator controllers
(reference: deploy/cloud/operator/internal/controller/
dynamographdeployment_controller.go): level-triggered reconciliation —
read desired state (deployment specs under ``{ns}/deployments/`` in the
coordinator store), observe actual state (supervisor-published replica
counts), and converge by issuing add/remove commands over the
supervisor control subject (the same lever the planner uses,
sdk/serving.py). Scaling remains cooperative: the planner adjusts
replicas *within* a deployment's bounds at runtime; the operator
enforces the declared baseline when specs change or workers die.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.deploy.spec import GraphDeploymentSpec, deployment_key
from dynamo_tpu.planner.connector import LocalConnector
from dynamo_tpu.store.base import Store

log = logging.getLogger("dynamo_tpu.deploy.operator")


@dataclass
class ReconcileResult:
    deployment: str
    actions: list[str] = field(default_factory=list)
    converged: bool = True
    errors: list[str] = field(default_factory=list)


class KubectlConnector:
    """Reconcile against a real cluster by scaling the per-component
    Deployments the manifest generator emits (``{name}-{component}``).
    Shells out to kubectl (reference: kubernetes_connector.py /
    kube.py patch the CR; here the operator IS the controller, so it
    drives apps/v1 Deployments directly)."""

    def __init__(self, deployment: str, k8s_namespace: str = "default",
                 kubectl: str = "kubectl"):
        self.deployment = deployment
        self.k8s_namespace = k8s_namespace
        self.kubectl = kubectl

    async def _run(self, *argv: str) -> tuple[int, str]:
        proc = await asyncio.create_subprocess_exec(
            self.kubectl, "-n", self.k8s_namespace, *argv,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
        )
        out, _ = await proc.communicate()
        return proc.returncode or 0, out.decode(errors="replace")

    def _dep(self, component: str) -> str:
        return f"deployment/{self.deployment}-{component}"

    async def replicas(self, component: str) -> Optional[int]:
        rc, out = await self._run(
            "get", self._dep(component), "-o",
            "jsonpath={.spec.replicas}",
        )
        if rc != 0:
            log.warning("kubectl get %s failed: %s", component, out.strip())
            return None
        try:
            return int(out.strip() or "0")
        except ValueError:
            return None

    async def set_replicas(self, component: str, n: int) -> bool:
        rc, out = await self._run(
            "scale", self._dep(component), f"--replicas={n}"
        )
        if rc != 0:
            log.warning("kubectl scale %s failed: %s", component, out.strip())
        return rc == 0


def split_json_stream(buf: str) -> tuple[list[str], str]:
    """Split a concatenation of top-level JSON objects (kubectl's
    ``--watch -o json`` output) into complete documents + the
    unfinished tail. Brace counting with string/escape awareness —
    no framing assumptions about pretty-printing or newlines."""
    docs: list[str] = []
    depth = 0
    in_str = False
    esc = False
    start = None
    consumed = 0
    for i, ch in enumerate(buf):
        if esc:
            esc = False
            continue
        if in_str:
            if ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch == "{":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0 and start is not None:
                docs.append(buf[start : i + 1])
                consumed = i + 1
                start = None
    return docs, buf[consumed:]


class CrWatcher:
    """In-cluster desired-state source: watches DynamoGraphDeployment
    CRs through the Kubernetes API and mirrors them into the
    reconciler's store, then writes ``.status`` back after each
    reconcile pass.

    This is the piece that makes ``kubectl apply`` of the rendered CRDs
    (deploy/manifests.py) actually drive the operator, matching the
    reference controller's contract (reference:
    deploy/cloud/operator/internal/controller/
    dynamographdeployment_controller.go — watch CRs, reconcile, update
    CR status). The API surface is ``kubectl get --watch-only
    --output-watch-events -o json`` (a stream of
    {"type": ADDED|MODIFIED|DELETED, "object": {...}} docs) plus
    ``kubectl patch --subresource=status`` — the same kubectl-CLI
    transport the KubectlConnector uses, so one binary dependency
    covers both directions."""

    def __init__(self, reconciler: "Reconciler", k8s_namespace: str = "default",
                 kubectl: str = "kubectl", resync_s: float = 30.0):
        self.rec = reconciler
        self.k8s_namespace = k8s_namespace
        self.kubectl = kubectl
        self.resync_s = resync_s
        self._known: set[str] = set()
        self._last_status: dict[str, str] = {}

    async def _run(self, *argv: str) -> tuple[int, str]:
        try:
            proc = await asyncio.create_subprocess_exec(
                self.kubectl, "-n", self.k8s_namespace, *argv,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT,
            )
        except OSError as exc:
            # kubectl missing / transient fork failure: degrade, never
            # kill the watcher task
            return 127, f"spawn {self.kubectl}: {exc}"
        out, _ = await proc.communicate()
        return proc.returncode or 0, out.decode(errors="replace")

    def _plural(self) -> str:
        from dynamo_tpu.deploy.manifests import PLURAL

        return PLURAL

    def _to_spec(self, obj: dict) -> GraphDeploymentSpec:
        """CR JSON -> spec. The CR's metadata.namespace is the KUBE
        namespace; the reconciler's logical namespace is authoritative
        for store keys (one operator instance serves one of each)."""
        spec = GraphDeploymentSpec.from_dict(obj)
        spec.namespace = self.rec.namespace
        return spec

    async def sync_once(self) -> int:
        """Full resync: make the store's deployment set exactly mirror
        the cluster's CR set. Returns the number of CRs seen."""
        import json

        rc, out = await self._run("get", self._plural(), "-o", "json")
        if rc != 0:
            log.warning("kubectl get CRs failed: %s", out.strip()[:500])
            return -1
        try:
            items = json.loads(out).get("items", [])
        except json.JSONDecodeError:
            log.warning("kubectl get CRs: bad JSON")
            return -1
        want: dict[str, GraphDeploymentSpec] = {}
        for item in items:
            try:
                spec = self._to_spec(item)
                spec.validate()
                want[spec.name] = spec
            except Exception as exc:
                log.warning("skipping bad CR: %s", exc)
        current = {
            s.name: s.to_bytes() for s in await self.rec.list_deployments()
        }
        for spec in want.values():
            await self._apply_if_changed(spec, current)
        # in-cluster mode makes the CR set THE source of desired state
        # (reference semantics): store deployments without a backing CR
        # are removed, including ones applied through other paths and
        # CRs deleted while the watcher was down
        for existing in current:
            if existing not in want:
                await self.rec.delete(existing)
        self._known = set(want)
        return len(want)

    async def _apply_if_changed(
        self,
        spec: GraphDeploymentSpec,
        current: Optional[dict[str, bytes]] = None,
    ) -> None:
        """apply() only when the stored spec differs: a no-op re-put
        would fire the reconciler's prefix-watch wake and a kubectl
        status-patch per deployment on every resync of an idle
        cluster. ``current`` (name -> stored bytes) lets sync_once pay
        one prefix scan for the whole batch."""
        if current is None:
            current = {
                s.name: s.to_bytes()
                for s in await self.rec.list_deployments()
            }
        if current.get(spec.name) == spec.to_bytes():
            return
        await self.rec.apply(spec)

    async def _consume_event(self, doc: str) -> None:
        import json

        try:
            ev = json.loads(doc)
        except json.JSONDecodeError:
            return
        obj = ev.get("object") or {}
        etype = ev.get("type")
        if etype == "DELETED":
            # delete needs only the name — a CR that went invalid before
            # deletion must still leave desired state
            name = (obj.get("metadata") or {}).get("name")
            if name:
                await self.rec.delete(name)
                self._known.discard(name)
            return
        try:
            spec = self._to_spec(obj)
            spec.validate()
        except Exception as exc:
            log.warning("ignoring bad CR event: %s", exc)
            return
        await self._apply_if_changed(spec)
        self._known.add(spec.name)

    async def run(self, shutdown: Optional[asyncio.Event] = None) -> None:
        """Resync, then hold a watch open; events mirror into the store
        (whose prefix-watch wakes the reconciler immediately). A dying
        watch process degrades to resync-by-poll at ``resync_s``."""
        shutdown = shutdown or asyncio.Event()
        while not shutdown.is_set():
            proc = None
            try:
                await self.sync_once()
                proc = await asyncio.create_subprocess_exec(
                    self.kubectl, "-n", self.k8s_namespace, "get",
                    self._plural(), "--watch-only",
                    "--output-watch-events=true", "-o", "json",
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.DEVNULL,
                )
                assert proc.stdout is not None
                tail = ""
                while not shutdown.is_set():
                    try:
                        chunk = await asyncio.wait_for(
                            proc.stdout.read(65536), timeout=self.resync_s
                        )
                    except asyncio.TimeoutError:
                        # quiet stream: resync to catch silent drops but
                        # KEEP the healthy watch process open
                        await self.sync_once()
                        continue
                    if not chunk:
                        break  # watch closed; outer loop resyncs
                    docs, tail = split_json_stream(tail + chunk.decode())
                    for doc in docs:
                        await self._consume_event(doc)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("CR watch failed; retrying")
            finally:
                if proc is not None and proc.returncode is None:
                    try:
                        proc.terminate()
                        await proc.wait()
                    except ProcessLookupError:
                        pass
            await asyncio.sleep(min(5.0, self.resync_s))

    async def write_status(self, results: list[ReconcileResult]) -> None:
        """Patch each CR's status subresource with the pass outcome
        (reference controller parity: CR .status reflects reconcile
        state)."""
        import json

        for r in results:
            state = (
                "failed" if r.errors
                else ("successful" if r.converged else "pending")
            )
            body = json.dumps({
                "status": {
                    "state": state,
                    "lastActions": r.actions[-8:],
                    "errors": r.errors[:8],
                }
            })
            if self._last_status.get(r.deployment) == body:
                # converged clusters reconcile every interval_s: don't
                # spawn a no-op kubectl patch per deployment per pass
                continue
            self._last_status[r.deployment] = body
            rc, out = await self._run(
                "patch", f"{self._plural()}/{r.deployment}",
                "--subresource=status", "--type=merge", "-p", body,
            )
            if rc != 0:
                log.warning(
                    "status patch for %s failed: %s",
                    r.deployment, out.strip()[:300],
                )


class Reconciler:
    """One reconciler per namespace; drives every deployment under it.

    ``connector_factory(spec)`` selects the actuation backend per
    deployment: the default LocalConnector speaks the supervisor
    control subject; a KubectlConnector drives real cluster
    Deployments. Backends expose ``replicas``/``add_component``/
    ``remove_component`` or the absolute ``set_replicas``."""

    def __init__(self, store: Store, namespace: str,
                 interval_s: float = 10.0, max_actions_per_pass: int = 8,
                 connector_factory=None, state_dir: Optional[str] = None):
        self.store = store
        self.namespace = namespace
        self.interval_s = interval_s
        # bound convergence speed: a wild spec change scales gradually,
        # and one pass can't wedge the supervisor with a command storm
        self.max_actions = max_actions_per_pass
        self.connector = LocalConnector(store, namespace)
        self._connector_factory = connector_factory or (
            lambda spec: self.connector
        )
        # durable mirror of DESIRED state: restore_state() seeds the
        # store after a coordinator restart; every reconcile pass then
        # re-syncs the mirror to the store (mirror FOLLOWS store, so
        # deletes through any path — CLI, REST, raw store — propagate
        # and can't resurrect)
        self.state_dir = state_dir
        # optional post-pass hook (CrWatcher.write_status in in-cluster
        # mode: CR .status mirrors each pass's outcome)
        self.on_results = None
        self._task: Optional[asyncio.Task] = None

    # -- desired/actual ----------------------------------------------------
    async def list_deployments(self) -> list[GraphDeploymentSpec]:
        prefix = deployment_key(self.namespace, "")
        entries = await self.store.kv_get_prefix(prefix)
        specs = []
        for entry in entries:
            try:
                specs.append(GraphDeploymentSpec.from_bytes(entry.value))
            except Exception as exc:
                log.warning("skipping bad deployment spec %s: %s", entry.key, exc)
        return specs

    async def reconcile_once(self) -> list[ReconcileResult]:
        results = []
        specs = await self.list_deployments()
        self._sync_mirror(specs)
        for spec in specs:
            results.append(await self._reconcile_deployment(spec))
        return results

    # -- durable desired-state mirror --------------------------------------
    # Mirror files carry this reconciler's namespace prefix: the sync
    # only ever creates/deletes files it owns, so a shared or misaimed
    # state_dir (another namespace's mirror, unrelated user JSON) is
    # never touched.
    def _mirror_prefix(self) -> str:
        return f"dgd.{self.namespace.replace('/', '_')}."

    def _mirror_path(self, name: str) -> str:
        import os

        return os.path.join(
            self.state_dir or "",
            self._mirror_prefix() + name.replace("/", "_") + ".json",
        )

    def _mirror_files(self) -> list[str]:
        import glob
        import os

        return glob.glob(
            os.path.join(self.state_dir or "", self._mirror_prefix() + "*.json")
        )

    def _sync_mirror(self, specs: list[GraphDeploymentSpec]) -> None:
        """Make this namespace's mirror files exactly reflect the
        store's desired state."""
        if not self.state_dir:
            return
        import json
        import os

        try:
            os.makedirs(self.state_dir, exist_ok=True)
            want = {self._mirror_path(s.name): s for s in specs}
            for path, spec in want.items():
                doc = json.dumps(spec.to_dict(), indent=1)
                try:
                    with open(path) as f:
                        if f.read() == doc:
                            continue
                except FileNotFoundError:
                    pass
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(doc)
                os.replace(tmp, path)
            for path in self._mirror_files():
                if path not in want:
                    os.remove(path)
        except OSError:
            log.exception("state mirror sync failed")

    async def restore_state(self) -> int:
        """Seed the store from the mirror after a coordinator restart.
        kv_create only: a live (newer) spec in the store wins."""
        if not self.state_dir:
            return 0
        import json

        restored = 0
        for path in sorted(self._mirror_files()):
            try:
                with open(path) as f:
                    spec = GraphDeploymentSpec.from_dict(json.load(f))
            except Exception as exc:
                log.warning("skipping bad persisted spec %s: %s", path, exc)
                continue
            created = await self.store.kv_create(
                deployment_key(self.namespace, spec.name), spec.to_bytes()
            )
            if created:
                restored += 1
                log.info("restored deployment %s from %s", spec.name, path)
        return restored

    async def _reconcile_deployment(
        self, spec: GraphDeploymentSpec
    ) -> ReconcileResult:
        res = ReconcileResult(deployment=spec.name)
        budget = self.max_actions
        conn = self._connector_factory(spec)
        for component, svc in spec.services.items():
            actual = await conn.replicas(component)
            if actual is None:
                res.errors.append(f"{component}: no supervisor state")
                res.converged = False
                continue
            delta = svc.replicas - actual
            if delta != 0 and hasattr(conn, "set_replicas"):
                # absolute backends (kubectl) converge in one action
                if budget <= 0:
                    res.converged = False
                    continue
                budget -= 1
                if await conn.set_replicas(component, svc.replicas):
                    res.actions.append(f"{component}={svc.replicas}")
                else:
                    res.errors.append(f"{component}: scale failed")
                    res.converged = False
                continue
            while delta > 0 and budget > 0:
                ok = await conn.add_component(component)
                if not ok:
                    res.errors.append(f"{component}: add failed")
                    res.converged = False
                    break
                res.actions.append(f"+{component}")
                delta -= 1
                budget -= 1
            while delta < 0 and budget > 0:
                ok = await conn.remove_component(component)
                if not ok:
                    res.errors.append(f"{component}: remove failed")
                    res.converged = False
                    break
                res.actions.append(f"-{component}")
                delta += 1
                budget -= 1
            if delta != 0:
                res.converged = False  # out of budget this pass
        if res.actions or res.errors:
            log.info(
                "reconciled %s: actions=%s errors=%s",
                spec.name, res.actions, res.errors,
            )
        return res

    # -- loop --------------------------------------------------------------
    async def run(self, shutdown: Optional[asyncio.Event] = None) -> None:
        """Event-driven control loop: a store WATCH on the deployment
        spec prefix triggers an immediate reconcile on every spec
        change (the reference's controller-runtime operator watches its
        CRDs the same way —
        deploy/cloud/operator/internal/controller/*_controller.go);
        ``interval_s`` remains as the periodic resync that catches
        drift in the ACTUAL state (crashed replicas, manual scaling)."""
        shutdown = shutdown or asyncio.Event()
        wake = asyncio.Event()
        watch = None
        pump_task: Optional[asyncio.Task] = None
        try:
            watch = await self.store.watch_prefix(
                deployment_key(self.namespace, "")
            )

            async def pump() -> None:
                try:
                    async for _ev in watch:
                        wake.set()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("spec watch died; falling back to polling")

            pump_task = asyncio.create_task(pump())
        except Exception:
            log.warning("store watch unavailable; reconciling by poll only")
        try:
            while not shutdown.is_set():
                # clear BEFORE reconciling: a spec change landing while
                # the pass is in flight re-sets the event and triggers
                # the next pass instead of being lost until the resync
                wake.clear()
                try:
                    results = await self.reconcile_once()
                    if self.on_results is not None:
                        await self.on_results(results)
                except Exception:
                    log.exception("reconcile pass failed")
                stop_t = asyncio.create_task(shutdown.wait())
                wake_t = asyncio.create_task(wake.wait())
                done, pending = await asyncio.wait(
                    {stop_t, wake_t},
                    timeout=self.interval_s,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                for t in pending:
                    t.cancel()
        finally:
            if pump_task is not None:
                pump_task.cancel()
            if watch is not None:
                try:
                    await watch.close()
                except Exception:
                    pass

    # -- spec CRUD (shared by api-store and the deploy CLI) ---------------
    async def apply(self, spec: GraphDeploymentSpec) -> None:
        spec.validate()
        if spec.namespace != self.namespace:
            # a prod spec applied through a dynamo-namespace reconciler
            # would land where no operator watches it — reject loudly
            raise ValueError(
                f"spec namespace {spec.namespace!r} != reconciler "
                f"namespace {self.namespace!r}"
            )
        await self.store.kv_put(
            deployment_key(self.namespace, spec.name), spec.to_bytes()
        )
        # mirror immediately (don't wait for the next reconcile pass):
        # an apply followed by a coordinator crash must survive
        if self.state_dir:
            self._sync_mirror(await self.list_deployments())

    async def delete(self, name: str) -> bool:
        deleted = await self.store.kv_delete(
            deployment_key(self.namespace, name)
        )
        if deleted and self.state_dir:
            self._sync_mirror(await self.list_deployments())
        return deleted

    async def status(self) -> dict:
        """Desired vs actual for every deployment (the CLI's view)."""
        out: dict = {}
        for spec in await self.list_deployments():
            comp_status = {}
            conn = self._connector_factory(spec)
            for component, svc in spec.services.items():
                actual = await conn.replicas(component)
                comp_status[component] = {
                    "desired": svc.replicas,
                    "actual": actual,
                }
            out[spec.name] = comp_status
        return out
