"""Graph-deployment spec: the declarative desired state the operator
reconciles toward.

The YAML shape mirrors the reference's ``DynamoGraphDeployment`` CRD
(reference: deploy/cloud/operator/api/v1alpha1/,
config/crd/bases/nvidia.com_dynamographdeployments.yaml): apiVersion/
kind/metadata/spec with per-service replica counts and resources —
resources here are TPU chips/topology rather than GPUs.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

API_VERSION = "dynamo-tpu.dev/v1alpha1"
KIND = "DynamoGraphDeployment"

MAX_REPLICAS = 1024


@dataclass
class ServiceSpec:
    replicas: int = 1
    tpu_chips: int = 0  # chips per replica (0 = cpu-only component)
    config: dict[str, Any] = field(default_factory=dict)

    def validate(self, name: str) -> None:
        if not 0 <= self.replicas <= MAX_REPLICAS:
            raise ValueError(f"{name}: replicas {self.replicas} out of range")
        if self.tpu_chips < 0:
            raise ValueError(f"{name}: negative tpu_chips")


@dataclass
class GraphDeploymentSpec:
    name: str
    namespace: str = "dynamo"
    services: dict[str, ServiceSpec] = field(default_factory=dict)

    def validate(self) -> None:
        if not self.name or "/" in self.name:
            raise ValueError(f"bad deployment name {self.name!r}")
        if not self.services:
            raise ValueError(f"{self.name}: no services")
        for sname, svc in self.services.items():
            svc.validate(sname)

    # -- serialization -----------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": KIND,
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "services": {
                    n: {
                        "replicas": s.replicas,
                        "resources": {"tpu": s.tpu_chips},
                        "config": s.config,
                    }
                    for n, s in self.services.items()
                }
            },
        }

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "GraphDeploymentSpec":
        if raw.get("kind") not in (None, KIND):
            raise ValueError(f"unexpected kind {raw.get('kind')!r}")
        meta = raw.get("metadata") or {}
        services = {}
        for name, s in ((raw.get("spec") or {}).get("services") or {}).items():
            services[name] = ServiceSpec(
                replicas=int(s.get("replicas", 1)),
                tpu_chips=int((s.get("resources") or {}).get("tpu", 0)),
                config=dict(s.get("config") or {}),
            )
        spec = cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "dynamo"),
            services=services,
        )
        spec.validate()
        return spec

    def to_bytes(self) -> bytes:
        return json.dumps(self.to_dict()).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "GraphDeploymentSpec":
        return cls.from_dict(json.loads(raw.decode()))

    @classmethod
    def from_yaml_file(cls, path: str) -> "GraphDeploymentSpec":
        import yaml

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))


def deployment_key(namespace: str, name: str) -> str:
    """Store key the api-store writes and the operator watches."""
    return f"{namespace}/deployments/{name}"
