"""Disaggregated prefill/decode serving (xPyD).

Reference: docs/disagg_serving.md + examples/llm/components/{worker.py,
prefill_worker.py} + the NIXL transfer plane. TPU-native redesign: remote
prefill delivers content-addressed KV blocks into the decode worker's G2
host tier over the transfer plane, and the existing KVBM onboarding path
pulls them into HBM at admission — so disaggregation composes with (and
reuses) the offload machinery instead of needing RDMA block descriptors.
"""

from dynamo_tpu.disagg.protocols import DisaggConfig, RemotePrefillRequest
from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.disagg.router import DisaggRouter

__all__ = [
    "DisaggConfig",
    "RemotePrefillRequest",
    "PrefillQueue",
    "DisaggRouter",
]
