"""Durable prefill work queue on the coordinator store.

Reference: the NATS JetStream stream "{ns}_prefill_queue"
(examples/llm/utils/prefill_queue.py:24-56, utils/nats_queue.py:82-103).
The store's queue primitive gives the same at-least-once semantics:
``pop`` leases a message, ``ack`` retires it; an un-acked message is
redelivered after its visibility timeout (prefill worker death ⇒ another
worker picks the request up).
"""

from __future__ import annotations

from typing import Optional

from dynamo_tpu import faults
from dynamo_tpu.disagg.protocols import RemotePrefillRequest, queue_name
from dynamo_tpu.store.base import Store


class PrefillQueue:
    def __init__(self, store: Store, namespace: str):
        self._store = store
        self._queue = queue_name(namespace)

    async def enqueue(self, req: RemotePrefillRequest) -> int:
        return await self._store.queue_push(self._queue, req.to_bytes())

    async def dequeue(
        self, timeout_s: float = 1.0
    ) -> Optional[tuple[int, RemotePrefillRequest]]:
        if faults.ACTIVE is not None:
            # injected dequeue faults: delays model a backed-up queue,
            # errors a flapping coordinator (the worker loop's retry/
            # redelivery path absorbs both)
            await faults.ACTIVE.fire_async("prefill.dequeue", queue=self._queue)
        msg = await self._store.queue_pop(self._queue, timeout_s=timeout_s)
        if msg is None:
            return None
        return msg.id, RemotePrefillRequest.from_bytes(msg.payload)

    async def ack(self, msg_id: int) -> bool:
        return await self._store.queue_ack(self._queue, msg_id)

    async def depth(self) -> int:
        return await self._store.queue_len(self._queue)
