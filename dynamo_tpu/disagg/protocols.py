"""Disaggregation protocol types.

Reference: the RemotePrefillParams/RemotePrefillRequest types the vLLM
patch adds (patch :4181) and DisaggRouterConf (lib/llm/src/
disagg_router.rs:24-262). Here a remote-prefill request carries the
prompt tokens plus the *store key* of the decode worker's transfer
metadata — the prefill worker computes KV, looks up that key, and pushes
content-addressed blocks directly; no GPU descriptor exchange is needed
because blocks are addressed by chained content hash on both sides.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field, fields
from typing import Optional


def _known_fields(cls, data: dict) -> dict:
    """Drop unknown keys before constructing: queue/store payloads are
    read by whatever worker version pops them, so a NEWER sender's extra
    field must not crash an older-schema reader (and vice versa)."""
    known = {f.name for f in fields(cls)}
    return {k: v for k, v in data.items() if k in known}


@dataclass
class RemotePrefillRequest:
    request_id: str
    token_ids: list[int]
    block_size: int
    transfer_key: str  # store key holding the decode worker's TransferMetadata
    # trace context ({"trace_id", "span_id"}) so the prefill worker's
    # spans join the decode request's trace (telemetry/spans.py);
    # optional: payloads from older workers simply lack it
    trace: Optional[dict] = None
    # request deadline as a wall-clock epoch instant (time.time()); a
    # prefill worker popping an expired message acks + skips it instead
    # of computing KV nobody will wait for. Wall clock is deliberate:
    # the queue crosses processes/hosts, and coarse deadline skew is
    # harmless (the decode side enforces its own monotonic budget).
    deadline_ts: Optional[float] = None

    def to_bytes(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "RemotePrefillRequest":
        return cls(**_known_fields(cls, json.loads(raw.decode())))


@dataclass
class DisaggConfig:
    """Conditional-disaggregation knobs (reference: DisaggRouterConf —
    max_local_prefill_length etc., disagg_router.rs:24; queue threshold
    from examples/llm/components/disagg_router.py)."""

    enabled: bool = False
    max_local_prefill_length: int = 512  # tokens prefilled locally at most
    max_prefill_queue_size: int = 16  # back off to local beyond this depth
    transfer_timeout_s: float = 30.0  # then fall back to local prefill

    def to_bytes(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "DisaggConfig":
        return cls(**_known_fields(cls, json.loads(raw.decode())))


def conf_key(namespace: str) -> str:
    return f"{namespace}/disagg/conf"


def queue_name(namespace: str) -> str:
    return f"{namespace}_prefill_queue"


def transfer_key(namespace: str, worker_id: int) -> str:
    return f"{namespace}/transfer/{worker_id:x}"
