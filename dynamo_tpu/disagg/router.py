"""Conditional disaggregation router.

Decides per-request whether the prefill runs locally on the decode
worker or is shipped to a dedicated prefill worker (reference decision
logic: examples/llm/components/disagg_router.py — remote iff the
un-cached prefill length exceeds the threshold AND the prefill queue is
not backed up; config hot-reloaded from etcd via
lib/llm/src/disagg_router.rs:38-100 — here from a store watch).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from typing import Optional

from dynamo_tpu.disagg.protocols import DisaggConfig, conf_key
from dynamo_tpu.store.base import Store
from dynamo_tpu.utils.tasks import spawn

log = logging.getLogger("dynamo_tpu.disagg.router")


class DisaggRouter:
    def __init__(self, conf: DisaggConfig):
        self.conf = conf
        self._watch_task: Optional[asyncio.Task] = None

    @classmethod
    async def create(
        cls,
        store: Store,
        namespace: str,
        default: Optional[DisaggConfig] = None,
        watch: bool = True,
    ) -> "DisaggRouter":
        """Load config from the store (publishing the default if absent)
        and keep it hot-reloaded via a prefix watch."""
        key = conf_key(namespace)
        conf = default or DisaggConfig(enabled=True)
        entry = await store.kv_get(key)
        if entry is None:
            await store.kv_create(key, conf.to_bytes())
        else:
            conf = DisaggConfig.from_bytes(entry.value)
        router = cls(conf)
        if watch:
            w = await store.watch_prefix(key)

            async def _follow() -> None:
                async for ev in w:
                    if ev.entry is not None and ev.type == "put":
                        try:
                            router.conf = DisaggConfig.from_bytes(ev.entry.value)
                            log.info("disagg conf updated: %s", router.conf)
                        except Exception:
                            log.exception("bad disagg conf update ignored")

            # spawn (not bare create_task): the registry pins the task
            # against GC and a crash in the watch loop is logged instead
            # of dying silently with the config frozen at its last value
            router._watch_task = spawn(_follow(), name="disagg-conf-watch")
        return router

    def should_prefill_remote(self, prefill_len: int, queue_depth: int) -> bool:
        c = self.conf
        return (
            c.enabled
            and prefill_len > c.max_local_prefill_length
            and queue_depth < c.max_prefill_queue_size
        )

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._watch_task
