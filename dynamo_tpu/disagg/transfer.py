"""KV transfer plane: descriptor-addressed block shipment between workers.

The TPU-native replacement for the reference's NIXL data plane
(lib/llm/src/block_manager/{layout/nixl.rs,block/transfer/nixl.rs},
docs/backend.md:427-516): an agent per worker with published metadata,
async block writes, and completion notifications. Differences by design:

- blocks are addressed by **content hash** (the chained TokenBlock
  sequence hash both sides compute from the prompt), not by remote
  memory descriptors — no address exchange, free dedup;
- the wire is a host-staged TCP stream (DCN path). Within a slice, KV
  never needs this plane at all: a slice is one jax process group and
  the mesh moves KV over ICI as array shards;
- delivery lands in the receiver's G2 host tier; the engine's KVBM
  onboarding lifts blocks into HBM at admission (manager.py onboard()).

Wire format per message: 4-byte big-endian header length, JSON header
{request_id, hashes, dtype, shape, head_start?, head_count?}, then raw
packed-block bytes. One reply line {"ok": bool}.

TP-mismatch resharding (reference: Triton kv_rearrange kernels in the
vLLM patch :914-1046) is handled here on the logical layout: a sender
whose KV cache is tensor-parallel over fewer/more ranks than the
receiver ships its head slice tagged with ``head_start/head_count``;
the server assembles slices into full-head blocks (ops/kv_rearrange.py
owns the rank→head-range mapping) and delivers once every head has
landed. Mixed float dtypes are cast to the receiver's layout dtype.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import time
from dataclasses import asdict, dataclass
from typing import Awaitable, Callable, Optional

import numpy as np

from dynamo_tpu import faults
from dynamo_tpu.disagg.protocols import transfer_key
from dynamo_tpu.kvbm.layout import BlockLayout, resolve_dtype
from dynamo_tpu.ops.kv_rearrange import cast_packed
from dynamo_tpu.store.base import Store
from dynamo_tpu.telemetry import get_tracer
from dynamo_tpu.telemetry.instruments import (
    KV_TRANSFER_BLOCKS,
    KV_TRANSFER_BYTES,
    KV_TRANSFER_SECONDS,
)

log = logging.getLogger("dynamo_tpu.disagg.transfer")

# deliver(hashes, packed) -> awaitable; runs the engine-thread insert
DeliverFn = Callable[[list[int], np.ndarray], Awaitable[None]]

# float dtypes the receiver will cast from (bounds itemsize too)
_CASTABLE = {"bfloat16", "float16", "float32", "float8_e4m3fn"}


class _HeadAssembler:
    """Accumulates per-rank head slices of a block batch until the full
    head range is covered, then yields the assembled array once."""

    def __init__(self, num_blocks: int, packed_shape: tuple, dtype: np.dtype):
        self.data = np.zeros((num_blocks, *packed_shape), dtype=dtype)
        self.covered = np.zeros(packed_shape[-2], dtype=bool)  # per KV head
        self.created = time.monotonic()

    def add(self, head_start: int, part: np.ndarray) -> bool:
        n = part.shape[-2]
        self.data[..., head_start : head_start + n, :] = part
        self.covered[head_start : head_start + n] = True
        return bool(self.covered.all())


@dataclass
class TransferMetadata:
    """Published under {ns}/transfer/{worker_id:x} with the worker's
    lease (≈ NIXL metadata in etcd, docs/disagg_serving.md:87)."""

    host: str
    port: int
    worker_id: int
    layout: str  # BlockLayout JSON

    def to_bytes(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TransferMetadata":
        return cls(**json.loads(raw.decode()))


MAX_BLOCKS_PER_TRANSFER = 4096


class TransferServer:
    """Receives packed KV blocks and hands them to the engine."""

    def __init__(
        self,
        deliver: DeliverFn,
        layout: BlockLayout,
        host: str = "127.0.0.1",
    ):
        self._deliver = deliver
        self._layout = layout
        self._host = host
        self._server: Optional[asyncio.base_events.Server] = None
        self.port: int = 0
        self._done: dict[str, asyncio.Event] = {}
        # (request_id, hashes) -> partial-head assembly in flight.
        # Bounded by resident BYTES (a partial header claims a full-size
        # buffer, so a hash-count cap alone would let a peer amplify a
        # tiny payload into huge allocations) and by a TTL (a dead
        # sender must not pin buffers forever). At capacity new partial
        # transfers are REJECTED, never evicted: an evicted assembly's
        # earlier slices were already acked ok=true and would be lost
        # silently.
        self._assembling: dict[tuple, _HeadAssembler] = {}
        self.MAX_ASSEMBLY_BYTES = 1 << 30
        self.ASSEMBLER_TTL_S = 120.0
        # keys whose assembly was purged/abandoned: late slices for them
        # must be REJECTED (ok=false), not silently re-seeded — earlier
        # slices were acked and lost, so a fresh assembly could never
        # complete while both senders believe they succeeded
        self._dead_keys: "collections.OrderedDict[tuple, None]" = (
            collections.OrderedDict()
        )
        self.MAX_DEAD_KEYS = 1024

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self._host, 0
        )
        self.port = self._server.sockets[0].getsockname()[1]

    def completion_event(self, request_id: str) -> asyncio.Event:
        return self._done.setdefault(request_id, asyncio.Event())

    def discard_completion(self, request_id: str) -> None:
        self._done.pop(request_id, None)
        # drop any partial assembly for the abandoned request too
        for key in [k for k in self._assembling if k[0] == request_id]:
            self._kill_assembly(key)

    def _kill_assembly(self, key: tuple) -> None:
        del self._assembling[key]
        self._dead_keys[key] = None
        while len(self._dead_keys) > self.MAX_DEAD_KEYS:
            self._dead_keys.popitem(last=False)

    def _purge_stale_assemblers(self) -> None:
        now = time.monotonic()
        for key in [
            k for k, a in self._assembling.items()
            if now - a.created > self.ASSEMBLER_TTL_S
        ]:
            log.warning("dropping expired partial transfer %s", key[0])
            self._kill_assembly(key)

    def _assembly_bytes(self) -> int:
        return sum(a.data.nbytes for a in self._assembling.values())

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            hdr_len = int.from_bytes(await reader.readexactly(4), "big")
            if hdr_len > 1 << 20:
                raise ValueError("oversized transfer header")
            header = json.loads((await reader.readexactly(hdr_len)).decode())
            if faults.ACTIVE is not None:
                # receiver-side injection: an error here NACKs the
                # transfer (sender retries/fails); a delay models a slow
                # delivery into the host tier
                await faults.ACTIVE.fire_async(
                    "kv_transfer.get",
                    request_id=header.get("request_id", ""),
                )
            shape = tuple(int(d) for d in header["shape"])
            hashes = [int(h) for h in header["hashes"]]
            full_heads = self._layout.packed_shape[-2]
            head_start = int(header.get("head_start", 0))
            head_count = int(header.get("head_count", full_heads))
            if not (0 <= head_start and head_start + head_count <= full_heads
                    and head_count > 0):
                raise ValueError(
                    f"head slice [{head_start},+{head_count}) out of range "
                    f"for {full_heads} heads"
                )
            # validate against OUR layout before buffering anything: the
            # socket is unauthenticated, the peer's shape claim is not
            # trusted (bounds the allocation too)
            slice_shape = (*self._layout.packed_shape[:-2], head_count,
                           self._layout.packed_shape[-1])
            expected = (len(hashes), *slice_shape)
            if shape != expected or len(hashes) > MAX_BLOCKS_PER_TRANSFER:
                raise ValueError(
                    f"transfer shape {shape} != expected {expected}"
                )
            if header["dtype"] not in _CASTABLE:
                raise ValueError(f"transfer dtype {header['dtype']} not castable")
            dtype = resolve_dtype(header["dtype"])
            payload = await reader.readexactly(int(np.prod(shape)) * dtype.itemsize)
            KV_TRANSFER_BYTES.labels("recv").inc(len(payload))
            KV_TRANSFER_BLOCKS.labels("recv").inc(len(hashes))
            packed = cast_packed(
                np.frombuffer(payload, dtype=dtype).reshape(shape),
                self._layout.np_dtype,
            )
            if head_count == full_heads:
                await self._deliver(hashes, packed)
            else:
                akey = (header.get("request_id", ""), tuple(hashes))
                asm = self._assembling.get(akey)
                if asm is None and akey in self._dead_keys:
                    raise ValueError(
                        "late slice for a purged/abandoned assembly"
                    )
                if asm is None:
                    self._purge_stale_assemblers()
                    new_bytes = (
                        len(hashes) * self._layout.block_bytes
                    )
                    if (self._assembly_bytes() + new_bytes
                            > self.MAX_ASSEMBLY_BYTES):
                        raise ValueError(
                            "partial-transfer assembly budget exhausted"
                        )
                    asm = _HeadAssembler(
                        len(hashes), self._layout.packed_shape,
                        self._layout.np_dtype,
                    )
                    self._assembling[akey] = asm
                if asm.add(head_start, packed):
                    del self._assembling[akey]
                    await self._deliver(hashes, asm.data)
                else:
                    # acknowledge the slice; completion fires on last one
                    writer.write(json.dumps({"ok": True}).encode() + b"\n")
                    await writer.drain()
                    return
            rid = header.get("request_id", "")
            # only signal an event a local waiter created; a late delivery
            # after discard_completion must not re-create (and leak) one
            ev = self._done.get(rid)
            if ev is not None:
                ev.set()
            writer.write(json.dumps({"ok": True}).encode() + b"\n")
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            log.exception("transfer receive failed")
            try:
                writer.write(json.dumps({"ok": False}).encode() + b"\n")
                await writer.drain()
            except Exception:
                pass
        finally:
            writer.close()

    async def register(self, store: Store, namespace: str, worker_id: int,
                       layout: BlockLayout, lease_id: int,
                       advertise_host: Optional[str] = None) -> str:
        meta = TransferMetadata(
            host=advertise_host or self._host,
            port=self.port,
            worker_id=worker_id,
            layout=layout.to_json(),
        )
        key = transfer_key(namespace, worker_id)
        await store.kv_put(key, meta.to_bytes(), lease_id=lease_id)
        return key

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class TransferClient:
    @staticmethod
    async def fetch_metadata(store: Store, key: str) -> Optional[TransferMetadata]:
        entry = await store.kv_get(key)
        return TransferMetadata.from_bytes(entry.value) if entry else None

    @staticmethod
    async def put(
        meta: TransferMetadata,
        request_id: str,
        hashes: list[int],
        packed: np.ndarray,
        timeout_s: float = 30.0,
        connect_timeout_s: float = 5.0,
        head_start: int = 0,
        head_count: Optional[int] = None,
        trace: Optional[dict] = None,
    ) -> bool:
        """Ship packed blocks to a peer; True on acknowledged delivery.
        ``head_start/head_count`` tag a TP head slice (ops/kv_rearrange);
        omitted means full heads. ``trace`` links the transfer span into
        the request's trace. Every stage is bounded: a stale or
        unroutable peer address must not stall the prefill worker."""
        span = get_tracer().span(
            "kv_transfer.put", parent=trace,
            attrs={"service": "prefill", "blocks": len(hashes),
                   "bytes": int(packed.nbytes)},
        )
        t0 = time.monotonic()
        ok = False
        try:
            if faults.ACTIVE is not None:
                # sender-side injection: drop/error surfaces as a failed
                # put, which the prefill worker's bounded retry absorbs
                await faults.ACTIVE.fire_async(
                    "kv_transfer.put", request_id=request_id
                )
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(meta.host, meta.port),
                timeout=connect_timeout_s,
            )
            try:
                hdr: dict = {
                    "request_id": request_id,
                    "hashes": [int(h) for h in hashes],
                    "dtype": packed.dtype.name,
                    "shape": list(packed.shape),
                }
                if head_count is not None:
                    hdr["head_start"] = head_start
                    hdr["head_count"] = head_count
                header = json.dumps(hdr).encode()
                writer.write(len(header).to_bytes(4, "big") + header)
                writer.write(packed.tobytes())
                await asyncio.wait_for(writer.drain(), timeout=timeout_s)
                line = await asyncio.wait_for(reader.readline(), timeout=timeout_s)
                ok = bool(json.loads(line.decode()).get("ok"))
                return ok
            finally:
                writer.close()
        finally:
            KV_TRANSFER_SECONDS.labels("send").observe(time.monotonic() - t0)
            if ok:
                KV_TRANSFER_BYTES.labels("send").inc(int(packed.nbytes))
                KV_TRANSFER_BLOCKS.labels("send").inc(len(hashes))
            else:
                span.set_attr("error", "rejected-or-failed")
            span.end()
