"""KV transfer plane: descriptor-addressed block shipment between workers.

The TPU-native replacement for the reference's NIXL data plane
(lib/llm/src/block_manager/{layout/nixl.rs,block/transfer/nixl.rs},
docs/backend.md:427-516): an agent per worker with published metadata,
async block writes, and completion notifications. Differences by design:

- blocks are addressed by **content hash** (the chained TokenBlock
  sequence hash both sides compute from the prompt), not by remote
  memory descriptors — no address exchange, free dedup;
- the wire is a host-staged TCP stream (DCN path). Within a slice, KV
  never needs this plane at all: a slice is one jax process group and
  the mesh moves KV over ICI as array shards;
- delivery lands in the receiver's G2 host tier; the engine's KVBM
  onboarding lifts blocks into HBM at admission (manager.py onboard()).

Wire format per message: 4-byte big-endian header length, JSON header
{request_id, hashes, dtype, shape}, then raw packed-block bytes. One
reply line {"ok": bool}.
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import asdict, dataclass
from typing import Awaitable, Callable, Optional

import numpy as np

from dynamo_tpu.disagg.protocols import transfer_key
from dynamo_tpu.kvbm.layout import BlockLayout, resolve_dtype
from dynamo_tpu.store.base import Store

log = logging.getLogger("dynamo_tpu.disagg.transfer")

# deliver(hashes, packed) -> awaitable; runs the engine-thread insert
DeliverFn = Callable[[list[int], np.ndarray], Awaitable[None]]


@dataclass
class TransferMetadata:
    """Published under {ns}/transfer/{worker_id:x} with the worker's
    lease (≈ NIXL metadata in etcd, docs/disagg_serving.md:87)."""

    host: str
    port: int
    worker_id: int
    layout: str  # BlockLayout JSON

    def to_bytes(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TransferMetadata":
        return cls(**json.loads(raw.decode()))


MAX_BLOCKS_PER_TRANSFER = 4096


class TransferServer:
    """Receives packed KV blocks and hands them to the engine."""

    def __init__(
        self,
        deliver: DeliverFn,
        layout: BlockLayout,
        host: str = "127.0.0.1",
    ):
        self._deliver = deliver
        self._layout = layout
        self._host = host
        self._server: Optional[asyncio.base_events.Server] = None
        self.port: int = 0
        self._done: dict[str, asyncio.Event] = {}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self._host, 0
        )
        self.port = self._server.sockets[0].getsockname()[1]

    def completion_event(self, request_id: str) -> asyncio.Event:
        return self._done.setdefault(request_id, asyncio.Event())

    def discard_completion(self, request_id: str) -> None:
        self._done.pop(request_id, None)

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            hdr_len = int.from_bytes(await reader.readexactly(4), "big")
            if hdr_len > 1 << 20:
                raise ValueError("oversized transfer header")
            header = json.loads((await reader.readexactly(hdr_len)).decode())
            shape = tuple(int(d) for d in header["shape"])
            hashes = [int(h) for h in header["hashes"]]
            # validate against OUR layout before buffering anything: the
            # socket is unauthenticated, the peer's shape claim is not
            # trusted (bounds the allocation too)
            expected = (len(hashes), *self._layout.packed_shape)
            if shape != expected or len(hashes) > MAX_BLOCKS_PER_TRANSFER:
                raise ValueError(
                    f"transfer shape {shape} != expected {expected}"
                )
            dtype = resolve_dtype(header["dtype"])
            if dtype != self._layout.np_dtype:
                raise ValueError(
                    f"transfer dtype {dtype} != layout {self._layout.np_dtype}"
                )
            payload = await reader.readexactly(int(np.prod(shape)) * dtype.itemsize)
            packed = np.frombuffer(payload, dtype=dtype).reshape(shape)
            await self._deliver(hashes, packed)
            rid = header.get("request_id", "")
            # only signal an event a local waiter created; a late delivery
            # after discard_completion must not re-create (and leak) one
            ev = self._done.get(rid)
            if ev is not None:
                ev.set()
            writer.write(json.dumps({"ok": True}).encode() + b"\n")
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            log.exception("transfer receive failed")
            try:
                writer.write(json.dumps({"ok": False}).encode() + b"\n")
                await writer.drain()
            except Exception:
                pass
        finally:
            writer.close()

    async def register(self, store: Store, namespace: str, worker_id: int,
                       layout: BlockLayout, lease_id: int,
                       advertise_host: Optional[str] = None) -> str:
        meta = TransferMetadata(
            host=advertise_host or self._host,
            port=self.port,
            worker_id=worker_id,
            layout=layout.to_json(),
        )
        key = transfer_key(namespace, worker_id)
        await store.kv_put(key, meta.to_bytes(), lease_id=lease_id)
        return key

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class TransferClient:
    @staticmethod
    async def fetch_metadata(store: Store, key: str) -> Optional[TransferMetadata]:
        entry = await store.kv_get(key)
        return TransferMetadata.from_bytes(entry.value) if entry else None

    @staticmethod
    async def put(
        meta: TransferMetadata,
        request_id: str,
        hashes: list[int],
        packed: np.ndarray,
        timeout_s: float = 30.0,
        connect_timeout_s: float = 5.0,
    ) -> bool:
        """Ship packed blocks to a peer; True on acknowledged delivery.
        Every stage is bounded: a stale/unroutable peer address must not
        stall the (sequential) prefill worker."""
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(meta.host, meta.port),
            timeout=connect_timeout_s,
        )
        try:
            header = json.dumps(
                {
                    "request_id": request_id,
                    "hashes": [int(h) for h in hashes],
                    "dtype": packed.dtype.name,
                    "shape": list(packed.shape),
                }
            ).encode()
            writer.write(len(header).to_bytes(4, "big") + header)
            writer.write(packed.tobytes())
            await asyncio.wait_for(writer.drain(), timeout=timeout_s)
            line = await asyncio.wait_for(reader.readline(), timeout=timeout_s)
            return bool(json.loads(line.decode()).get("ok"))
        finally:
            writer.close()
