"""Disaggregated worker roles.

Decode side (``DisaggDecodeEngine``): wraps the JaxEngine's adapter; per
request it measures the un-cached prefill length, consults the
``DisaggRouter``, and either runs locally or enqueues a
``RemotePrefillRequest`` and waits for the KV blocks to land in its host
tier before submitting — at which point admission onboards them and only
the prompt tail is prefilled locally (reference flow:
examples/llm/components/worker.py:186-235; transfer timeout falls back
to a plain local prefill, so disagg can only *add* latency headroom, not
availability risk).

Prefill side (``run_prefill_worker``): pops the queue, prefills with
max_tokens=1, exports the prompt's content-addressed blocks, ships them
to the decode worker's transfer server, acks (reference:
examples/llm/components/prefill_worker.py:139-207). On shutdown it
drains in-flight work before exiting, like the reference's SIGTERM
drain (prefill_worker.py:164-176).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.disagg.prefill_queue import PrefillQueue
from dynamo_tpu.disagg.protocols import (
    DisaggConfig,
    RemotePrefillRequest,
    transfer_key,
)
from dynamo_tpu.disagg.router import DisaggRouter
from dynamo_tpu.disagg.transfer import TransferClient, TransferServer
from dynamo_tpu.engine.engine import JaxEngine
from dynamo_tpu.kvbm import BlockLayout
from dynamo_tpu.protocols.common import PreprocessedRequest
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream
from dynamo_tpu.store.base import Store
from dynamo_tpu.telemetry import autopsy, get_tracer, propagation_context
from dynamo_tpu.telemetry.instruments import (
    DEADLINE_EXPIRED,
    DISAGG_LOCAL_FALLBACKS,
    DISAGG_REMOTE_PREFILLS,
    PREFILL_QUEUE_DEPTH,
    PREFILL_QUEUE_WAIT,
)
from dynamo_tpu.tokens import TokenBlockSequence

log = logging.getLogger("dynamo_tpu.disagg.worker")


class DisaggDecodeEngine(AsyncEngine):
    """Decode-worker engine with conditional remote prefill."""

    def __init__(
        self,
        engine: JaxEngine,
        store: Store,
        namespace: str,
        router: DisaggRouter,
        server: TransferServer,
        my_transfer_key: str,
    ):
        self.engine = engine
        self.store = store
        self.namespace = namespace
        self.router = router
        self.server = server
        self.my_transfer_key = my_transfer_key
        self.queue = PrefillQueue(store, namespace)
        self.remote_prefills = 0
        self.local_fallbacks = 0

    @classmethod
    async def create(
        cls,
        engine: JaxEngine,
        store: Store,
        namespace: str,
        worker_id: int,
        lease_id: int,
        conf: DisaggConfig,
        advertise_host: str = "127.0.0.1",
    ) -> "DisaggDecodeEngine":
        if engine.kvbm is None:
            raise ValueError(
                "disagg decode requires host_kv_blocks > 0 (remote KV "
                "lands in the G2 host tier)"
            )
        router = await DisaggRouter.create(store, namespace, default=conf)
        assert engine.model_config is not None
        layout = BlockLayout.for_model(
            engine.model_config, engine.config.block_size,
            engine.config.wire_kv_dtype(),
        )
        server = TransferServer(
            deliver=lambda hashes, packed: engine.import_kv_blocks(hashes, packed),
            layout=layout,
            host="0.0.0.0",
        )
        await server.start()
        key = await server.register(
            store, namespace, worker_id, layout, lease_id,
            advertise_host=advertise_host,
        )
        return cls(engine, store, namespace, router, server, key)

    async def _maybe_remote_prefill(
        self, request: PreprocessedRequest, context: Optional[Context] = None
    ) -> None:
        conf = self.router.conf
        if not conf.enabled:
            return
        bs = self.engine.config.block_size
        tokens = TokenBlockSequence(request.token_ids, block_size=bs)
        hashes = tokens.sequence_hashes()
        n_full = len(request.token_ids) // bs
        cached = self.engine.match_cached_prefix(hashes[:n_full])
        prefill_len = len(request.token_ids) - cached * bs
        # cheap local checks first; only then pay the store round-trip
        if prefill_len <= conf.max_local_prefill_length:
            return
        assert self.engine.kvbm is not None
        if n_full > self.engine.kvbm.host.num_blocks:
            # the delivery could not fit the host tier without evicting
            # its own leading blocks — remote prefill would be wasted
            log.warning(
                "prompt (%d blocks) exceeds host tier (%d); prefilling locally",
                n_full, self.engine.kvbm.host.num_blocks,
            )
            return
        depth = await self.queue.depth()
        PREFILL_QUEUE_DEPTH.set(depth)
        if not self.router.should_prefill_remote(prefill_len, depth):
            return
        # deadline budget (docs/robustness.md): the transfer wait may
        # not outlive the request's remaining budget, and a nearly-
        # expired request skips the remote hop entirely (local prefill
        # fails fast in the engine's own deadline reap instead)
        wait_s = self.router.conf.transfer_timeout_s
        deadline_ts = None
        if context is not None and context.deadline is not None:
            remaining_ms = context.remaining_ms() or 0.0
            if remaining_ms / 1e3 <= 0.05:
                return
            wait_s = min(wait_s, remaining_ms / 1e3)
            deadline_ts = time.time() + remaining_ms / 1e3
        self.remote_prefills += 1
        DISAGG_REMOTE_PREFILLS.inc()
        rid = request.request_id
        # enqueue-to-KV-landed wait: the span the "where did TTFT go?"
        # question is usually answered by
        span = get_tracer().span(
            "prefill_queue.wait", parent=context,
            attrs={"service": "decode", "prefill_tokens": prefill_len,
                   "queue_depth": depth},
        )
        t0 = time.monotonic()
        timed_out = False
        # the finally must cover the enqueue too: a store failure there
        # would otherwise leak the completion-event entry, the span,
        # and the queue-wait observation
        done = self.server.completion_event(rid)
        try:
            await self.queue.enqueue(
                RemotePrefillRequest(
                    request_id=rid,
                    token_ids=list(request.token_ids),
                    block_size=bs,
                    transfer_key=self.my_transfer_key,
                    # our span when tracing here, else the inbound
                    # context (incl. a head's negative sampling mark)
                    # passed through verbatim — telemetry/spans.py
                    # propagation_context owns the rules
                    trace=propagation_context(span, context),
                    deadline_ts=deadline_ts,
                )
            )
            await asyncio.wait_for(done.wait(), timeout=wait_s)
        except asyncio.TimeoutError:
            self.local_fallbacks += 1
            timed_out = True
            DISAGG_LOCAL_FALLBACKS.inc()
            span.set_attr("timeout_fallback", True)
            log.warning("remote prefill %s timed out; prefilling locally", rid)
        finally:
            PREFILL_QUEUE_WAIT.observe(time.monotonic() - t0)
            # request autopsy: the decode-side remote-prefill wait as
            # its own segment — it parks in this process's pending
            # table and ships with the engine segment on the seg frame
            # keyed on the CALLER's Context.id (the frontend's autopsy
            # rid), not the preprocessor's request_id — ctx.id is what
            # the endpoint server's take_pending ships on the seg frame
            autopsy.publish_segment(context.id or rid, {
                "source": "remote_prefill",
                "pid": os.getpid(),
                "wait_ms": round((time.monotonic() - t0) * 1e3, 3),
                "queue_depth": depth,
                "prefill_tokens": prefill_len,
                "timeout_fallback": timed_out,
            })
            span.end()
            self.server.discard_completion(rid)

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.model_validate(request)
        await self._maybe_remote_prefill(request, context)
        inner = self.engine.as_async_engine()
        async for item in inner.generate(request, context):
            yield item

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)

    async def close(self) -> None:
        await self.router.close()
        await self.server.close()


MAX_PREFILL_ATTEMPTS = 3


async def run_prefill_worker(
    engine,  # JaxEngine or parallel/long_context.py LongContextPrefiller
    store: Store,
    namespace: str,
    shutdown: asyncio.Event,
    poll_s: float = 0.2,
) -> None:
    """Dequeue → prefill → export blocks → ship → ack, until shutdown
    (then drain: in-flight request finishes before exit). A request that
    keeps failing (e.g. its decode worker died and took its transfer
    metadata with it) is dropped after MAX_PREFILL_ATTEMPTS so one
    poison message can't spin the worker forever."""
    queue = PrefillQueue(store, namespace)
    bs = engine.config.block_size
    attempts: dict[str, int] = {}
    while not shutdown.is_set():
        got = await queue.dequeue(timeout_s=poll_s)
        if got is None:
            continue
        msg_id, req = got
        if req.deadline_ts is not None and time.time() >= req.deadline_ts:
            # the decode side stopped waiting long ago: computing this
            # KV would be pure waste — retire the message
            DEADLINE_EXPIRED.labels("prefill_queue").inc()
            log.warning(
                "prefill %s deadline expired in queue; dropping",
                req.request_id,
            )
            await queue.ack(msg_id)
            continue
        try:
            await _prefill_one(engine, store, req, bs)
            await queue.ack(msg_id)
            attempts.pop(req.request_id, None)
        except Exception:
            n = attempts.get(req.request_id, 0) + 1
            attempts[req.request_id] = n
            if n >= MAX_PREFILL_ATTEMPTS:
                log.exception(
                    "prefill %s failed %d times; dropping", req.request_id, n
                )
                await queue.ack(msg_id)  # dead-letter: retire the message
                attempts.pop(req.request_id, None)
            else:
                log.exception(
                    "prefill %s failed (attempt %d; left for redelivery)",
                    req.request_id, n,
                )
    log.info("prefill worker drained; exiting")


async def _prefill_one(
    engine, store: Store, req: RemotePrefillRequest, bs: int
) -> None:
    from dynamo_tpu.protocols.common import SamplingOptions, StopConditions

    if req.block_size != bs:
        raise ValueError(
            f"block_size mismatch: decode {req.block_size} != prefill {bs}"
        )
    # joins the decode request's trace via the queued trace context
    span = get_tracer().span(
        "prefill.remote", parent=req.trace,
        attrs={"service": "prefill", "prompt_tokens": len(req.token_ids)},
    )
    # downstream child: engine spans on this worker attach to the
    # prefill span (the adapter path below builds its own Context)
    with span:
        if hasattr(engine, "prefill_export"):
            # sequence-parallel prefiller (parallel/long_context.py): the
            # prompt is sharded over an sp mesh and attended with ring/
            # Ulysses attention — no engine scheduler involved
            found, packed = await engine.prefill_export(list(req.token_ids))
        else:
            # run the prompt with max_tokens=1: computes + content-addresses
            # the prompt's full blocks in this engine's cache
            preq = PreprocessedRequest(
                request_id=f"prefill-{req.request_id}",
                token_ids=list(req.token_ids),
                sampling=SamplingOptions(use_greedy=True),
                stop=StopConditions(max_tokens=1, ignore_eos=True),
            )
            ctx = Context()
            ctx.set_trace(propagation_context(span, req.trace) or {})
            adapter = engine.as_async_engine()
            async for _ in adapter.generate(preq, ctx):
                pass
            tokens = TokenBlockSequence(list(req.token_ids), block_size=bs)
            hashes = tokens.sequence_hashes()[: len(req.token_ids) // bs]
            found, packed = await engine.export_kv_blocks(hashes)
        if not found:
            raise RuntimeError("prefill produced no exportable blocks")
        meta = await TransferClient.fetch_metadata(store, req.transfer_key)
        if meta is None:
            raise RuntimeError(f"no transfer metadata at {req.transfer_key}")
        # Single-host: export all-gathers full heads over the mesh, so one put
        # carries the whole block regardless of this worker's TP degree. A
        # multi-host prefill rank ships only its local slice instead, tagged
        # head_start/head_count; the decode side assembles (ops/kv_rearrange,
        # ≈ reference Triton kv_rearrange for prefill-TP ≠ decode-TP).
        ok = await TransferClient.put(
            meta, req.request_id, found, packed,
            trace=propagation_context(span, req.trace),
        )
        if not ok:
            raise RuntimeError("transfer rejected by decode worker")
        span.set_attr("blocks", len(found))
        log.info(
            "prefilled %s: shipped %d/%d blocks",
            req.request_id, len(found), len(req.token_ids) // bs,
        )
