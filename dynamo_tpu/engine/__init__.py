"""The native JAX inference engine: continuous batching over paged KV.

This is the component the reference outsources to vLLM/SGLang/TRT-LLM
(reference: SURVEY.md §1 L3, §7 step 4) — here it is first-class and
TPU-native: jitted unified prefill/decode steps over a device mesh, paged
KV cache with prefix reuse, on-device sampling, and an async streaming
front matching the AsyncEngine contract.
"""

from dynamo_tpu.engine.config import EngineConfig, load_engine_config
from dynamo_tpu.engine.engine import JaxEngine

__all__ = ["EngineConfig", "JaxEngine", "load_engine_config"]
