"""Paged KV block allocator with content-addressed prefix reuse.

The G1 (device/HBM) tier of the KV block manager (reference:
lib/llm/src/block_manager/pool/{active.rs,inactive.rs} — ref-counted
active blocks + LRU-ordered inactive pool with sequence-hash dedupe).

Block 0 is reserved as the padding/garbage block: padded entries of block
tables and slot mappings point at it, so masked lanes have somewhere
harmless to read/write.

Emits KV events (stored/removed) through ``on_event`` — the feed for the
KV-aware router's radix indexer (reference: kv_router/publisher.rs).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

KvEventFn = Callable[[str, list[int], list[int]], None]
# signature: (op="stored"|"removed", block_hashes, parent_info) — see publisher


@dataclass
class _Block:
    id: int
    ref_count: int = 0
    seq_hash: Optional[int] = None  # set once the block's content is complete


class NoBlocksError(RuntimeError):
    pass


class BlockAllocator:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
        on_event: Optional[KvEventFn] = None,
    ):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.enable_prefix_caching = enable_prefix_caching
        self.on_event = on_event
        self._blocks = [_Block(i) for i in range(num_blocks)]
        # free blocks in LRU order (least-recently-freed first = evict first)
        self._free: OrderedDict[int, None] = OrderedDict(
            (i, None) for i in range(1, num_blocks)
        )
        # seq_hash -> block id, for complete cached blocks (active or free)
        self._hash_index: dict[int, int] = {}
        # free blocks still holding content-addressed KV (maintained
        # incrementally: O(free) scans per scrape would defeat the
        # point of a per-step gauge)
        self._cached_free = 0

    # -- introspection ----------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_cached_free(self) -> int:
        """Free blocks whose content is still content-addressed — the
        prefix cache's evictable working set (observability)."""
        return self._cached_free

    @property
    def usage(self) -> float:
        usable = self.num_blocks - 1
        return 1.0 - len(self._free) / usable if usable else 1.0

    def lookup_block(self, seq_hash: int) -> Optional[int]:
        """Device block currently holding this content (if cached)."""
        return self._hash_index.get(seq_hash)

    def match_prefix(self, seq_hashes: list[int]) -> int:
        """How many leading complete blocks are cached (no allocation)."""
        n = 0
        for h in seq_hashes:
            if h in self._hash_index:
                n += 1
            else:
                break
        return n

    def free_need(self, seq_hashes: list[int], n_total: int) -> int:
        """How many blocks allocating this prompt would take from the
        FREE pool (no allocation): fresh blocks plus matched prefix
        blocks that are currently cached-free. Matched blocks pinned by
        other sequences cost the free pool nothing — charging them
        would make admission stall on exactly the shared-prefix
        workloads prefix caching exists for."""
        need = n_total
        if self.enable_prefix_caching:
            for h in seq_hashes:
                bid = self._hash_index.get(h)
                if bid is None:
                    break
                if bid not in self._free:
                    need -= 1  # actively shared: already pinned elsewhere
        return max(0, need)

    # -- allocation -------------------------------------------------------
    def allocate_prefix(self, seq_hashes: list[int]) -> tuple[list[int], int]:
        """Allocate blocks for a prompt: reuse the cached complete-block
        prefix, fresh-allocate the rest. Returns (block_ids, cached_blocks).

        Raises NoBlocksError (allocating nothing) if capacity is short.
        """
        reused: list[int] = []
        if self.enable_prefix_caching:
            for h in seq_hashes:
                bid = self._hash_index.get(h)
                if bid is None:
                    break
                reused.append(bid)
        # pin reused blocks FIRST so _pop_free can't evict them from the
        # free/cached list while we allocate the fresh tail
        for bid in reused:
            self._ref(bid)
        need_fresh = len(seq_hashes) - len(reused)
        if need_fresh > len(self._free):
            self.free_sequence(reused)  # rollback pins
            raise NoBlocksError(
                f"need {need_fresh} fresh blocks, have {len(self._free)}"
            )
        fresh = [self._pop_free() for _ in range(need_fresh)]
        return reused + fresh, len(reused)

    def allocate_block(self) -> int:
        """One fresh block (decode growth)."""
        if not self._free:
            raise NoBlocksError("no free blocks")
        return self._pop_free()

    def commit_block(self, block_id: int, seq_hash: int) -> None:
        """Mark a block's content complete + content-addressed."""
        if not self.enable_prefix_caching:
            return
        block = self._blocks[block_id]
        if block.seq_hash == seq_hash:
            return  # already committed: no duplicate event
        old = self._hash_index.get(seq_hash)
        if old is not None and old != block_id:
            # duplicate content computed concurrently; keep the existing entry
            return
        block.seq_hash = seq_hash
        self._hash_index[seq_hash] = block_id
        if block_id in self._free:  # defensive: commits normally target
            self._cached_free += 1  # active blocks
        if self.on_event:
            self.on_event("stored", [seq_hash], [block_id])

    def free_sequence(self, block_ids: list[int]) -> None:
        """Release a sequence's blocks. Hashed blocks stay cached (LRU);
        unhashed blocks are recycled immediately."""
        for bid in block_ids:
            if bid == 0:
                continue
            block = self._blocks[bid]
            block.ref_count -= 1
            if block.ref_count > 0:
                continue
            if block.seq_hash is None:
                self._free[bid] = None  # plain free
                self._free.move_to_end(bid, last=False)  # recycle soon
            else:
                self._free[bid] = None  # cached-free: evict LRU-last
                self._free.move_to_end(bid, last=True)
                self._cached_free += 1

    # -- internals --------------------------------------------------------
    def _ref(self, bid: int) -> None:
        block = self._blocks[bid]
        if block.ref_count == 0:
            if self._free.pop(bid, -1) is None and block.seq_hash is not None:
                self._cached_free -= 1
        block.ref_count += 1

    def _evictable_count(self) -> int:
        return len(self._free)

    def _pop_free(self) -> int:
        if not self._free:
            raise NoBlocksError("no free blocks")
        bid, _ = self._free.popitem(last=False)
        block = self._blocks[bid]
        if block.seq_hash is not None:
            # evicting cached content
            self._cached_free -= 1
            self._hash_index.pop(block.seq_hash, None)
            if self.on_event:
                self.on_event("removed", [block.seq_hash], [bid])
            block.seq_hash = None
        block.ref_count = 1
        return bid
