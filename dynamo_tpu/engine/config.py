"""Engine configuration."""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class EngineConfig:
    model_path: str = ""
    model_name: str = ""
    # parallelism (≈ reference flags.rs --tensor-parallel-size etc.)
    tensor_parallel_size: int = 1
    data_parallel_size: int = 1
    expert_parallel_size: int = 1
    pipeline_parallel_size: int = 1  # GPipe stage rotation (parallel/pipeline.py)
    num_nodes: int = 1
    node_rank: int = 0
    leader_addr: str = ""
    # KV cache. block_size None = auto: 128-token pages on TPU backends
    # (measured +20% decode and the prefill kernel's MXU-width match —
    # 16-wide pages run the flash dots at 16/128 systolic efficiency),
    # 16 elsewhere (CPU tests, finer prefix-cache granularity).
    block_size: Optional[int] = None
    num_blocks: Optional[int] = None  # None = size by gpu_memory_utilization
    hbm_utilization: float = 0.9
    # "bfloat16" or "float8_e4m3fn" (alias "fp8"): quantized fp8 KV
    # halves cache bytes per token — doubles long-context residency and
    # halves decode-attention HBM reads — at ~1/16 relative rounding
    # per element (reference analogue: vLLM --kv-cache-dtype fp8 the
    # reference passes through, lib/llm vLLM engine args). Scale-free
    # E4M3 storage: the Pallas kernels upcast to bf16 at the VMEM edge
    # (exact), so no per-page scale plumbing — an int8-with-scales
    # variant needs a lane->sublane scale-tile relayout Mosaic's TPU
    # lowering rejects ("unsupported shape cast"; benchmarks/RESULTS.md).
    kv_cache_dtype: str = "bfloat16"

    def __post_init__(self) -> None:
        aliases = {"fp8": "float8_e4m3fn", "float8": "float8_e4m3fn"}
        self.kv_cache_dtype = aliases.get(
            self.kv_cache_dtype, self.kv_cache_dtype
        )

    def wire_kv_dtype(self) -> str:
        """Dtype of PACKED KV blocks (host tiers, disagg wire): an int8
        device cache dequantizes at the block-copy boundary
        (ops/block_copy.py), so everything off-device stays bfloat16;
        float caches ship their own dtype."""
        return (
            "bfloat16" if self.kv_cache_dtype == "int8"
            else self.kv_cache_dtype
        )
    enable_prefix_caching: bool = True
    # KV offload tiers (G2 host / G3 disk; 0 = disabled)
    host_kv_blocks: int = 0
    disk_kv_blocks: int = 0
    disk_kv_path: str = ""
    kv_offload_batch: int = 16
    # restore-vs-recompute gate for the G2 host tier: at startup the
    # engine probes real host<->device copy bandwidth and disables the
    # tier when restoring a block costs more than recomputing its
    # tokens (block_size / this rate). Chips behind a slow tunnel fail
    # the probe (measured: unthrottled G2 collapsed multi-turn serving
    # 16x, throttled still 2x — benchmarks/RESULTS.md); directly
    # attached HBM<->DRAM passes easily. Set kv_offload_force=True to
    # keep the tier regardless (benchmarking, known-fast links).
    kv_recompute_tok_per_s: float = 2000.0
    kv_offload_force: bool = False
    # G4 remote tier: bucket in the coordinator store's object plane
    # ("" = disabled; requires the worker to run with a store, and
    # host_kv_blocks > 0 for the demotion cascade to reach it)
    remote_kv_bucket: str = ""
    # batching
    max_batch_size: int = 64
    max_prefill_tokens: int = 4096
    prefill_chunk_size: int = 1024
    max_model_len: Optional[int] = None
    # fused multi-step decode: tokens generated per device dispatch.
    # >1 amortizes host↔device round-trips (the dominant decode cost
    # when dispatch latency is high); tokens stream in bursts of this
    # size and up to decode_steps-1 sampled-past-stop tokens are
    # discarded per finishing request.
    decode_steps: int = 1
    # mixed prefill+decode batching (needs decode_steps > 1): pending
    # prefill chunks ride the decode window's dispatch in a fixed
    # [rows, len] rectangle, so a straggler's prefill costs ~10-15% of a
    # window instead of a dedicated full-weight pass while decode
    # stalls. rows=0 disables (reference behavior: vLLM's mixed
    # scheduler, container/deps/vllm/...-patch :535).
    # rows=8: each mixed window graduates up to 8 prefills into decode;
    # at 4 windows per 128-token generation that sustains a full
    # 32-deep decode batch (4 rows measured as a decode-population cap
    # of 16 — half the batch idle)
    mixed_prefill_rows: int = 8
    mixed_prefill_len: int = 256
    # adaptive WIDE mixed rectangle: when few prompts are prefilling
    # (and decode occupancy is under mixed_wide_max_running, if set),
    # the mixed window swaps its rectangle for
    # [~rows*len/wide_len, wide_len] — same token budget, fewer rows —
    # so a long prompt prefills in backlog/wide_len windows instead of
    # backlog/len (measured: a 3000-token prompt at ISL-3000/c=4 took
    # 12 windows = 8.4 s TTFT through the 256-token trickle; dedicated
    # prefill instead starves decode — benchmarks/RESULTS.md negative
    # result). 0 disables. The wide variant costs a few extra prewarm
    # compiles at startup.
    mixed_prefill_wide_len: int = 1024
    # decode-occupancy ceiling for the wide rectangle (None = no
    # ceiling, the default): the wide and narrow rectangles have the
    # SAME padded token budget, so when at most wide_rows prompts are
    # prefilling the wide swap costs decode nothing at any occupancy —
    # measured at ISL-3000 c=16: 123.6 -> 138.2 out tok/s, p50 TTFT
    # 17.7 -> 10.8 s when the old ceiling of 4 was lifted. The real
    # guards are the prefilling-count (<= wide_rows) and backlog
    # (> narrow len) conditions in scheduler._mixed_rect.
    mixed_wide_max_running: Optional[int] = None
    # speculative decoding (dynamo_tpu/spec; needs decode_steps == 1 —
    # fused windows and speculation are competing multi-token-per-
    # dispatch techniques and do not compose): a dependency-free drafter
    # proposes up to spec_tokens tokens per sequence per step, one
    # jitted verify forward scores them all through the paged-KV
    # attention, and rejection sampling keeps the longest accepted
    # prefix + 1 fresh token. "" disables; "ngram[:N]" = prompt-lookup
    # self-drafting, "bigram:PATH" = static table (spec/drafter.py).
    # Per-request opt-out via PreprocessedRequest.speculative=False
    # (OpenAI ext.speculative). docs/speculative_decoding.md covers K
    # tuning and accept-rate interpretation.
    spec_decode: str = ""
    spec_tokens: int = 4
    # overlapped decode pipeline (docs/performance.md): double-buffer
    # host scheduling against device execution so the only hot-path
    # sync waits on a result that is already (or nearly) done. At
    # decode_steps == 1 the plain decode loop runs dispatch(N+1) —
    # token column chained on device — before harvesting step N; at
    # decode_steps > 1 the cohort prefill dispatch additionally chains
    # its first tokens straight into the first decode window instead of
    # hard-syncing between the two. Greedy output is bit-identical with
    # overlap on or off (the compute is the same program over the same
    # values; only the host's position in the timeline moves).
    # False (--no-overlap) restores the fully serial
    # plan -> dispatch -> sync -> emit loop — the escape hatch and the
    # A/B baseline (bench.py --overlap).
    overlap: bool = True
    # explicit MID decode bucket override (None = auto: pad/2 when the
    # pad is >= 64). Deployments whose steady population sits well
    # under max_batch_size (e.g. long-context residency caps) can pin
    # a lighter window here at the cost of one more prewarmed variant
    # set.
    decode_batch_mid: Optional[int] = None
    # static serving shapes: pad the decode batch to max_batch_size and
    # block-table width to the max_model_len cap so the decode/mixed
    # dispatch is ONE compiled shape (padded rows are ~free — decode is
    # weight-read-bound). Composition-dependent buckets AOT-compile
    # mid-serve, which measured as ~100 s p99 TTFT stalls over the chip
    # tunnel.
    static_shapes: bool = True
    # compile every reachable serving shape at startup (None = auto:
    # on for TPU backends, off elsewhere). Lazy compiles take minutes
    # over a chip tunnel and land mid-serve as 100 s+ TTFT stalls.
    prewarm: Optional[bool] = None
    # also prewarm the penalty-sampling AND logit-bias step variants
    # (each selects a separately-compiled step carrying its tables) —
    # covers the dedicated prefill shapes and the pure decode windows,
    # the only paths such requests take (they never ride the mixed
    # rectangle). Off by default: it multiplies startup compiles for
    # features many deployments never receive — the first such request
    # then pays a one-time compile stall instead. Multi-feature combos
    # in one batch (e.g. bias+penalties) always compile on first use.
    prewarm_penalties: bool = False
    # likewise for the top-logprobs step variant (requests with
    # top_logprobs > 0 / completions logprobs > 0). Off by default for
    # the same startup-cost reason; the first such request pays a
    # one-time compile stall instead.
    prewarm_logprobs: bool = False
    # likewise for the guided-decoding (allow-mask) step variants
    # (docs/guided_decoding.md): the masked serial prefill/decode
    # shapes, plus the masked spec-verify rectangle on spec engines.
    # Deployments serving structured-output traffic should turn this on
    # — it is what keeps a guided run serve-compile-free under
    # DYN_COMPILE_FENCE. The masked variant set mirrors the flags
    # above: guided+penalties/bias warm only with prewarm_penalties,
    # guided+top-logprobs only with prewarm_logprobs — combos outside
    # the opted-in set pay the same documented first-use compile their
    # unguided counterparts pay. Guided requests need decode_steps == 1
    # (the mask advances on host per committed token), so this flag
    # does too.
    prewarm_guided: bool = False
    # observability (telemetry/{recorder,slo}.py; docs/observability.md)
    # step flight recorder: ring of the last N step records, auto-dumped
    # to JSONL around anomalies. 0 disables recording entirely.
    flight_recorder_steps: int = 256
    # slow-step watchdog: a device step longer than this dumps the ring
    # (None = DYN_SLOW_STEP_MS env, else off). Millseconds of WALL time
    # per dispatch — size it to a few windows, not a single token.
    slow_step_ms: Optional[float] = None
    # where flight-recorder dumps land ("" = DYN_FLIGHT_DIR or tmpdir)
    flight_dump_dir: str = ""
    # SLO targets evaluated per finished request (engine-side TTFT =
    # submit -> first emitted token; ITL = mean decode inter-token
    # latency). None = no target; attainment/goodput then track 1.0 /
    # nothing while the raw TTFT/ITL histograms still populate.
    slo_ttft_ms: Optional[float] = None
    slo_itl_ms: Optional[float] = None
    # weights
    random_weights: bool = False  # bench/test mode: skip checkpoint load
    # weight-only quantization applied at load: None | "int8"
    # (per-channel symmetric, models/quant.py — halves weight HBM
    # traffic and fits the 8B flagship on one 16 GB chip)
    quantization: Optional[str] = None
    seed: int = 0
    extra: dict[str, Any] = field(default_factory=dict)

    def resolve_block_size(self) -> int:
        """The effective page size (see block_size). Initializes the
        JAX backend — call only where that is already safe."""
        if self.block_size is not None:
            return self.block_size
        import jax

        return 128 if jax.default_backend() == "tpu" else 16

    @property
    def mesh_devices(self) -> int:
        return (
            self.tensor_parallel_size
            * self.data_parallel_size
            * self.expert_parallel_size
        )


def load_engine_config(args: Any) -> EngineConfig:
    """Build an EngineConfig from CLI args (+ --extra-engine-args JSON)."""
    extra: dict[str, Any] = {}
    if getattr(args, "extra_engine_args", None):
        with open(args.extra_engine_args) as f:
            extra = json.load(f)
    cfg = EngineConfig(
        model_path=args.model_path or "",
        model_name=args.model_name or (args.model_path or "model").rstrip("/").rsplit("/", 1)[-1],
        tensor_parallel_size=getattr(args, "tensor_parallel_size", 1),
        pipeline_parallel_size=getattr(args, "pipeline_parallel_size", 1),
        num_nodes=getattr(args, "num_nodes", 1),
        node_rank=getattr(args, "node_rank", 0),
        leader_addr=getattr(args, "leader_addr", ""),
        quantization=getattr(args, "quantization", None),
        decode_steps=getattr(args, "decode_steps", 1),
        mixed_prefill_rows=getattr(
            args, "mixed_prefill_rows", EngineConfig.mixed_prefill_rows
        ),
        mixed_prefill_len=getattr(args, "mixed_prefill_len", 256),
        mixed_prefill_wide_len=getattr(
            args, "mixed_prefill_wide_len",
            EngineConfig.mixed_prefill_wide_len,
        ),
        mixed_wide_max_running=getattr(
            args, "mixed_wide_max_running",
            EngineConfig.mixed_wide_max_running,
        ),
        spec_decode=getattr(args, "spec_decode", "") or "",
        spec_tokens=getattr(args, "spec_tokens", EngineConfig.spec_tokens),
        prewarm_guided=getattr(args, "prewarm_guided", False),
        overlap=not getattr(args, "no_overlap", False),
        host_kv_blocks=getattr(args, "host_kv_blocks", 0),
        disk_kv_blocks=getattr(args, "disk_kv_blocks", 0),
        disk_kv_path=getattr(args, "disk_kv_path", ""),
        remote_kv_bucket=getattr(args, "remote_kv_bucket", ""),
        flight_recorder_steps=getattr(
            args, "flight_recorder_steps", EngineConfig.flight_recorder_steps
        ),
        slow_step_ms=getattr(args, "slow_step_ms", None),
        flight_dump_dir=getattr(args, "flight_dump_dir", "") or "",
        slo_ttft_ms=getattr(args, "slo_ttft_ms", None),
        slo_itl_ms=getattr(args, "slo_itl_ms", None),
    )
    for k, v in extra.items():
        if hasattr(cfg, k):
            setattr(cfg, k, v)
        else:
            cfg.extra[k] = v
    return cfg
