"""JaxEngine: the async-facing native TPU inference engine.

Orchestration (≈ what vLLM's AsyncLLMEngine does for the reference):

- a dedicated **engine thread** runs the step loop (JAX dispatch blocks;
  the asyncio event loop must never wait on the device);
- one **fused jitted step** does forward + KV-cache update + sampling on
  device, with cache buffers donated so XLA updates them in place;
- per-request output queues bridge back into asyncio via
  ``loop.call_soon_threadsafe``;
- publishes ForwardPassMetrics-shaped stats for the KV router
  (reference: lib/llm/src/kv_router/publisher.rs ForwardPassMetrics).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import hashlib
import logging
import os
import queue as thread_queue
import threading
import time
import uuid
import zlib
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu import faults
from dynamo_tpu.engine.allocator import BlockAllocator
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.kvbm import BlockLayout, KvbmConfig, KvBlockManager
from dynamo_tpu.ops.block_copy import gather_blocks, scatter_blocks
from dynamo_tpu.engine.sampling import (
    SamplingBatch,
    dense_gen_counts,
    dense_prompt_presence,
    sample,
)
from dynamo_tpu.engine.scheduler import (
    Scheduler,
    SeqState,
    Sequence,
    StepPlan,
)
from dynamo_tpu.models import ModelConfig
from dynamo_tpu.utils import affinity, compile_fence, transfer_fence
from dynamo_tpu.utils.bucketing import next_bucket
from dynamo_tpu.models.llama import (
    CACHE_SPEC,
    init_cache,
    param_specs,
)
from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
)
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream
from dynamo_tpu.telemetry import autopsy, get_tracer
from dynamo_tpu.telemetry.debug import (
    register_debug_provider,
    unregister_debug_provider,
)
from dynamo_tpu.telemetry.attribution import (
    AttributionLedger,
    BlackBox,
    register_attribution_provider,
    unregister_attribution_provider,
)
from dynamo_tpu.telemetry.hbm import HbmAccountant, tree_bytes
from dynamo_tpu.telemetry.instruments import (
    COMPILE_FENCE_EVENTS,
    ENGINE_BATCH_OCCUPANCY,
    ENGINE_COMPILE_EVENTS,
    ENGINE_PREWARM_SECONDS,
    ENGINE_QUEUE_DEPTH,
    ENGINE_REQUESTS_FINISHED,
    ENGINE_STEP_SECONDS,
    ENGINE_TOKENS_GENERATED,
    GUIDED_REQUESTS,
    KV_POOL_BLOCKS_ACTIVE,
    KV_POOL_BLOCKS_TOTAL,
    KV_POOL_CACHED_FREE_BLOCKS,
    SPEC_ACCEPT_RATE,
    SPEC_ACCEPTED_TOKENS,
    SPEC_DRAFT_HIDDEN_FRAC,
    SPEC_PROPOSED_TOKENS,
    SPEC_STEP_SECONDS,
    TRANSFER_FENCE_EVENTS,
)
from dynamo_tpu.telemetry.overlap import OverlapTracker
from dynamo_tpu.telemetry.recorder import FlightRecorder
from dynamo_tpu.telemetry.slo import SloConfig, SloTracker
from dynamo_tpu.tokens import DEFAULT_SALT, TokenBlockSequence

log = logging.getLogger("dynamo_tpu.engine")

# compile-event attribution: "prewarm" while ANY engine's _initialize/
# _prewarm runs, "serve" otherwise — a serve-phase compile is exactly
# the mid-serve TTFT stall the static-shape machinery exists to prevent,
# so it deserves its own counter series. jax.monitoring events carry no
# engine identity, so a refcount of initializing engines is the closest
# attribution a multi-engine process allows.
_initializing_engines = 0
_compile_listener_registered = False


def _register_compile_listener() -> None:
    """Count XLA compilations via jax.monitoring duration events
    (best-effort: event names vary across jax versions, so filter on
    substring; absence of the API degrades to no compile counting)."""
    global _compile_listener_registered
    if _compile_listener_registered:
        return
    _compile_listener_registered = True
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            if "compile" in event:
                phase = "prewarm" if _initializing_engines > 0 else "serve"
                ENGINE_COMPILE_EVENTS.labels(phase).inc()
                # compile fence (DYN_COMPILE_FENCE, docs/static_analysis
                # .md): the fence keeps its own allowed-window refcount
                # — _initialize registers it alongside this phase tag —
                # and collects anything outside it for _record_step to
                # escalate. Inert unless armed.
                compile_fence.note_compile(event, duration)

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # pragma: no cover — older/newer jax without the API
        log.debug("jax.monitoring unavailable; compile events not counted")


@dataclass
class ForwardPassMetrics:
    """Worker load metrics for routers/planners
    (reference: kv_router/protocols.rs:43-57)."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0
    # SLO/goodput signals (telemetry/slo.py): rolling attainment of the
    # configured TTFT/ITL targets and cumulative goodput tokens — the
    # Planner scales on *goodput*, not raw load, when targets are set.
    # slo_enabled lets aggregators average attainment over only the
    # workers that actually evaluate targets (a target-less worker's
    # constant 1.0 would dilute the fleet signal).
    slo_enabled: bool = False
    slo_attainment: float = 1.0
    goodput_tokens_total: int = 0
    # perf attribution (telemetry/attribution.py): live achieved/roofline
    # ratio and the window's dominant loss bucket. -1.0 = no decode
    # window yet (aggregators exclude it from the fleet mean — a fresh
    # worker must not read as either perfect or broken).
    roofline_frac: float = -1.0
    top_loss_bucket: str = ""

    def to_dict(self) -> dict:
        return self.__dict__.copy()



def _lag_add(lag: dict, entry: dict) -> None:
    """Charge an in-flight entry to a pipeline's lag ledger: ``vmap``
    maps id(seq) -> tokens the entry will add, sampled on device but
    not yet applied to host state (both pipelined step loops share
    this invariant — scheduler.plan_pipelined_* read the same map)."""
    for sid, v in entry["vmap"].items():
        lag[sid] = lag.get(sid, 0) + v


def _lag_sub(lag: dict, entry: dict) -> None:
    """Release a harvested entry's charges from the lag ledger."""
    for sid, v in entry["vmap"].items():
        left = lag.get(sid, 0) - v
        if left > 0:
            lag[sid] = left
        else:
            lag.pop(sid, None)


class JaxEngine:
    def __init__(self, config: EngineConfig):
        self.config = config
        self.model_config: Optional[ModelConfig] = None
        self.mesh = None
        self.params = None
        self.k_cache = None
        self.v_cache = None
        self.allocator: Optional[BlockAllocator] = None
        self.scheduler: Optional[Scheduler] = None
        self.kvbm: Optional[KvBlockManager] = None
        self.eos_token_ids: list[int] = []
        self._step_fn: Optional[Callable] = None
        self._step_fn_mm: Optional[Callable] = None
        self._multi_step_fn: Optional[Callable] = None
        self._mixed_step_fn: Optional[Callable] = None
        self._chain_next_fn: Optional[Callable] = None
        self._pack_pair_fn: Optional[Callable] = None
        # wide mixed rectangle (rows, len), set when enabled (see
        # _initialize; scheduler._mixed_rect picks per population)
        self._wide_rect: Optional[tuple[int, int]] = None
        # blocks the busy-path offload pump may move per serving step
        # (derived from the probed copy bandwidth in _gate_kv_offload;
        # 0 = transfers wait for idle moments; None = pump's own
        # default batch — the multihost sharded tier, which has no
        # local probe)
        self._kv_busy_pump_cap: Optional[int] = 0
        self._pp = config.pipeline_parallel_size
        # multi-host: rank 0 leads (scheduler + broadcast), others follow
        self._is_follower = config.num_nodes > 1 and config.node_rank > 0
        self._mh_broadcast = None  # StepBroadcaster on the leader
        self._thread: Optional[threading.Thread] = None
        self._incoming: thread_queue.Queue = thread_queue.Queue()
        self._control: thread_queue.Queue = thread_queue.Queue()
        self._wake = threading.Event()
        self._running = False  # dynalint: handoff=stop-flag — one-way bool, each side only ever writes False; readers poll per step/await
        # graceful drain (runtime/drain.py; docs/robustness.md): once
        # set, submit() rejects new work and the step loop hands off
        # every eligible in-flight stream with FinishReason.MIGRATE
        self._draining = False  # dynalint: handoff=drain-flag — one-way bool, only ever flipped True; engine thread polls per step
        self._drain_migrated = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._seed_counter = 0
        # step-failure quarantine (see _quarantine_step_failure)
        self._last_plan: Optional[StepPlan] = None
        self._step_failures = 0
        # speculative decoding (dynamo_tpu/spec; config.spec_decode)
        self._drafter = None
        self._spec_step_fn: Optional[Callable] = None
        self._chain_spec_fn: Optional[Callable] = None
        # guided decoding (dynamo_tpu/guided; docs/guided_decoding.md):
        # the served tokenizer, loaded lazily on the first guided
        # request (submit thread — compiles never stall the step loop)
        # or eagerly when config.prewarm_guided
        self._guided_tokenizer = None
        # runtime suspend (degradation ladder rung 2, planner/
        # degradation.py): flipped from the asyncio thread, read by the
        # engine thread each step — a plain bool attr is race-free here
        self.spec_suspended = False
        self.spec_proposed_total = 0  # bench/introspection counters
        self.spec_accepted_total = 0
        # overlapped spec pipeline accounting (docs/speculative_decoding.md):
        # wall seconds of host drafting hidden under device execution
        # (optimistic pre-drafts) vs exposed on the dispatch critical
        # path (first-step drafts + harvest-time repairs), and how often
        # the pre-draft's predicted tail matched the realized one.
        # Engine-thread writes; bench//debug/state read advisorily.
        self.spec_draft_hidden_s_total = 0.0
        self.spec_draft_exposed_s_total = 0.0
        self.spec_predraft_hits = 0
        self.spec_predraft_misses = 0
        self.spec_pipeline_steps = 0
        # per-engine token counter (the registry counter is process-
        # global): /debug/state exposes it so `top` can derive tok/s
        # from deltas regardless of SLO configuration
        self.tokens_generated_total = 0
        # recent sync=False dispatches whose device errors would DEFER
        # to a later synced step (_annotate_deferred_error)
        self._unsynced_steps: list[str] = []
        # observability (docs/observability.md): step flight recorder
        # with slow-step watchdog, SLO/goodput tracker, HBM accountant
        slow_ms = config.slow_step_ms
        if slow_ms is None:
            try:
                env = os.environ.get("DYN_SLOW_STEP_MS")
                slow_ms = float(env) if env else None
            except ValueError:
                log.warning("ignoring malformed DYN_SLOW_STEP_MS")
                slow_ms = None
        self.recorder: Optional[FlightRecorder] = (
            FlightRecorder(
                capacity=config.flight_recorder_steps,
                slow_step_s=slow_ms / 1e3 if slow_ms else None,
                dump_dir=config.flight_dump_dir,
                # a device idle gap as long as a slow step is the same
                # anomaly spent on the host side of the pipeline
                idle_gap_slow_s=slow_ms / 1e3 if slow_ms else None,
            )
            if config.flight_recorder_steps > 0
            else None
        )
        # overlapped decode pipeline (docs/performance.md): device
        # idle-gap accounting feeding the flight recorder's
        # idle_gap_ms stamps, /debug/state "overlap", and bench.py's
        # device_idle_frac. Engine-thread only.
        self.overlap = OverlapTracker()
        self.slo = SloTracker(
            SloConfig(ttft_ms=config.slo_ttft_ms, itl_ms=config.slo_itl_ms)
        )
        self.hbm = HbmAccountant()
        # continuous perf attribution (telemetry/attribution.py): the
        # per-step loss-bucket ledger behind dynamo_step_time_frac /
        # dynamo_roofline_frac; the byte model installs in
        # _initialize_inner once the geometry is known. Engine-thread
        # writes, snapshot reads.
        self.attribution = AttributionLedger()
        # anomaly-triggered black-box capture: slow-step/idle-gap
        # watchdog trips and roofline-band drops bundle the flight
        # recorder ring + attribution window + /debug/state into one
        # timestamped dump dir (rate-limited)
        self.blackbox = BlackBox(
            recorder=self.recorder,
            ledger=self.attribution,
            dump_dir=config.flight_dump_dir,
        )
        # per-dispatch phase timings (_run_device_step fills; the step
        # recorder reads) — a plain dict, engine-thread only
        self._last_phases: dict[str, float] = {}
        self._debug_name: Optional[str] = None
        try:
            self.PIPELINE_DEPTH = max(
                1, int(os.environ.get("DYN_PIPELINE_DEPTH", "2"))
            )
        except ValueError:
            log.warning("ignoring malformed DYN_PIPELINE_DEPTH; using 2")
            self.PIPELINE_DEPTH = 2
        self.kv_event_sink: Optional[Callable[[str, list[int], list[int]], None]] = None

    # ------------------------------------------------------------------
    # Launch
    # ------------------------------------------------------------------
    @classmethod
    async def launch(
        cls, config: EngineConfig, model_config: Optional[ModelConfig] = None,
        remote_kv_objects=None,
    ) -> "JaxEngine":
        """``model_config`` injection skips reading config.json from
        model_path (benchmarks / synthetic model shapes).
        ``remote_kv_objects``: a kvbm SyncObjectStore backing the G4
        remote tier when config.remote_kv_bucket is set."""
        engine = cls(config)
        engine.model_config = model_config
        engine._remote_kv_objects = remote_kv_objects
        loop = asyncio.get_running_loop()
        engine._loop = loop
        await loop.run_in_executor(None, engine._initialize)
        engine._running = True
        # affinity sanitizer (docs/static_analysis.md, DYN_AFFINITY_CHECK=1):
        # this thread IS the event loop; the step loop registers "engine"
        # at its own start. spec_suspended is engine-affine — the loop-side
        # writer (planner degradation rung) declares its handoff.
        affinity.register_thread("loop")
        affinity.guard_attrs(engine, {"spec_suspended": "engine"})
        engine._thread = threading.Thread(
            target=engine._step_loop, name="jax-engine", daemon=True
        )
        engine._thread.start()
        # live introspection: /debug/state serves this snapshot (latest
        # engine wins the bare "engine" name; shutdown unregisters only
        # its own registration)
        engine._debug_name = "engine"
        register_debug_provider(engine._debug_name, engine.debug_state)
        register_attribution_provider(
            engine._debug_name, engine.attribution_state
        )
        if faults.ACTIVE is not None and engine.recorder is not None:
            # fired faults land in the flight recorder's ring so an
            # anomaly dump shows the injected chaos next to the steps
            # it perturbed
            recorder = engine.recorder
            faults.ACTIVE.add_listener(
                lambda rec: recorder.record(
                    "fault", 0.0,
                    point=rec.get("point"), fault_kind=rec.get("kind"),
                )
            )
        return engine

    def _initialize(self) -> None:
        global _initializing_engines
        _register_compile_listener()
        _initializing_engines += 1
        try:
            # the prewarm window registers both fences' allowed phase:
            # everything compiled (and every host<->device upload) in
            # here is sanctioned AOT warming; anything after is a
            # mid-serve compile/transfer the fences escalate. arm()
            # flips JAX's transfer guard to "disallow" first so the
            # serve phase inherits the armed guard.
            transfer_fence.arm()
            with compile_fence.allow(), transfer_fence.allow():
                self._initialize_inner()
        finally:
            _initializing_engines -= 1

    def _initialize_inner(self) -> None:
        from dynamo_tpu.utils.jaxtools import enable_compile_cache

        cfg = self.config
        if cfg.spec_decode:
            # speculative decoding composes with neither fused windows
            # (both are multi-token-per-dispatch techniques competing
            # for the same step contract) nor the pp/multihost step
            # protocols (the verify step is a new jit signature the
            # follower/stage machinery doesn't mirror) — fail LOUDLY at
            # config time rather than silently serving without it
            if cfg.decode_steps > 1:
                raise ValueError(
                    "spec_decode requires decode_steps == 1 (fused "
                    "decode windows and speculation do not compose)"
                )
            if self._pp > 1:
                raise ValueError(
                    "spec_decode is not supported with "
                    "pipeline_parallel_size > 1"
                )
            if cfg.num_nodes > 1:
                raise ValueError(
                    "spec_decode is not supported with num_nodes > 1"
                )
            if cfg.spec_tokens < 1:
                raise ValueError(
                    f"spec_decode needs spec_tokens >= 1 (got "
                    f"{cfg.spec_tokens}); 0 would silently serve "
                    "without speculation while compiling a useless "
                    "verify shape"
                )
            from dynamo_tpu.spec import build_drafter

            self._drafter = build_drafter(cfg.spec_decode)
        if cfg.prewarm_guided and cfg.decode_steps > 1:
            # guided requests themselves are rejected per-request at
            # submit() on fused-window engines; a config asking to
            # prewarm their variants there is a deployment mistake
            raise ValueError(
                "prewarm_guided requires decode_steps == 1 (guided "
                "masks advance on host per committed token; fused "
                "windows sample K tokens per dispatch)"
            )
        if cfg.num_nodes > 1:
            # multi-host bring-up (reference: MultiNodeConfig, engines.rs:41)
            jax.distributed.initialize(
                coordinator_address=cfg.leader_addr,
                num_processes=cfg.num_nodes,
                process_id=cfg.node_rank,
            )
        # after distributed init: probing the backend before it would
        # break jax.distributed.initialize (must precede any XLA call)
        enable_compile_cache()  # restarts reuse tunnel-compiled variants
        if cfg.block_size is None:
            # 128-token pages on TPU (MXU-width flash dots, +20%
            # measured decode), 16 elsewhere — see EngineConfig
            cfg.block_size = cfg.resolve_block_size()
        mesh_cfg = MeshConfig(
            dp=cfg.data_parallel_size,
            pp=cfg.pipeline_parallel_size,
            tp=cfg.tensor_parallel_size,
            ep=cfg.expert_parallel_size,
        )
        devices = jax.devices()[: mesh_cfg.size]
        self.mesh = build_mesh(mesh_cfg, devices)
        from dynamo_tpu.models.llama import set_attention_mesh

        if self._pp == 1:
            # enable the Pallas decode kernel on multi-device tp meshes
            # (shard_map over "tp"; see models/llama.py attend_mlp).
            # pp engines keep the gather path: "tp" is a GSPMD auto axis
            # inside the pp stage rotation.
            set_attention_mesh(self.mesh)
        else:
            # a stale mesh left by an earlier engine in this process
            # would poison the pp trace with a manual-tp shard_map
            set_attention_mesh(None)
        if cfg.num_nodes > 1 and cfg.node_rank == 0:
            from dynamo_tpu.parallel.multihost import StepBroadcaster

            self._mh_broadcast = StepBroadcaster()

        specs_fn = None
        cache_spec = None
        if self._pp > 1:
            # stage-sharded layer stacks + cache (parallel/pipeline.py);
            # resolve_model calls specs_fn once the config is known, so
            # the pp/layer-count compatibility check runs BEFORE any
            # expensive weight load
            from dynamo_tpu.parallel.pipeline import PP_CACHE_SPEC, pp_param_specs

            pp = self._pp

            def specs_fn(mc: ModelConfig) -> dict:
                if mc.num_hidden_layers % pp != 0:
                    raise ValueError(
                        f"pipeline_parallel_size={pp} must divide "
                        f"num_hidden_layers={mc.num_hidden_layers}"
                    )
                return pp_param_specs(mc)

            cache_spec = PP_CACHE_SPEC

        from dynamo_tpu.models import loader

        self.model_config, self.params = loader.resolve_model(
            cfg.model_path,
            model_config=self.model_config,
            random_weights=cfg.random_weights,
            seed=cfg.seed,
            mesh=self.mesh,
            specs_fn=specs_fn,
            quantize=cfg.quantization,
        )
        self.eos_token_ids = self.model_config.eos_token_ids
        # install the attribution ledger's byte model: geometry + quant
        # + kv dtype are now final, so the live roofline denominator is
        # computed from the same formula bench.py prints (roofline.py)
        from dynamo_tpu.telemetry.roofline import build_roofline

        self.attribution.configure(build_roofline(
            self.model_config, cfg.quantization, cfg.kv_cache_dtype,
        ))

        if jnp.dtype(cfg.kv_cache_dtype) == jnp.int8:
            # int8 KV limits (ops/kv_quant.py documents the layout):
            # the in-kernel scale-tile reshape needs lane-multiple pages
            # on real TPUs, and the pp cache layout has no scale plane
            if self._pp > 1:
                raise ValueError(
                    "kv_cache_dtype=int8 is not supported with "
                    "pipeline_parallel_size > 1 (use bfloat16 or fp8)"
                )
            if (
                jax.default_backend() == "tpu"
                and cfg.block_size % 128 != 0
            ):
                raise ValueError(
                    f"kv_cache_dtype=int8 on TPU requires block_size to "
                    f"be a multiple of 128 (got {cfg.block_size}); the "
                    f"scale-tile reshape is lane-preserving only then"
                )
        num_blocks = cfg.num_blocks or self._auto_num_blocks(devices)
        if cfg.num_nodes > 1:
            # every process must build identically-shaped caches; only
            # the leader's HBM probe is authoritative
            from jax.experimental import multihost_utils

            num_blocks = int(
                multihost_utils.broadcast_one_to_all(np.int32(num_blocks))
            )
        self.k_cache, self.v_cache = init_cache(
            self.model_config,
            num_blocks,
            cfg.block_size,
            self.mesh,
            dtype=jnp.dtype(cfg.kv_cache_dtype),
            spec=cache_spec,
        )
        self.allocator = BlockAllocator(
            num_blocks,
            cfg.block_size,
            enable_prefix_caching=cfg.enable_prefix_caching,
            on_event=self._on_kv_event,
        )
        self.scheduler = Scheduler(
            self.allocator,
            cfg.block_size,
            max_batch_size=cfg.max_batch_size,
            prefill_chunk_size=cfg.prefill_chunk_size,
            max_model_len=cfg.max_model_len
            or self.model_config.max_position_embeddings,
            max_prefill_tokens=cfg.max_prefill_tokens,
        )
        self.scheduler.decode_lookahead = max(1, cfg.decode_steps)
        if cfg.static_shapes:
            # one compiled decode/mixed shape: pad the decode batch to
            # max_batch_size and the table width to the max_model_len
            # cap (+ window growth margin). Composition-dependent
            # buckets would otherwise AOT-compile MID-SERVE (minutes
            # per variant over a chip tunnel — measured as 100 s TTFT
            # p99 stalls). Coarse prefill buckets bound that path too.
            sched = self.scheduler
            sched.decode_batch_pad = next_bucket(
                cfg.max_batch_size, Scheduler.BATCH_BUCKETS
            )
            if sched.decode_batch_pad > 4:
                # low-concurrency bucket: a lone stream decodes in a
                # [4,1]-padded window (~10% lighter than the full pad)
                # for a handful of extra prewarmed variants
                sched.decode_batch_small = 4
            if cfg.decode_batch_mid is not None:
                # explicit override: the LARGEST bucket <= the request
                # strictly between the small bucket and the pad (a mid
                # bucket at/above the pad is a no-op, at/below small is
                # dead code that still costs AOT prewarms). 0 = no mid
                # bucket, explicitly (None = auto).
                lo = sched.decode_batch_small or 0
                fits = [
                    b for b in Scheduler.BATCH_BUCKETS
                    if lo < b < sched.decode_batch_pad
                    and b <= cfg.decode_batch_mid
                ]
                if cfg.decode_batch_mid > 0 and fits:
                    sched.decode_batch_mid = fits[-1]
                elif cfg.decode_batch_mid > 0:
                    log.warning(
                        "decode_batch_mid=%d has no bucket strictly "
                        "between the small bucket (%d) and the pad "
                        "(%d); ignoring the override",
                        cfg.decode_batch_mid, lo, sched.decode_batch_pad,
                    )
            elif sched.decode_batch_pad >= 64:
                # mid bucket: a half-occupancy population on a wide-pad
                # engine decodes in [pad/2]-windows (measured ~11% at
                # c=32 on a max_batch=64 engine) for one more set of
                # prewarmed variants
                sched.decode_batch_mid = sched.decode_batch_pad // 2
            eff_len = (
                cfg.max_model_len or self.model_config.max_position_embeddings
            )
            # capped by the cache itself: a sequence can never hold more
            # blocks than exist, and an uncapped long-context
            # max_position_embeddings would give every decode step a
            # thousands-wide dead block table (grid overhead per page)
            blocks_cap = min(
                -(-(eff_len + max(1, cfg.decode_steps))
                  // cfg.block_size) + 1,
                num_blocks,
            )
            sched.table_width_pad = max(
                Scheduler.TABLE_BUCKET,
                -(-blocks_cap // Scheduler.TABLE_BUCKET)
                * Scheduler.TABLE_BUCKET,
            )
            # prefill-batch shapes (each bucket is a multi-minute AOT
            # prewarm): a single-row shape so a lone prompt on an idle
            # engine doesn't pay 8× padded compute (prefill is
            # compute-bound, unlike decode), the mixed rectangle's row
            # count, the full-burst width, AND the budget-filling width
            # (max_prefill_tokens / smallest chunk): without it, a
            # burst wider than the mixed rows has no bucket between
            # rows and the full pad, so batched prefill degrades to
            # rows-sized steps — measured at B=64 as staggered prefill
            # waves that desynchronize decode for the population's
            # lifetime (windows run 16-40 wide at full-window cost,
            # 924 vs 1505 tok/s)
            sched.prefill_chunk_buckets = [128, 256, 1024, 4096]
            budget_rows = max(
                1,
                (cfg.max_prefill_tokens or 4096)
                // sched.prefill_chunk_buckets[0],
            )
            sched.prefill_batch_buckets = sorted(
                {1,
                 min(cfg.mixed_prefill_rows, sched.decode_batch_pad),
                 min(budget_rows, sched.decode_batch_pad),
                 sched.decode_batch_pad}
            )
        if cfg.decode_steps > 1 and cfg.mixed_prefill_rows > 0:
            # normalize to bucket values: _pad_prefill_rect's fixed
            # rectangle must be >= the bucketed prefill arrays, which
            # round UP (a non-bucket rows/len would crash every mixed
            # step and fail all in-flight requests)
            cfg.mixed_prefill_rows = next_bucket(
                cfg.mixed_prefill_rows, self.scheduler.prefill_batch_buckets
            )
            cfg.mixed_prefill_len = next_bucket(
                cfg.mixed_prefill_len, self.scheduler.prefill_chunk_buckets
            )
            # the rectangle must fit the prefill token budget the HBM
            # headroom sizing reserves for (see _auto_num_blocks area);
            # shrink along the bucket lists so the fixed rectangle
            # stays a bucket value (pad invariant)
            pb = self.scheduler.prefill_batch_buckets
            pc = self.scheduler.prefill_chunk_buckets
            cap = max(pc[0], self.scheduler.max_prefill_tokens)

            def down(v: int, buckets: list) -> int:
                smaller = [b for b in buckets if b < v]
                return smaller[-1] if smaller else buckets[0]

            while cfg.mixed_prefill_len > max(cap, pc[0]) and (
                cfg.mixed_prefill_len > pc[0]
            ):
                cfg.mixed_prefill_len = down(cfg.mixed_prefill_len, pc)
            while (
                cfg.mixed_prefill_rows * cfg.mixed_prefill_len > cap
                and cfg.mixed_prefill_rows > pb[0]
            ):
                cfg.mixed_prefill_rows = down(cfg.mixed_prefill_rows, pb)
            if cfg.mixed_prefill_rows * cfg.mixed_prefill_len > cap:
                # the smallest rectangle still exceeds the configured
                # prefill budget: running it anyway would silently
                # violate the HBM headroom that budget reserves
                log.warning(
                    "mixed prefill rectangle %dx%d exceeds "
                    "max_prefill_tokens=%d; disabling mixed batching",
                    cfg.mixed_prefill_rows, cfg.mixed_prefill_len, cap,
                )
                cfg.mixed_prefill_rows = 0
            self.scheduler.mixed_prefill_rows = cfg.mixed_prefill_rows
            self.scheduler.mixed_prefill_len = cfg.mixed_prefill_len
            # adaptive WIDE rectangle: same token budget, fewer rows —
            # long prompts at low decode occupancy prefill in
            # backlog/wide_len windows instead of backlog/len
            # (config.mixed_prefill_wide_len; scheduler._mixed_rect)
            wide = getattr(cfg, "mixed_prefill_wide_len", 0)
            if cfg.mixed_prefill_rows > 0 and wide > cfg.mixed_prefill_len:
                # never wider than one prefill chunk: _plan_prefill_batch
                # caps every row's chunk at prefill_chunk_size, so a
                # longer rectangle would dispatch permanently-padded
                # dead tokens. Round DOWN to a bucket (next_bucket
                # rounds up, which for a non-bucket chunk size like 512
                # would reintroduce exactly that padding).
                wl = next_bucket(min(wide, cfg.prefill_chunk_size), pc)
                while wl > cfg.prefill_chunk_size and wl > pc[0]:
                    wl = down(wl, pc)
                while wl > max(cap, pc[0]) and wl > pc[0]:
                    wl = down(wl, pc)
                # the wide rect keeps the narrow rect's token budget
                # (rows*len): shrink wl until at least one row fits —
                # if that lands back at the narrow len, the budget is
                # too small for a wide variant and it stays disabled
                budget = cfg.mixed_prefill_rows * cfg.mixed_prefill_len
                while budget // wl < 1 and wl > pc[0]:
                    wl = down(wl, pc)
                wr = min(budget // wl, cap // wl)
                if wl > cfg.mixed_prefill_len and wr >= 1:
                    sched = self.scheduler
                    if wr not in sched.prefill_batch_buckets:
                        # the rectangle must be a batch bucket, or
                        # bucketed prefill arrays round PAST it and
                        # every wide mixed step crashes
                        sched.prefill_batch_buckets = sorted(
                            set(sched.prefill_batch_buckets) | {wr}
                        )
                    sched.mixed_prefill_wide_rows = wr
                    sched.mixed_prefill_wide_len = wl
                    sched.mixed_wide_max_running = getattr(
                        cfg, "mixed_wide_max_running", None
                    )
                    self._wide_rect = (wr, wl)
        self.scheduler.on_finish = self._emit_finish
        if cfg.disk_kv_blocks > 0 and cfg.host_kv_blocks <= 0:
            raise ValueError(
                "disk_kv_blocks requires host_kv_blocks > 0 (G3 demotion "
                "cascades from the G2 host tier)"
            )
        if cfg.remote_kv_bucket and cfg.host_kv_blocks <= 0:
            raise ValueError(
                "remote_kv_bucket requires host_kv_blocks > 0 (the G4 "
                "remote tier demotes from / onboards through the G2 host "
                "tier) — a configured remote tier must not vanish silently"
            )
        if cfg.host_kv_blocks > 0 and cfg.num_nodes > 1:
            # Sharded KV offload (docs/multihost.md): each process
            # offloads only its LOCAL shard via mirrored gather/scatter
            # broadcasts — G2 host tier only; disk/remote demotion and
            # disagg export stay single-host features.
            if cfg.disk_kv_blocks > 0 or cfg.remote_kv_bucket:
                log.warning(
                    "disk/remote KV tiers unsupported with num_nodes>1; "
                    "serving with the sharded host tier only"
                )
            if cfg.node_rank == 0:
                from dynamo_tpu.parallel.multihost import ShardedKvOffload

                assert self._mh_broadcast is not None
                self.kvbm = ShardedKvOffload(
                    self, self._mh_broadcast,
                    host_num_blocks=cfg.host_kv_blocks,
                    offload_batch=cfg.kv_offload_batch,
                )
                self.scheduler.onboard = self._safe_onboard
            # followers build their shard pool inside StepFollower.run
        elif cfg.host_kv_blocks > 0:
            self.kvbm = KvBlockManager(
                KvbmConfig(
                    host_num_blocks=cfg.host_kv_blocks,
                    disk_num_blocks=cfg.disk_kv_blocks,
                    disk_path=cfg.disk_kv_path
                    or f"/tmp/dynamo_tpu_kv_{os.getpid()}_{uuid.uuid4().hex[:8]}.bin",
                    offload_batch=cfg.kv_offload_batch,
                    remote_bucket=cfg.remote_kv_bucket,
                ),
                BlockLayout.for_model(
                    self.model_config, cfg.block_size, cfg.wire_kv_dtype()
                ),
                gather_fn=self._kv_gather,
                scatter_fn=self._kv_scatter,
                resolve_fn=self.allocator.lookup_block,
                remote_objects=getattr(self, "_remote_kv_objects", None),
            )
            self.scheduler.onboard = self._safe_onboard
        self._ensure_qmatmul_tuned()
        self._build_step_fn()
        prewarm = cfg.prewarm
        if prewarm is None:
            prewarm = jax.default_backend() == "tpu"
        self._gate_kv_offload()
        if prewarm:
            self._prewarm()
        # HBM accounting: long-lived allocations once, live stats on
        # refresh (per-step sampled + every /debug/state snapshot)
        self.hbm.set_device(devices[0] if len(devices) else None)
        self.hbm.set_static(
            tree_bytes(self.params), tree_bytes((self.k_cache, self.v_cache))
        )
        self.hbm.refresh()
        log.info(
            "engine up: %s, mesh=%s, blocks=%d×%d",
            cfg.model_name,
            dict(zip(self.mesh.axis_names, self.mesh.devices.shape)),
            num_blocks,
            cfg.block_size,
        )

    def _ensure_qmatmul_tuned(self) -> None:
        """Resolve tile configs for every qmatmul shape the step
        functions can reach, BEFORE those functions trace — the tile
        choice is a trace-time constant, so a tuned entry landing after
        tracing would never be used. Reads the on-disk tune table;
        with DYN_QMATMUL_TUNE=1 on TPU, missing shapes are measured and
        persisted here (one-time cost, then cached). The step-shape
        prewarm below then compiles the kernels as part of the jitted
        steps — no separate kernel warmup is needed."""
        from dynamo_tpu.models.llama import pallas_matmul_active

        if not pallas_matmul_active() or self.config.quantization != "int8":
            return
        mc, sched = self.model_config, self.scheduler
        assert mc is not None and sched is not None
        D, F, V = mc.hidden_size, mc.intermediate_size, mc.vocab_size
        H, Hk, Dh = (
            mc.num_attention_heads, mc.num_key_value_heads, mc.head_dim,
        )
        decode_buckets = sorted(
            {b for b in (sched.decode_batch_small, sched.decode_batch_mid,
                         sched.decode_batch_pad) if b}
        ) or [1]
        ms = set(decode_buckets)
        max_chunk = next_bucket(
            self.config.prefill_chunk_size, sched.prefill_chunk_buckets
        )
        for b in sched.prefill_batch_buckets:
            for chunk in sched.prefill_chunk_buckets:
                if chunk <= max_chunk:
                    ms.add(b * chunk)
        if self.config.spec_decode:
            for b in decode_buckets:
                ms.add(b * (self.config.spec_tokens + 1))
        shapes: list[tuple[int, int, int, str]] = []
        for m in sorted(ms):
            shapes += [
                (m, D, H * Dh, "mm"),          # wq
                (m, D, Hk * Dh, "mm"),         # wk / wv
                (m, H * Dh, D, "residual"),    # wo + residual epilogue
                (m, F, D, "residual"),         # w_down + residual epilogue
                (m, D, F, "gate_up"),          # fused gate/up
            ]
        # lm_head reads [B, D] (last-token gather) on every non-spec
        # path; the spec verify path feeds the full [B, S] rectangle
        lm_ms = set(decode_buckets) | set(sched.prefill_batch_buckets)
        if self.config.spec_decode:
            lm_ms |= {b * (self.config.spec_tokens + 1) for b in decode_buckets}
        for m in sorted(lm_ms):
            shapes.append((m, D, V, "lm_head"))
        from dynamo_tpu.ops import qmatmul

        qmatmul.ensure_tuned(shapes)

    def _prewarm(self) -> None:
        """Compile every serving-path shape variant NOW, before the
        engine accepts traffic. With static_shapes the reachable set is
        small and fixed: the fused decode window, the mixed window, and
        the dedicated-prefill rectangles. A lazy compile is minutes
        over a chip tunnel and would land mid-serve as a 100 s+ TTFT
        stall (measured). All dummy work writes to the reserved garbage
        slot 0 with ctx=0, so the KV cache is untouched semantically."""
        sched = self.scheduler
        assert sched is not None
        t0 = time.monotonic()
        width = sched.table_width_pad or sched.TABLE_BUCKET

        def sampling_for(
            n: int, penalties: bool = False, toplp: bool = False,
            bias: bool = False,
        ) -> SamplingBatch:
            opts = (
                SamplingOptions(
                    temperature=1.0, frequency_penalty=0.1,
                    presence_penalty=0.1, repetition_penalty=1.1,
                )
                if penalties
                else SamplingOptions(use_greedy=True)
            )
            if bias:
                opts = opts.model_copy(update={"logit_bias": {1: 0.0}})
            return SamplingBatch.from_options(
                [opts] * n, [0] * n,
                [{} for _ in range(n)] if penalties else None,
                [np.zeros((0,), np.int32)] * n if penalties else None,
                [1] * n if toplp else None,
            )

        # Opt-in sampling-feature variants beyond the base signature,
        # as (penalties, toplp, bias) tuples. prewarm_penalties warms
        # the penalty AND logit-bias single-feature variants (the two
        # features that divert to dedicated prefill + pure windows);
        # prewarm_logprobs warms top-logprobs; with both flags the
        # penalties+toplp combo is warmed too. Multi-feature combos
        # beyond that (e.g. bias+penalties in one batch) still compile
        # on first use — the cross product would explode startup time.
        feat_variants: list[tuple[bool, bool, bool]] = [
            (False, False, False)
        ]
        if self.config.prewarm_logprobs:
            feat_variants.append((False, True, False))
        if self.config.prewarm_penalties:
            feat_variants.append((True, False, False))
            feat_variants.append((False, False, True))
        if self.config.prewarm_logprobs and self.config.prewarm_penalties:
            feat_variants.append((True, True, False))

        def prefill_arrays(b: int, t: int) -> dict[str, np.ndarray]:
            return {
                "tokens": np.zeros((b, t), np.int32),
                "positions": np.zeros((b, t), np.int32),
                "slot_mapping": np.zeros((b * t,), np.int32),
                "block_tables": np.zeros((b, width), np.int32),
                "context_lens": np.zeros((b,), np.int32),
                "last_token_idx": np.zeros((b,), np.int32),
            }

        def decode_arrays(b: int) -> dict[str, np.ndarray]:
            return {
                "tokens": np.zeros((b, 1), np.int32),
                "positions": np.zeros((b, 1), np.int32),
                "slot_mapping": np.zeros((b,), np.int32),
                "block_tables": np.zeros((b, width), np.int32),
                "context_lens": np.zeros((b,), np.int32),
                "valid_steps": np.zeros((b,), np.int32),
                "last_token_idx": np.zeros((b,), np.int32),
            }

        # NOTE: direct jitted calls, NOT _run_device_step — prewarm runs
        # during _initialize on every rank in the same order, before the
        # followers' receive loop exists, so the step broadcast must not
        # fire here (the jit's own collectives line up because all ranks
        # prewarm the same shapes in the same sequence).
        max_chunk = next_bucket(
            self.config.prefill_chunk_size, sched.prefill_chunk_buckets
        )
        chunks = [c for c in sched.prefill_chunk_buckets if c <= max_chunk]
        # two passes: the first call sees the init_cache sharding, later
        # ones XLA's canonical output sharding — a different jit
        # signature. Pass 2 ensures every shape is compiled against the
        # steady-state sharding (cache hit if they're equal).
        p_outs: dict[int, tuple] = {}  # base-variant prefill outputs
        for _ in range(2):
            for chunk in chunks:
                for b in sched.prefill_batch_buckets:
                    # the planner only emits multi-row rectangles whose
                    # padded area fits the prefill token budget (single-
                    # row steps may use the full chunk regardless)
                    if (
                        b > sched.prefill_batch_buckets[0]
                        and b * chunk > sched.max_prefill_tokens
                    ):
                        continue
                    for pv, tv, bv in feat_variants:
                        a = prefill_arrays(b, chunk)
                        s = sampling_for(b, penalties=pv, toplp=tv, bias=bv)
                        out = self._step_fn(
                            self.params, self.k_cache, self.v_cache,
                            a["tokens"], a["positions"],
                            a["slot_mapping"], a["block_tables"],
                            a["context_lens"], a["last_token_idx"],
                            s.arrays,
                        )
                        self.k_cache, self.v_cache = out[-2], out[-1]
                        if not (pv or tv or bv):
                            # retained for the overlap-glue warm below
                            p_outs[b] = out[:2]
                        jax.block_until_ready(self.k_cache)
        decode_buckets = sorted(
            {b for b in (sched.decode_batch_small, sched.decode_batch_mid,
                         sched.decode_batch_pad)
             if b}
        ) or [next_bucket(1, sched.BATCH_BUCKETS)]
        B = decode_buckets[-1]
        if self._multi_step_fn is not None:
            # opt-in sampling-feature window variants (the base window
            # is warmed with chaining below)
            for Bd in decode_buckets:
                for pv, tv, bv in feat_variants[1:]:
                    a = decode_arrays(Bd)
                    packed, _, self.k_cache, self.v_cache = (
                        self._multi_step_fn(
                            self.params, self.k_cache, self.v_cache,
                            a["tokens"], a["positions"], a["block_tables"],
                            a["context_lens"], a["valid_steps"],
                            sampling_for(
                                Bd, penalties=pv, toplp=tv, bias=bv
                            ).arrays,
                        )
                    )
                    jax.block_until_ready(packed)
        if self._multi_step_fn is None:
            # single-step decode serving shapes (decode_steps == 1)
            for Bd in decode_buckets:
                for pv, tv, bv in feat_variants:
                    a = decode_arrays(Bd)
                    s = sampling_for(Bd, penalties=pv, toplp=tv, bias=bv)
                    out = self._step_fn(
                        self.params, self.k_cache, self.v_cache,
                        a["tokens"], a["positions"], a["slot_mapping"],
                        a["block_tables"], a["context_lens"],
                        a["last_token_idx"], s.arrays,
                    )
                    self.k_cache, self.v_cache = out[-2], out[-1]
                    jax.block_until_ready(self.k_cache)
        if self._multi_step_fn is None and self._overlap_ok():
            # overlapped decode pipeline variants (docs/performance.md)
            # — warmed on spec engines too: zero-proposal/suspended/
            # opted-out batches fall back to the plain decode paths, and
            # an unwarmed chained variant would be a mid-serve compile.
            # the chained dispatch feeds the previous step's DEVICE
            # token column — a committed device array is a different
            # jit signature than host numpy — plus the packed harvest
            # and the chain gathers, including bucket transitions for a
            # shrinking population. An unwarmed variant is a mid-serve
            # compile.
            toks_by_bucket: dict[int, Any] = {}
            for Bd in decode_buckets:
                a = decode_arrays(Bd)
                s = sampling_for(Bd)
                out = self._step_fn(
                    self.params, self.k_cache, self.v_cache,
                    a["tokens"], a["positions"], a["slot_mapping"],
                    a["block_tables"], a["context_lens"],
                    a["last_token_idx"], s.arrays,
                )
                self.k_cache, self.v_cache = out[-2], out[-1]
                col = self._chain_next_fn(out[0], np.zeros((Bd,), np.int32))
                out = self._step_fn(
                    self.params, self.k_cache, self.v_cache,
                    col, a["positions"], a["slot_mapping"],
                    a["block_tables"], a["context_lens"],
                    a["last_token_idx"], s.arrays,
                )
                self.k_cache, self.v_cache = out[-2], out[-1]
                jax.block_until_ready(self._pack_pair_fn(out[0], out[1]))
                toks_by_bucket[Bd] = out[0]
            for b_from, tok in toks_by_bucket.items():
                for b_to in decode_buckets:
                    if b_to != b_from:
                        self._chain_next_fn(tok, np.zeros((b_to,), np.int32))
        if self._multi_step_fn is not None and self._overlap_ok():
            # cohort-graduation glue (the window pipeline's prefill-only
            # entry): packed prefill harvest + first-token chain from
            # each prefill batch bucket into each decode bucket (the
            # chained window itself shares the chain_pure-warmed
            # signature — ns_rep2-constrained device column)
            for b, (nt, lp) in p_outs.items():
                jax.block_until_ready(self._pack_pair_fn(nt, lp))
                for Bd in decode_buckets:
                    self._chain_next_fn(nt, np.zeros((Bd,), np.int32))
        if self._spec_step_fn is not None:
            # speculative verify shapes: one fixed [B, spec_tokens+1]
            # rectangle per decode bucket (greedy and sampled rows share
            # the one compiled variant — verify's sampling machinery is
            # a runtime lax.cond)
            Ssp = self.config.spec_tokens + 1

            def spec_arrays(b: int) -> dict[str, np.ndarray]:
                return {
                    "tokens": np.zeros((b, Ssp), np.int32),
                    "positions": np.zeros((b, Ssp), np.int32),
                    "slot_mapping": np.zeros((b * Ssp,), np.int32),
                    "block_tables": np.zeros((b, width), np.int32),
                    "context_lens": np.zeros((b,), np.int32),
                    "draft_lens": np.zeros((b,), np.int32),
                }

            spec_packed: dict[int, Any] = {}
            for Bd in decode_buckets:
                sa = spec_arrays(Bd)
                packed, self.k_cache, self.v_cache = self._spec_step_fn(
                    self.params, self.k_cache, self.v_cache,
                    sa["tokens"], sa["positions"], sa["slot_mapping"],
                    sa["block_tables"], sa["context_lens"],
                    sa["draft_lens"], sampling_for(Bd).arrays,
                )
                jax.block_until_ready(packed)
                spec_packed[Bd] = packed
            if self._overlap_ok() and self._chain_spec_fn is not None:
                # pipelined spec variants (docs/speculative_decoding.md):
                # the verify rectangle fed a DEVICE token column — the
                # carry chained from the previous step's packed output
                # is a committed device array, a different jit signature
                # than host numpy — plus the chain gathers themselves,
                # including bucket TRANSITIONS for a shrinking
                # population. An unwarmed variant is a mid-serve
                # compile, the same gap the decode pipeline's prewarm
                # closes for plain decode.
                for Bd in decode_buckets:
                    # transitions only SHRINK (the pipeline never
                    # admits; survivors are a subset of the previous
                    # rows), so growing b_from < Bd pairs are
                    # unreachable and not worth a compile
                    for b_from in decode_buckets:
                        if b_from < Bd:
                            continue
                        col = self._chain_spec_fn(
                            spec_packed[b_from],
                            np.zeros((Bd, Ssp), np.int32),
                            np.zeros((Bd,), np.int32),
                        )
                        if b_from != Bd:
                            continue
                        sa = spec_arrays(Bd)
                        packed, self.k_cache, self.v_cache = (
                            self._spec_step_fn(
                                self.params, self.k_cache, self.v_cache,
                                col, sa["positions"], sa["slot_mapping"],
                                sa["block_tables"], sa["context_lens"],
                                sa["draft_lens"], sampling_for(Bd).arrays,
                            )
                        )
                        jax.block_until_ready(packed)
                        spec_packed[Bd] = packed
        lasts: dict[int, Any] = {}
        p_nexts: dict[int, Any] = {}
        if self._multi_step_fn is not None:
            for Bd in decode_buckets:
                a, s = decode_arrays(Bd), sampling_for(Bd)
                packed, last_tok, self.k_cache, self.v_cache = (
                    self._multi_step_fn(
                        self.params, self.k_cache, self.v_cache, a["tokens"],
                        a["positions"], a["block_tables"],
                        a["context_lens"], a["valid_steps"], s.arrays,
                    )
                )
                # the pipelined path feeds the previous window's DEVICE
                # token column — a committed device array is a different
                # jit signature than host numpy, so warm that variant
                # too (an unwarmed variant is a mid-serve compile)
                if self._chain_pure_fn is not None:
                    last_tok = self._chain_pure_fn(
                        last_tok, np.zeros((Bd,), np.int32)
                    )
                packed, last_tok, self.k_cache, self.v_cache = (
                    self._multi_step_fn(
                        self.params, self.k_cache, self.v_cache, last_tok,
                        a["positions"], a["block_tables"],
                        a["context_lens"], a["valid_steps"], s.arrays,
                    )
                )
                jax.block_until_ready(packed)
                lasts[Bd] = last_tok
        if (
            self._mixed_step_fn is not None
            and sched.mixed_prefill_rows > 0
        ):
            rects = [
                (self.config.mixed_prefill_rows, self.config.mixed_prefill_len)
            ]
            if self._wide_rect is not None:
                rects.append(self._wide_rect)
            for P, T in rects:
                p = prefill_arrays(P, T)
                sp = sampling_for(P)
                for Bd in decode_buckets:
                    d = decode_arrays(Bd)
                    sd = sampling_for(Bd)
                    flat, m_last, p_next, self.k_cache, self.v_cache = (
                        self._mixed_step_fn(
                            self.params, self.k_cache, self.v_cache,
                            p["tokens"], p["positions"], p["slot_mapping"],
                            p["block_tables"], p["context_lens"],
                            p["last_token_idx"], sp.arrays,
                            d["tokens"], d["positions"], d["block_tables"],
                            d["context_lens"], d["valid_steps"], sd.arrays,
                        )
                    )
                    assert self._chain_fn is not None
                    chained = self._chain_fn(
                        m_last, p_next, np.zeros((Bd,), np.int32)
                    )
                    # chained-token mixed variant (pipelined mixed windows)
                    flat, m_last, p_next, self.k_cache, self.v_cache = (
                        self._mixed_step_fn(
                            self.params, self.k_cache, self.v_cache,
                            p["tokens"], p["positions"], p["slot_mapping"],
                            p["block_tables"], p["context_lens"],
                            p["last_token_idx"], sp.arrays,
                            chained, d["positions"], d["block_tables"],
                            d["context_lens"], d["valid_steps"], sd.arrays,
                        )
                    )
                    jax.block_until_ready(flat)
                    lasts[Bd] = m_last
                    p_nexts[(Bd, P)] = p_next
        if self._chain_pure_fn is not None:
            # chain gathers across bucket TRANSITIONS (population
            # crossing the small-bucket boundary mid-pipeline), for
            # every prefill-rectangle width in play (narrow + wide)
            for b_from in decode_buckets:
                for b_to in decode_buckets:
                    if b_from == b_to or b_from not in lasts:
                        continue
                    idx = np.zeros((b_to,), np.int32)
                    self._chain_pure_fn(lasts[b_from], idx)
                    for (bf, pw), pn in p_nexts.items():
                        if bf == b_from:
                            self._chain_fn(lasts[b_from], pn, idx)
        if self.config.prewarm_guided:
            self._prewarm_guided(
                chunks, decode_buckets, sampling_for, prefill_arrays,
                decode_arrays,
            )
        if self.kvbm is not None and self._mh_broadcast is None:
            # (single-host manager only: the multihost sharded offload
            # runs mirrored gathers, a different program)
            # KV offload/onboard shapes: each gather/scatter id bucket is
            # its own cache-sized jit program — an unwarmed bucket lands
            # as a mid-serve stall exactly when the first conversation's
            # blocks offload (measured: the multi-turn A/B's first turns
            # all stalled ~80 s together). Warm the buckets the offload
            # batch and prompt-onboard paths can reach.
            from dynamo_tpu.ops.block_copy import ID_BUCKETS

            width_cap = sched.table_width_pad or 32
            max_ids = min(
                max(self.config.kv_offload_batch, width_cap),
                ID_BUCKETS[-1],
            )
            for b in [x for x in ID_BUCKETS if x <= max_ids]:
                ids = [0] * b  # garbage block: reads/writes are harmless
                data = self._kv_gather(ids)
                self._kv_scatter(ids, data)
            jax.block_until_ready(self.k_cache)
        ENGINE_PREWARM_SECONDS.set(time.monotonic() - t0)
        log.info("prewarm done in %.1fs", time.monotonic() - t0)

    def _prewarm_guided(
        self, chunks, decode_buckets, sampling_for, prefill_arrays,
        decode_arrays,
    ) -> None:
        """Warm the guided (allow-mask) jit variants — the masked
        serial prefill rectangles and decode buckets, plus the masked
        spec-verify rectangle on spec engines (docs/guided_decoding.md).
        The mask is a presence-keyed sampling-pytree entry, so each is
        its own compiled signature; an unwarmed one would land as a
        mid-serve compile exactly when the first structured-output
        request arrives (the compile fence flags it). Runs AFTER the
        base warms, so every cache input already carries the
        steady-state sharding. Guided serving is serial by design
        (overlap/spec pipelines flush to serial), so no chained
        device-column masked variants exist to warm."""
        sched = self.scheduler
        assert sched is not None and self.model_config is not None
        V = self.model_config.vocab_size

        def masked(s: SamplingBatch, b: int, S: Optional[int] = None):
            out = SamplingBatch(dict(s.arrays))
            shape = (b, V) if S is None else (b, S, V)
            out.arrays["allow_mask"] = np.ones(shape, dtype=bool)
            return out

        # masked variants mirror the base prewarm's opt-in flag policy
        # (penalties/bias under prewarm_penalties, top-logprobs under
        # prewarm_logprobs): a guided request combined with a feature
        # whose flag is off pays the same documented first-use compile
        # the unguided feature pays
        feat_variants: list[tuple[bool, bool, bool]] = [
            (False, False, False)
        ]
        if self.config.prewarm_logprobs:
            feat_variants.append((False, True, False))
        if self.config.prewarm_penalties:
            feat_variants.append((True, False, False))
            feat_variants.append((False, False, True))
        for chunk in chunks:
            for b in sched.prefill_batch_buckets:
                if (
                    b > sched.prefill_batch_buckets[0]
                    and b * chunk > sched.max_prefill_tokens
                ):
                    continue
                for pv, tv, bv in feat_variants:
                    a = prefill_arrays(b, chunk)
                    s = masked(
                        sampling_for(b, penalties=pv, toplp=tv, bias=bv), b
                    )
                    out = self._step_fn(
                        self.params, self.k_cache, self.v_cache,
                        a["tokens"], a["positions"], a["slot_mapping"],
                        a["block_tables"], a["context_lens"],
                        a["last_token_idx"], s.arrays,
                    )
                    self.k_cache, self.v_cache = out[-2], out[-1]
                    jax.block_until_ready(self.k_cache)
        for Bd in decode_buckets:
            for pv, tv, bv in feat_variants:
                a = decode_arrays(Bd)
                s = masked(
                    sampling_for(Bd, penalties=pv, toplp=tv, bias=bv), Bd
                )
                out = self._step_fn(
                    self.params, self.k_cache, self.v_cache,
                    a["tokens"], a["positions"], a["slot_mapping"],
                    a["block_tables"], a["context_lens"],
                    a["last_token_idx"], s.arrays,
                )
                self.k_cache, self.v_cache = out[-2], out[-1]
                jax.block_until_ready(self.k_cache)
        if self._spec_step_fn is not None:
            Ssp = self.config.spec_tokens + 1
            width = sched.table_width_pad or sched.TABLE_BUCKET
            for Bd in decode_buckets:
                sa = {
                    "tokens": np.zeros((Bd, Ssp), np.int32),
                    "positions": np.zeros((Bd, Ssp), np.int32),
                    "slot_mapping": np.zeros((Bd * Ssp,), np.int32),
                    "block_tables": np.zeros((Bd, width), np.int32),
                    "context_lens": np.zeros((Bd,), np.int32),
                    "draft_lens": np.zeros((Bd,), np.int32),
                }
                s = masked(sampling_for(Bd), Bd, S=Ssp)
                packed, self.k_cache, self.v_cache = self._spec_step_fn(
                    self.params, self.k_cache, self.v_cache,
                    sa["tokens"], sa["positions"], sa["slot_mapping"],
                    sa["block_tables"], sa["context_lens"],
                    sa["draft_lens"], s.arrays,
                )
                jax.block_until_ready(packed)

    def _gate_kv_offload(self) -> None:
        """Restore-vs-recompute gate for the G2 host tier: probe the
        REAL host<->device copy bandwidth and drop the tier when
        restoring a block costs more than recomputing its tokens.

        Rationale (measured, benchmarks/RESULTS.md): on a tunneled chip
        a 16.8 MB block moves slower than the flash-prefill path
        recomputes its 128 tokens, so every onboard and write-through
        offload made multi-turn serving STRICTLY worse (16x collapse
        unthrottled, 2x throttled). On directly-attached hardware
        (PCIe/DMA, or CPU where host==device) the probe passes and the
        tier behaves as designed. kv_offload_force keeps it
        unconditionally."""
        cfg = self.config
        if self.kvbm is None:
            return
        if self._mh_broadcast is not None:
            # sharded tier: mirrored transfers, no local probe — keep
            # the full busy-path batch (None = pump default) rather
            # than starving offload to idle-only with no measurement
            self._kv_busy_pump_cap = None
            return
        n = 4
        ids = [0] * n  # garbage block: harmless reads/writes
        data = self._kv_gather(ids)  # compile
        self._kv_scatter(ids, data)
        jax.block_until_ready(self.k_cache)
        # best-of-3: one contended sample must not permanently kill a
        # tier the link can actually sustain (capacity question ->
        # best observed bandwidth is the right estimator)
        gather_bps = scatter_bps = 0.0
        for _ in range(3):
            t0 = time.monotonic()
            data = self._kv_gather(ids)
            t1 = time.monotonic()
            self._kv_scatter(ids, data)
            jax.block_until_ready(self.k_cache)
            t2 = time.monotonic()
            gather_bps = max(gather_bps, data.nbytes / max(t1 - t0, 1e-9))
            scatter_bps = max(scatter_bps, data.nbytes / max(t2 - t1, 1e-9))
        block_bytes = data.nbytes / n
        # restoring a block must beat recomputing block_size tokens
        required = block_bytes * cfg.kv_recompute_tok_per_s / max(
            1, cfg.block_size or 1
        )
        bps = min(gather_bps, scatter_bps)
        # busy-path offload cap from the measured bandwidth: allow only
        # what fits in ~20 ms between serving steps (0 on slow links —
        # transfers then wait for idle moments)
        self._kv_busy_pump_cap = min(4, int(bps * 0.02 / block_bytes))
        if bps >= required:
            log.info(
                "G2 host KV tier active: copy bandwidth %.0f MB/s >= "
                "threshold %.0f MB/s (busy-path cap %d blocks/step)",
                bps / 1e6, required / 1e6, self._kv_busy_pump_cap,
            )
        elif cfg.kv_offload_force or cfg.disk_kv_blocks > 0 or cfg.remote_kv_bucket:
            # explicitly configured G3/G4 tiers must not vanish behind
            # a probe (mirrors the config-time invariant above): keep
            # the cascade, loudly
            log.warning(
                "G2 host KV tier kept (%s) despite copy bandwidth "
                "%.0f MB/s < restore-beats-recompute threshold "
                "%.0f MB/s — restores will be slower than recompute "
                "on this link",
                "kv_offload_force" if cfg.kv_offload_force
                else "G3/G4 tiers configured",
                bps / 1e6, required / 1e6,
            )
        else:
            log.warning(
                "G2 host KV tier disabled: measured copy bandwidth "
                "%.0f MB/s (gather %.0f / scatter %.0f) is below the "
                "restore-beats-recompute threshold %.0f MB/s at "
                "kv_recompute_tok_per_s=%.0f — restoring blocks would "
                "be slower than re-prefilling them on this link. Set "
                "kv_offload_force=true to keep the tier.",
                bps / 1e6, gather_bps / 1e6, scatter_bps / 1e6,
                required / 1e6, cfg.kv_recompute_tok_per_s,
            )
            self._disable_kvbm()

    def _auto_num_blocks(self, devices) -> int:
        """Size the KV cache from free HBM (fallback: modest default)."""
        mc = self.model_config
        assert mc is not None
        # TPU tiling pads the cache's trailing [Hkv, Dh] dims (minor to
        # a 128-lane multiple, second-minor to the sublane tile) — a
        # small-geometry cache can occupy several× its unpadded bytes,
        # so size from PADDED dims or the chip overcommits at compile
        itemsize = jnp.dtype(self.config.kv_cache_dtype).itemsize
        dh_pad = -(-mc.head_dim // 128) * 128
        # second-minor bound: 8 covers the layouts observed on v5e for
        # the paged cache (bf16 caches lower to packed (..,128)(2,1)
        # tiles — empirically a [32,S,8,128] bf16 cache occupies its
        # unpadded bytes, so 16-sublane padding does NOT apply here)
        hk_pad = -(-mc.num_key_value_heads // 8) * 8
        bytes_per_block_total = (
            2  # K and V
            * mc.num_hidden_layers
            * self.config.block_size
            * hk_pad
            * dh_pad
            * itemsize
        )
        if jnp.dtype(self.config.kv_cache_dtype) == jnp.int8:
            # per-(slot, head) f32 scale planes ([L, N, Hk*bs] per K/V —
            # layout already lane-compact, no tile padding to model)
            bytes_per_block_total += (
                2 * mc.num_hidden_layers * self.config.block_size
                * mc.num_key_value_heads * 4
            )
        free = None
        try:
            stats = devices[0].memory_stats()
            free = stats["bytes_limit"] - stats["bytes_in_use"]
        except Exception:
            free = None
        if free is None and getattr(devices[0], "platform", "") != "tpu":
            # CPU/virtual test backends: a modest fixed pool. The
            # datasheet estimate below would size a gigantic cache and
            # stall worker bring-up allocating it.
            return 512
        if free is None:
            # tunneled chips report no memory stats: estimate from
            # datasheet HBM minus what the params actually occupy
            # (int8-aware via nbytes). An undersized fallback causes
            # recompute preemptions mid-serve, which is far worse than
            # a slightly optimistic estimate under 0.x utilization.
            hbm = {
                "TPU v5 lite": 16, "TPU v5e": 16, "TPU v4": 32,
                "TPU v5p": 95, "TPU v6 lite": 32, "TPU v6e": 32,
            }.get(getattr(devices[0], "device_kind", ""), 16) * (1 << 30)
            hbm = int(hbm * 0.98)  # runtime-reserved slice
            # params shard over tp×pp only; dp/ep replicas hold full
            # copies, so dividing by the whole device count would
            # overestimate free HBM by the dp factor
            n_shard = max(
                1,
                self.config.tensor_parallel_size
                * self.config.pipeline_parallel_size,
            )
            param_bytes = sum(
                x.nbytes for x in jax.tree_util.tree_leaves(self.params)
            ) / n_shard
            free = max(0.0, hbm - param_bytes)
        # step-transient headroom the cache must leave: a full batched
        # prefill's activations dominate — per token roughly 6 D-wide
        # bf16 tensors (h/q/k/v/attn/out), 3 F-wide (gate/up/act, ×E for
        # dense-compute MoE), plus f32 attention scores H × S_table
        # a prefill step's token area is capped by max_prefill_tokens
        # (scheduler._plan_prefill_batch budget), NOT the full
        # batch × chunk rectangle — ×2 covers bucket padding
        area = min(
            self.config.max_batch_size * self.config.prefill_chunk_size,
            2 * (self.config.max_prefill_tokens or self.config.prefill_chunk_size),
        )
        # scores-width estimate: only the XLA reference attention
        # materializes [T, S] scores (one layer-transient, capped so an
        # uncapped max_position_embeddings can't swallow the budget).
        # The Pallas flash kernels keep scores in VMEM — charging HBM
        # for them would waste gigabytes of KV capacity exactly on the
        # long-context workloads that need it (at max_model_len 3328 /
        # max_prefill_tokens 4096 the phantom term is ~4 GB).
        from dynamo_tpu.models.llama import pallas_attention_active

        if pallas_attention_active():
            s_est = 0
        else:
            s_est = min(
                (self.config.max_model_len or mc.max_position_embeddings)
                + 8 * self.config.block_size,
                4096,
            )
        e_mult = max(1, mc.num_local_experts)
        per_tok = (
            12 * mc.hidden_size
            + 6 * mc.intermediate_size * e_mult
            + 4 * mc.num_attention_heads * s_est
        )
        # activations shard over tp (hidden/head axes), so the per-device
        # transient shrinks with tp; flat guard covers scan/fusion
        # scratch the per-token model misses
        transient = (
            area * per_tok / self.config.tensor_parallel_size + (512 << 20)
        )
        budget = max(0.0, free - transient) * self.config.hbm_utilization
        # cache is sharded over tp: each device holds Hkv/tp heads
        budget_total = budget * (self.config.tensor_parallel_size
                                  * self.config.pipeline_parallel_size)
        n = int(budget_total // bytes_per_block_total)
        one_seq = -(-(self.config.max_model_len or mc.max_position_embeddings)
                    // self.config.block_size) + 2
        if n < one_seq:
            log.warning(
                "auto-sized KV cache (%d blocks) can't hold one "
                "max_model_len sequence (%d blocks): serving will thrash "
                "— lower max_batch_size/prefill_chunk_size or set "
                "num_blocks explicitly", n, one_seq,
            )
        return max(16, min(n, 1_000_000))

    def _on_kv_event(self, op: str, hashes: list[int], blocks: list[int]) -> None:
        if self.kvbm is not None and op == "stored":
            for h, b in zip(hashes, blocks):
                self.kvbm.on_block_committed(h, b)
        if self.kv_event_sink is not None:
            self.kv_event_sink(op, hashes, blocks)

    def _safe_onboard(self, hashes: list[int], blocks: list[int]) -> int:
        """Onboarding is an optimization: a lower-tier failure degrades to
        G1-only (a 0 return just means 'prefill those tokens normally')."""
        if self.kvbm is None:
            return 0
        from dynamo_tpu.parallel.multihost import FatalMultihostError

        try:
            return self.kvbm.onboard(hashes, blocks)
        except FatalMultihostError:
            raise  # inside a mirrored collective: not recoverable
        except Exception:
            log.exception("kv onboard failed; disabling kvbm")
            self._disable_kvbm()
            return 0

    # -- KVBM device data path (engine thread only: caches are donated) ----
    def _kv_gather(self, block_ids: list[int]) -> np.ndarray:
        return gather_blocks(
            self.k_cache, self.v_cache, block_ids, self.config.block_size
        )

    def _kv_scatter(self, block_ids: list[int], data: np.ndarray) -> None:
        self.k_cache, self.v_cache = scatter_blocks(
            self.k_cache, self.v_cache, block_ids, data, self.config.block_size
        )

    # ------------------------------------------------------------------
    # The fused device step
    # ------------------------------------------------------------------
    def _build_step_fn(self) -> None:
        mc = self.model_config
        block_size = self.config.block_size
        assert mc is not None

        # Pin every step fn's outputs to ONE canonical sharding. A jit
        # signature includes each input's committed sharding, and the
        # caches/token columns thread from outputs back into inputs —
        # without pinning, the sharding lineage (init vs step-output vs
        # mixed-output) silently forks the signature and a "prewarmed"
        # shape recompiles at serve time (measured: a 69 s mid-serve
        # stall for an already-warmed prefill shape).
        from jax.sharding import NamedSharding, PartitionSpec as PSpec

        if self._pp > 1:
            from dynamo_tpu.parallel.pipeline import PP_CACHE_SPEC

            cache_sp = PP_CACHE_SPEC
        else:
            cache_sp = CACHE_SPEC
        ns_cache = NamedSharding(self.mesh, cache_sp)
        ns_rep2 = NamedSharding(self.mesh, PSpec(None, None))
        ns_rep1 = NamedSharding(self.mesh, PSpec(None))
        from dynamo_tpu.models.llama import SCALE_SPEC

        ns_scale = NamedSharding(self.mesh, SCALE_SPEC)

        def pin_caches(k, v):
            def pin(c):
                if isinstance(c, tuple):  # int8 cache: (values, scales)
                    return (
                        jax.lax.with_sharding_constraint(c[0], ns_cache),
                        jax.lax.with_sharding_constraint(c[1], ns_scale),
                    )
                return jax.lax.with_sharding_constraint(c, ns_cache)

            return pin(k), pin(v)

        if self._pp > 1:
            from dynamo_tpu.parallel.pipeline import forward_pp

            mesh = self.mesh

            def forward(*a, **kw):  # noqa: F811 — pp-sharded model step
                return forward_pp(*a, mesh=mesh, **kw)
        else:
            from dynamo_tpu.models.llama import forward  # noqa: F811

        def step(
            params,
            k_cache,
            v_cache,
            tokens,
            positions,
            slot_mapping,
            block_tables,
            context_lens,
            last_token_idx,
            sampling,  # SamplingBatch.arrays pytree
            *mm_args,  # optionally (extra_embeds, embeds_mask)
        ):
            logits, new_k, new_v = forward(
                mc,
                params,
                k_cache,
                v_cache,
                tokens,
                positions,
                slot_mapping,
                block_tables,
                context_lens,
                last_token_idx,
                block_size,
                *mm_args,
            )
            # sample() returns 2 outputs on the base path, 4 when the
            # batch carries the top-logprobs marker (a separately-traced
            # variant — the pytree structure differs)
            s_out = sample(logits, sampling)
            new_k, new_v = pin_caches(new_k, new_v)
            return (*s_out, new_k, new_v)

        # donate the caches: XLA aliases them in-place. One jitted fn
        # serves both arities (jit retraces per signature); the
        # multimodal variant compiles only if a request uses it.
        self._step_fn = jax.jit(step, donate_argnums=(1, 2))
        self._step_fn_mm = self._step_fn

        K = self.config.decode_steps
        bs = block_size

        def decode_window(
            params,
            k_cache,
            v_cache,
            tokens,  # [B, 1] the last sampled token per sequence
            positions,  # [B, 1] its position
            block_tables,
            context_lens,
            valid_steps,  # [B] steps the seq will actually keep (<= K)
            sampling,  # SamplingBatch.arrays pytree
        ):
            """K fused decode steps: one dispatch, K tokens per sequence.
            Slot mapping is recomputed on-device from the advancing
            positions; sampling seeds advance per step so outputs match
            K single steps exactly. When the batch carries penalty
            tables, a dense [B, V] generated-token count rides the scan
            carry and updates after every sampled token, so penalties
            inside the window are exact too. When it carries the
            top-logprobs marker, each step's top-TOPLP_N alternatives
            ride the packed output (ids exact in f32: vocab < 2^24)."""
            has_pen = "rep_pen" in sampling
            has_tlp = "top_lp_n" in sampling
            B = tokens.shape[0]
            V = mc.vocab_size
            gen0 = dense_gen_counts(sampling, V) if has_pen else jnp.zeros((B, 1))
            prompt_dense = (
                dense_prompt_presence(sampling, V) if has_pen else None
            )

            def body(carry, i):
                k_c, v_c, tok, pos, ctx, gen = carry
                pos_flat = pos[:, 0]
                slot = (
                    jnp.take_along_axis(
                        block_tables, (pos_flat // bs)[:, None], axis=1
                    )[:, 0]
                    * bs
                    + pos_flat % bs
                )
                # The scheduler only allocates blocks for each sequence's
                # remaining-token budget; steps past that window would have
                # their table lookup clipped onto the seq's LAST REAL block
                # (take_along_axis clips), corrupting possibly-shared KV.
                # Redirect surplus writes to slot 0 — block 0 is the
                # reserved garbage block. The surplus outputs are
                # discarded host-side by _emit_window.
                slot = jnp.where(i < valid_steps, slot, 0)
                logits, k_c, v_c = forward(
                    mc, params, k_c, v_c, tok, pos, slot, block_tables,
                    ctx, jnp.zeros_like(pos_flat), bs,
                )
                s_i = dict(sampling)
                s_i["seeds"] = sampling["seeds"] + i.astype(jnp.uint32)
                s_res = sample(
                    logits, s_i,
                    gen if has_pen else None,
                    prompt_dense,
                )
                nt = s_res[0]
                if has_pen:
                    gen = gen.at[jnp.arange(B), nt].add(1.0)
                return (k_c, v_c, nt[:, None], pos + 1, ctx + 1, gen), s_res

            carry = (k_cache, v_cache, tokens, positions, context_lens, gen0)
            (k_cache, v_cache, last_tok, *_), ys = jax.lax.scan(
                body, carry, jnp.arange(K)
            )
            toks, lps = ys[0], ys[1]
            # one packed host transfer per window (tokens are exact in
            # f32: vocab ids < 2^24), plus the device-resident last
            # token column for chaining the next window without a host
            # round trip
            cols = [toks.T.astype(jnp.float32), lps.T]
            if has_tlp:
                # [K, B, N] -> [B, K*N]
                tids, tlps = ys[2], ys[3]
                N = tids.shape[-1]
                cols.append(
                    tids.transpose(1, 0, 2).reshape(B, K * N).astype(jnp.float32)
                )
                cols.append(tlps.transpose(1, 0, 2).reshape(B, K * N))
            packed = jnp.concatenate(cols, axis=1)  # [B, 2K (+2KN)]
            k_cache, v_cache = pin_caches(k_cache, v_cache)
            last_tok = jax.lax.with_sharding_constraint(last_tok, ns_rep2)
            return packed, last_tok, k_cache, v_cache

        def mixed_step(
            params,
            k_cache,
            v_cache,
            # prefill rectangle [P, T] (fixed shape; engine pads)
            p_tokens,
            p_positions,
            p_slot_mapping,
            p_block_tables,
            p_context_lens,
            p_last_idx,
            p_sampling,
            # decode window [B, 1]
            d_tokens,
            d_positions,
            d_block_tables,
            d_context_lens,
            d_valid_steps,
            d_sampling,
        ):
            """Mixed continuous-batching step: the pending prefill
            chunks run FIRST (so new requests' first tokens land this
            window), then the K-step decode window — one dispatch, one
            host round trip, no decode stall for stragglers' prefills.
            The prefill rectangle's weight reads are shared with the
            window only at the XLA-fusion level; its real win is that a
            ~1k-token rectangle adds ~10-15% to a window instead of a
            dedicated full-weight pass per straggler."""
            p_logits, k_cache, v_cache = forward(
                mc, params, k_cache, v_cache, p_tokens, p_positions,
                p_slot_mapping, p_block_tables, p_context_lens,
                p_last_idx, bs,
            )
            # top-logprobs batches never reach the mixed step (the
            # window pipeline diverts them to dedicated prefill +
            # pure windows — see _window_pipeline), so both sampling
            # dicts here are 2-output variants
            p_next, p_lp = sample(p_logits, p_sampling)
            packed, last_tok, k_cache, v_cache = decode_window(
                params, k_cache, v_cache, d_tokens, d_positions,
                d_block_tables, d_context_lens, d_valid_steps, d_sampling,
            )
            # ONE flat host transfer for all outputs: each separate
            # device->host read costs a full round trip over a tunneled
            # chip (~200 ms measured), which would triple the window's
            # sync cost. p_next additionally returns device-resident so
            # a pipelined next window can chain graduated prefills'
            # first tokens without a host hop.
            flat = jnp.concatenate(
                [packed.reshape(-1), p_next.astype(jnp.float32), p_lp]
            )
            p_next = jax.lax.with_sharding_constraint(p_next, ns_rep1)
            return flat, last_tok, p_next, k_cache, v_cache

        def chain_tokens(last_tok, p_next, src_idx):
            """Next window's token column, gathered on device from the
            in-flight window's outputs: rows [0, B) of the concat are
            the decode window's last tokens, rows [B, B+P) the prefill
            rectangle's sampled tokens (graduations)."""
            cat = jnp.concatenate([last_tok[:, 0], p_next])
            return jax.lax.with_sharding_constraint(
                jnp.take(cat, src_idx)[:, None], ns_rep2
            )

        def chain_tokens_pure(last_tok, src_idx):
            """Chain from a pure decode window (no prefill rectangle
            outputs to graduate)."""
            return jax.lax.with_sharding_constraint(
                jnp.take(last_tok[:, 0], src_idx)[:, None], ns_rep2
            )

        def chain_next(next_tokens, src_idx):
            """Next step's [B', 1] token column gathered on device from
            a single-step dispatch's sampled tokens [B] (the overlapped
            decode pipeline) or a prefill batch's sampled first tokens
            (the cohort-graduation entry) — no host round trip."""
            return jax.lax.with_sharding_constraint(
                jnp.take(next_tokens, src_idx)[:, None], ns_rep2
            )

        def pack_pair(next_tokens, logprobs):
            """One packed [2B] host transfer for a single-step
            dispatch's outputs (token ids exact in f32: vocab < 2^24) —
            over a tunneled chip each separate device->host read is a
            full round trip, so the overlapped pipeline's harvest syncs
            exactly one array per step."""
            return jax.lax.with_sharding_constraint(
                jnp.concatenate(
                    [next_tokens.astype(jnp.float32), logprobs]
                ),
                ns_rep1,
            )

        def spec_step(
            params,
            k_cache,
            v_cache,
            tokens,  # [B, S] carry token + up to S-1 drafts per row
            positions,  # [B, S] contiguous run from each row's base
            slot_mapping,  # [B*S] (pads -> garbage slot 0)
            block_tables,
            context_lens,  # [B] real tokens incl. drafts
            draft_lens,  # [B] valid drafts per row
            sampling,  # SamplingBatch.arrays (base path only)
        ):
            """Speculative verify step: ONE forward over the draft run
            through the paged-KV attention (draft KV is written
            speculatively — rejected positions are overwritten by the
            next real append before they can ever be read or
            content-addressed), then on-device rejection sampling
            (spec/verify.py). Output rides one packed host transfer
            (verify.pack_spec): [B, S out_tokens | S out_lps | 1 n_emit]."""
            from dynamo_tpu.spec.verify import pack_spec, verify_tokens

            logits_all, k_cache, v_cache = forward(
                mc, params, k_cache, v_cache, tokens, positions,
                slot_mapping, block_tables, context_lens,
                jnp.zeros_like(context_lens), bs, logits_all=True,
            )
            out_toks, out_lps, n_emit = verify_tokens(
                logits_all, tokens, draft_lens, sampling
            )
            packed = pack_spec(out_toks, out_lps, n_emit)
            k_cache, v_cache = pin_caches(k_cache, v_cache)
            packed = jax.lax.with_sharding_constraint(packed, ns_rep2)
            return packed, k_cache, v_cache

        def chain_spec(packed, host_tokens, src_idx):
            """Next verify step's [B', S] token rectangle for the
            overlapped spec pipeline: column 0 — each row's CARRY token
            (the in-flight step's LAST emitted token, out_tokens at
            n_emit-1) — gathered on device from the packed verify
            output, columns 1.. the host-proposed drafts. The spec
            twin of ``chain_next``: the carry never round-trips
            host<->device between consecutive verify steps, and the
            gather rebuckets a shrinking population (src_idx maps new
            rows onto the previous step's rows)."""
            S_ = host_tokens.shape[1]
            out_toks = packed[:, :S_].astype(jnp.int32)
            n_emit = packed[:, 2 * S_].astype(jnp.int32)
            carry = jnp.take_along_axis(
                out_toks, jnp.clip(n_emit - 1, 0, S_ - 1)[:, None], axis=1
            )[:, 0]
            col = jnp.take(carry, src_idx)
            return jax.lax.with_sharding_constraint(
                host_tokens.at[:, 0].set(col), ns_rep2
            )

        self._spec_step_fn = (
            jax.jit(spec_step, donate_argnums=(1, 2))
            if self.config.spec_decode
            else None
        )
        self._chain_spec_fn = (
            jax.jit(chain_spec) if self.config.spec_decode else None
        )

        self._multi_step_fn = (
            jax.jit(decode_window, donate_argnums=(1, 2)) if K > 1 else None
        )
        self._mixed_step_fn = (
            jax.jit(mixed_step, donate_argnums=(1, 2)) if K > 1 else None
        )
        self._chain_fn = jax.jit(chain_tokens) if K > 1 else None
        self._chain_pure_fn = jax.jit(chain_tokens_pure) if K > 1 else None
        # overlapped-pipeline glue (both K regimes): on-device token
        # chaining off a single-step/prefill dispatch + packed harvest
        self._chain_next_fn = jax.jit(chain_next)
        self._pack_pair_fn = jax.jit(pack_pair)

    def _stage_step_inputs(
        self, arrays: dict[str, np.ndarray], sampling: SamplingBatch
    ) -> tuple[dict, SamplingBatch]:
        """Explicitly stage the host-built step inputs onto the device
        before feeding the jitted step.  Under the armed transfer fence
        (DYN_TRANSFER_FENCE, utils/transfer_fence.py) a raw np.ndarray
        argument would trip the guard as an implicit host->device
        upload; ``jax.device_put`` is the sanctioned spelling of the
        same transfer.  Only ndarray leaves are staged — Python scalars
        keep their weak types (a device_put would change avals and
        recompile every step variant).  Inert when the fence is off:
        the default hot path feeds numpy exactly as before.  The
        fence tests monkeypatch this method to reintroduce the
        implicit upload the fence exists to catch."""
        if not transfer_fence.enabled():
            return arrays, sampling
        staged = {
            k: jax.device_put(v) if isinstance(v, np.ndarray) else v
            for k, v in arrays.items()
        }
        samp = SamplingBatch(arrays={
            k: jax.device_put(v) if isinstance(v, np.ndarray) else v
            for k, v in sampling.arrays.items()
        })
        return staged, samp

    def _dispatch_device_step(
        self,
        arrays: dict[str, np.ndarray],
        sampling: SamplingBatch,
        origin: str = "",
        defer_sync: bool = True,
    ) -> tuple:
        """DISPATCH half of a fused device step: announce (multihost),
        launch the jitted step, swap the donated caches, and return the
        sampled DEVICE outputs — no host sync. The caller harvests via
        ``_harvest_device_step`` when (and only when) it needs values;
        between the two, the host is free to plan/pack the next step
        while the device executes this one (docs/performance.md).

        ``origin`` labels the dispatch for deferred-error forensics: an
        async dispatch's device error only SURFACES at a later synced
        step (_annotate_deferred_error). ``defer_sync=False`` skips that
        registration — for callers that harvest THIS dispatch before
        doing anything else, its error surfaces under its own batch."""
        assert self._step_fn is not None
        if self._mh_broadcast is not None:
            if "extra_embeds" in arrays:
                # embed rectangle broadcasts as its own control kind so
                # followers enter the mm-variant step with real embeds
                self._mh_broadcast.announce_step_mm(arrays, sampling)
            else:
                self._mh_broadcast.announce_step(arrays, sampling)
        # stage AFTER the announce: followers deserialize host numpy
        arrays, sampling = self._stage_step_inputs(arrays, sampling)
        base_args = (
            self.params,
            self.k_cache,
            self.v_cache,
            arrays["tokens"],
            arrays["positions"],
            arrays["slot_mapping"],
            arrays["block_tables"],
            arrays["context_lens"],
            arrays["last_token_idx"],
            sampling.arrays,
        )
        idle_gap_s = self.overlap.note_dispatch()
        t_disp = time.monotonic()
        if "extra_embeds" in arrays:
            out = self._step_fn_mm(
                *base_args, arrays["extra_embeds"], arrays["embeds_mask"]
            )
        else:
            out = self._step_fn(*base_args)
        self.k_cache, self.v_cache = out[-2], out[-1]
        t_done = time.monotonic()
        self._last_phases = {
            "dispatch_ms": round((t_done - t_disp) * 1e3, 3),
            "idle_gap_ms": round(idle_gap_s * 1e3, 3),
        }
        if defer_sync:
            self._unsynced_steps.append(
                origin or f"shape={arrays['tokens'].shape}"
            )
            del self._unsynced_steps[:-8]  # bounded forensics window
        return out[:-2]

    def _harvest_device_step(self, outs: tuple) -> tuple:
        """HARVEST half: the designated host-sync point for step
        outputs (dynalint DL010 flags syncs anywhere else in the step
        loop). Blocks until the device result lands on host — under the
        overlapped pipeline that result is already (or nearly) done."""
        from dynamo_tpu.parallel.multihost import host_value

        t0 = time.monotonic()
        # (next_tokens, logprobs) base; (+ top_ids, top_lps) on the
        # top-logprobs variant
        res = tuple(host_value(x) for x in outs)
        self.overlap.note_complete(all_prior=True)
        self._last_phases["sync_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3
        )
        # a successful sync retires every earlier async dispatch
        # (in-order device execution): their deferred errors would have
        # surfaced in this host read
        self._unsynced_steps.clear()
        return res

    def _run_device_step(
        self,
        arrays: dict[str, np.ndarray],
        sampling: SamplingBatch,
        sync: bool = True,
        origin: str = "",
    ):
        """``sync=False`` skips the device->host read of the sampled
        outputs (returns None): a prefill batch with NO last chunks has
        no token anyone needs, and over a tunneled chip each host read
        is a full round trip (~200 ms measured) — a 3-chunk ISL-3000
        prompt pays it twice for nothing. The dispatch still happens
        (and still broadcasts under multihost); donated caches chain
        the next step regardless."""
        outs = self._dispatch_device_step(
            arrays, sampling, origin=origin, defer_sync=not sync
        )
        if not sync:
            return None
        return self._harvest_device_step(outs)

    # ------------------------------------------------------------------
    # Engine thread loop
    # ------------------------------------------------------------------
    @affinity.thread_affinity("engine")
    def _step_loop(self) -> None:
        affinity.register_thread("engine")
        try:
            self._step_loop_body()
        finally:
            # OS thread idents are reused — a stale binding would blame
            # "engine" for a later unrelated thread's writes
            affinity.unregister_thread()

    def _step_loop_body(self) -> None:
        if self._is_follower:
            # follower ranks mirror the leader's device dispatches until
            # the leader broadcasts STOP (parallel/multihost.py)
            from dynamo_tpu.parallel.multihost import StepFollower

            try:
                StepFollower(self).run()
            except Exception:
                log.exception("multihost follower loop failed")
            self._running = False  # dynalint: handoff=stop-flag — one-way bool, each side only ever writes False; readers poll per step/await
            return
        assert self.scheduler is not None
        from dynamo_tpu.parallel.multihost import FatalMultihostError

        def pump_kvbm(max_blocks: Optional[int] = None) -> bool:
            """False = fatal multihost failure: the loop must fail all
            requests and stop (a raise here would escape _step_loop and
            leave every request stream hanging on a dead thread)."""
            if self.kvbm is None:
                return True
            try:
                self.kvbm.pump(max_blocks)
            except FatalMultihostError:
                log.exception(
                    "fatal multihost failure inside a mirrored KV op; "
                    "taking the engine down"
                )
                return False
            except Exception:
                log.exception("kv offload pump failed; disabling kvbm")
                self._disable_kvbm()
            return True

        while self._running:
            # worker-liveness injection point: `kill` rules here model a
            # hard worker death between steps (one-shot by default)
            faults.fire("worker.liveness")
            self._drain_incoming()
            if self._draining:
                # graceful drain: hand off eligible in-flight streams at
                # this step boundary (every generated token has already
                # been emitted, so the router's commit log is exact)
                self._migrate_eligible()
            if (
                not self.scheduler.running
                and not self.scheduler.prefilling
                and len(self.scheduler.waiting) >= 2
            ):
                # an arrival BURST onto an idle engine: the submitter is
                # still enqueueing (e.g. a gather of N requests, or an
                # HTTP cohort) — planning now would split the burst
                # across prefill steps and desynchronize the decode
                # population for its whole lifetime. Wait out the burst
                # while it is still growing (bounded: ~16 ms worst case
                # vs a multi-hundred-ms prefill dispatch saved).
                # blocking sleep is deliberate: _step_loop runs on the
                # dedicated "jax-engine" thread (launch()), never on the
                # event loop, so this parks only the engine thread
                for _ in range(8):
                    before = len(self.scheduler.waiting)
                    time.sleep(0.002)
                    self._drain_incoming()
                    if len(self.scheduler.waiting) == before:
                        break
            if not self.scheduler.has_work:
                # idle: drain the offload queue (and run the pump's
                # periodic G4 index refresh) before sleeping. SMALL
                # batches per iteration: each block is a multi-MB
                # device->host transfer, and a request arriving
                # mid-batch must not wait out a 16-block gather.
                if not pump_kvbm(4):
                    self._fail_all()
                    self._running = False  # dynalint: handoff=stop-flag — one-way bool, each side only ever writes False; readers poll per step/await
                    return
                if self.kvbm is not None and self.kvbm.pending_offloads:
                    continue  # more queued: keep draining
                # no work: the wait for the next request is load, not a
                # device idle gap — drop the overlap tracker's anchor
                # and break the attribution timeline for the same reason
                self.overlap.note_idle()
                self.attribution.note_idle()
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                self._one_step()
                self._step_failures = 0
            except FatalMultihostError:
                log.exception(
                    "fatal multihost failure inside a mirrored collective; "
                    "taking the engine down"
                )
                self._fail_all()
                self._running = False  # dynalint: handoff=stop-flag — one-way bool, each side only ever writes False; readers poll per step/await
                return
            except Exception as exc:
                if transfer_fence.intercept(exc):
                    # the transfer guard raised at the offending site:
                    # the aborted step may never reach _record_step, so
                    # escalate here. Under fatal mode the fence error
                    # takes the engine down like a fatal multihost
                    # failure — streams get a terminal error, not a
                    # hang on a dead thread.
                    try:
                        self._check_transfer_fence("aborted")
                    except transfer_fence.TransferFenceError:
                        log.exception(
                            "serve-phase implicit transfer under "
                            "DYN_TRANSFER_FENCE=fatal; taking the "
                            "engine down"
                        )
                        self._fail_all()
                        self._running = False  # dynalint: handoff=stop-flag — one-way bool, each side only ever writes False; readers poll per step/await
                        return
                self._step_failures += 1
                # queue depth is unknowable after an aborted dispatch
                self.overlap.reset()
                self._annotate_deferred_error(exc)
                if not self._quarantine_step_failure():
                    log.exception(
                        "engine step failed; failing in-flight requests"
                    )
                    self._fail_all()
                continue
            # BUSY path: bounded by the probed copy bandwidth (~20 ms
            # of transfer per step; 0 on slow links). Unbounded
            # write-through offload between serving steps put multi-MB
            # transfers on every window and collapsed multi-turn
            # serving 16x on the tunneled chip (benchmarks/RESULTS.md);
            # pending commits are bounded by G1 size, revalidated at
            # pump time, and drain at idle moments.
            if not pump_kvbm(self._kv_busy_pump_cap):
                self._fail_all()
                self._running = False  # dynalint: handoff=stop-flag — one-way bool, each side only ever writes False; readers poll per step/await
                return

    def _disable_kvbm(self) -> None:
        """Offload tiers are an optimization: on failure, degrade to
        G1-only rather than taking the engine down. Multihost: the
        sharded manager first broadcasts the disable so follower shard
        pools drop in lockstep (runs on the engine thread, while
        followers are still in their receive loop)."""
        if self.kvbm is not None:
            kvbm, self.kvbm = self.kvbm, None
            if self.scheduler is not None:
                self.scheduler.onboard = None
            try:
                getattr(kvbm, "on_disable", lambda: None)()
                kvbm.close()
            except Exception:
                pass

    def _drain_incoming(self) -> None:
        assert self.scheduler is not None
        # control calls first: a KV import enqueued before a submit must be
        # visible to that request's admission (disagg relies on this order)
        while True:
            try:
                fn, fut = self._control.get_nowait()
            except thread_queue.Empty:
                break
            if fut.set_running_or_notify_cancel():
                try:
                    fut.set_result(fn())
                except Exception as exc:
                    fut.set_exception(exc)
        while True:
            try:
                item = self._incoming.get_nowait()
            except thread_queue.Empty:
                return
            self.scheduler.add_request(item)

    # ------------------------------------------------------------------
    # Engine-thread call plane (KV export/import for the transfer agent)
    # ------------------------------------------------------------------
    def call_on_thread(self, fn: Callable[[], Any]) -> "concurrent.futures.Future":
        """Run fn on the engine thread (the only thread allowed to touch
        the donated cache buffers and KVBM pools); returns a Future."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._control.put((fn, fut))
        self._wake.set()
        return fut

    async def acall_on_thread(self, fn: Callable[[], Any]) -> Any:
        return await asyncio.wrap_future(self.call_on_thread(fn))

    def _export_blocks(self, seq_hashes: list[int]) -> tuple[list[int], np.ndarray]:
        """ENGINE THREAD. Gather the longest cached prefix of seq_hashes
        as packed blocks (device tier first, then host tier).

        Multihost (num_nodes > 1): the cache's KV-head axis is sharded
        ACROSS processes, so the export runs as a mirrored replicated
        gather (announce + mirror_gather_full) — the leader ends up with
        whole blocks for the transfer plane. Only the DEVICE-resident
        prefix exports there: the sharded G2 pools hold per-process head
        slices, and assembling those would need a host-side cross-
        process collective the step broadcast channel doesn't carry
        (per-tier design notes: docs/multihost.md)."""
        from dynamo_tpu.kvbm import BlockLayout

        assert self.allocator is not None and self.model_config is not None
        layout = BlockLayout.for_model(
            self.model_config, self.config.block_size,
            self.config.wire_kv_dtype(),
        )
        multihost = self.config.num_nodes > 1
        plan: list[tuple[str, int]] = []  # (tier, device block | hash)
        for h in seq_hashes:
            bid = self.allocator.lookup_block(h)
            if bid is not None:
                plan.append(("dev", bid))
            elif (
                not multihost
                and self.kvbm is not None
                and hasattr(self.kvbm.host, "read")  # not the multihost shard pool
                and self.kvbm.host.contains(h)
            ):
                plan.append(("host", h))
            else:
                break
        if multihost:
            from dynamo_tpu.parallel.multihost import mirror_gather_full

            n = len(plan)
            if n == 0:
                return [], np.zeros((0, *layout.packed_shape), layout.np_dtype)
            ids = [bid for _, bid in plan]
            assert self._mh_broadcast is not None
            self._mh_broadcast.announce_kv_export(ids)
            packed = mirror_gather_full(
                self.k_cache, self.v_cache, np.asarray(ids, np.int32),
                self.config.block_size, self.mesh,
            )
            return seq_hashes[:n], packed
        n = len(plan)
        if n == 0:
            return [], np.zeros((0, *layout.packed_shape), layout.np_dtype)
        packed = np.zeros((n, *layout.packed_shape), layout.np_dtype)
        dev_rows = [i for i, (t, _) in enumerate(plan) if t == "dev"]
        if dev_rows:
            dev_data = self._kv_gather([plan[i][1] for i in dev_rows])
            for j, i in enumerate(dev_rows):
                packed[i] = dev_data[j]
        host_rows = [i for i, (t, _) in enumerate(plan) if t == "host"]
        if host_rows:
            assert self.kvbm is not None
            host_data = self.kvbm.host.read([plan[i][1] for i in host_rows])
            for j, i in enumerate(host_rows):
                packed[i] = host_data[j]
        return seq_hashes[:n], packed

    def _import_blocks(self, seq_hashes: list[int], packed: np.ndarray) -> int:
        """ENGINE THREAD. Land remote KV blocks in the host tier; the
        next admission onboards them into HBM (kvbm onboard()).

        Multihost: the full blocks broadcast to every process and each
        inserts ITS head slice into its shard pool (lockstep kept);
        onboarding then lifts them through the existing mirrored
        scatter."""
        if self.kvbm is None:
            raise RuntimeError("KV import requires host_kv_blocks > 0")
        if len(seq_hashes) > self.kvbm.host.num_blocks:
            # inserting would LRU-evict the delivery's own leading blocks,
            # silently voiding the remote prefill — reject instead
            raise RuntimeError(
                f"KV import of {len(seq_hashes)} blocks exceeds host tier "
                f"capacity {self.kvbm.host.num_blocks}"
            )
        if not hasattr(self.kvbm.host, "read"):
            # ShardedKvOffload: mirrored insert — every process slices
            # its own head range so the pools stay in lockstep
            from dynamo_tpu.parallel.multihost import local_head_rows

            assert self._mh_broadcast is not None
            self._mh_broadcast.announce_kv_import(seq_hashes, packed)
            self.kvbm.host.insert_many(
                seq_hashes, local_head_rows(packed, self.k_cache)
            )
            return len(seq_hashes)
        self.kvbm.host.insert_many(seq_hashes, packed)
        return len(seq_hashes)

    async def export_kv_blocks(
        self, seq_hashes: list[int]
    ) -> tuple[list[int], np.ndarray]:
        return await self.acall_on_thread(
            functools.partial(self._export_blocks, seq_hashes)
        )

    async def import_kv_blocks(self, seq_hashes: list[int], packed: np.ndarray) -> int:
        return await self.acall_on_thread(
            functools.partial(self._import_blocks, seq_hashes, packed)
        )

    def match_cached_prefix(self, seq_hashes: list[int]) -> int:
        """Blocks resolvable without prefill (G1 + offload tiers). Safe to
        call from any thread (read-only dict lookups; advisory only)."""
        n = 0
        for h in seq_hashes:
            if self.allocator is not None and self.allocator.lookup_block(h) is not None:
                n += 1
            elif self.kvbm is not None and (
                self.kvbm.host.contains(h)
                or (self.kvbm.disk is not None and self.kvbm.disk.contains(h))
            ):
                n += 1
            else:
                break
        return n

    _trace_enabled = bool(os.environ.get("DYN_STEP_TRACE"))

    def _trace(self, event: str, **fields) -> None:
        """Step tracing (DYN_STEP_TRACE=1): one log line per engine
        step with kind, wall time, and batch geometry — the profiling
        surface for serving-stall forensics (reference analogue: the
        runtime's tracing spans, SURVEY.md §5)."""
        if self._trace_enabled:
            log.info(
                "step %s %s", event,
                " ".join(f"{k}={v}" for k, v in fields.items()),
            )

    # -- step flight recording (telemetry/recorder.py) ---------------------
    _step_counter = 0
    _last_preemptions = 0

    def _update_pool_gauges(self) -> None:
        """KV-pool occupancy gauges from the allocator (refreshed per
        step AND per debug snapshot so /metrics and /debug/state agree
        on the same moment)."""
        alloc = self.allocator
        if alloc is None:
            return
        KV_POOL_BLOCKS_TOTAL.set(alloc.num_blocks - 1)
        KV_POOL_BLOCKS_ACTIVE.set(alloc.num_blocks - 1 - alloc.num_free)
        KV_POOL_CACHED_FREE_BLOCKS.set(alloc.num_cached_free)

    def _record_step(
        self, kind: str, duration_s: float,
        batch: int = 0, prefill_rows: int = 0, use_phases: bool = True,
        tokens: int = 0, overlapped: bool = False,
        **extra,
    ) -> None:
        """One flight-recorder entry per device step: kind, batch
        composition, queue depth, per-phase latency (dispatch/sync from
        ``_last_phases``), preemption delta. Engine-thread only.

        ``use_phases=False`` for records whose dispatch did NOT go
        through ``_run_device_step`` (fused windows, spec) — merging
        ``_last_phases`` there would attribute a stale, unrelated
        dispatch's timings to this step.

        ``tokens``/``overlapped`` feed the attribution ledger
        (telemetry/attribution.py): tokens emitted by this step and
        whether its dispatch overlapped other host work (the decode/
        window pipelines) — the ledger's partition rules differ
        (docstring there). A slow-step/idle-gap watchdog dump or a
        ledger roofline-band anomaly triggers the black-box bundle."""
        sched = self.scheduler
        self._step_counter += 1
        self._update_pool_gauges()
        if self._step_counter % 32 == 0:
            try:
                self.hbm.refresh()
            except Exception:  # stats are advisory; never fail a step
                log.debug("hbm refresh failed", exc_info=True)
        phases, self._last_phases = self._last_phases, {}
        if sched is None:
            return
        pre = sched.preemptions
        fields = dict(
            batch=batch,
            prefill_rows=prefill_rows,
            running=sched.num_running,
            prefilling=len(sched.prefilling),
            queue_depth=sched.num_waiting,
            kv_free=self.allocator.num_free if self.allocator else 0,
            preemptions=pre - self._last_preemptions,
        )
        self._last_preemptions = pre
        if use_phases:
            fields.update(phases)
        fields.update(extra)
        # attribution ledger: live context from the scheduler (advisory
        # — one step stale under the pipelines); the spec step's
        # draft/verify stamps map onto plan/sync (host drafting ahead
        # of the harvest-blocking verify)
        try:
            anomaly = self.attribution.note_step(
                kind, duration_s,
                batch=batch or fields["running"],
                tokens=tokens,
                context_tokens=sum(
                    s.num_computed for s in sched.running
                ),
                plan_ms=fields.get("plan_ms") or fields.get("draft_ms") or 0.0,
                dispatch_ms=fields.get("dispatch_ms") or 0.0,
                sync_ms=fields.get("sync_ms") or fields.get("verify_ms") or 0.0,
                idle_gap_ms=fields.get("idle_gap_ms") or 0.0,
                overlapped=overlapped,
            )
        except Exception:  # advisory: never fail a step on accounting
            log.debug("attribution note failed", exc_info=True)
            anomaly = None
        dump = None
        if self.recorder is not None:
            dump = self.recorder.record(kind, duration_s, **fields)
        if dump is not None:
            # watchdog tripped (slow step or idle gap): preserve the
            # full forensic context, not just the ring
            self.blackbox.trigger(f"watchdog:{kind}")
        elif anomaly is not None:
            self.blackbox.trigger(anomaly)
        self._check_compile_fence(kind)
        self._check_transfer_fence(kind)

    def _check_compile_fence(self, kind: str) -> None:
        """Escalate serve-phase compiles the fence collected since the
        last step (DYN_COMPILE_FENCE, utils/compile_fence.py): ONE
        flight-recorder ``serve_compile`` record per drain — the events
        of a single unprewarmed signature coalesce instead of spamming
        the ring — plus a black-box bundle (its own rate limit applies)
        and a hard error under fatal mode."""
        if not compile_fence.enabled():
            return
        events, n_events = compile_fence.drain()
        if not n_events:
            return
        # n_events is the TRUE count; `events` holds at most the
        # fence's bounded detail window — a retrace storm past the
        # bound still counts in full
        COMPILE_FENCE_EVENTS.inc(n_events)
        total_s = sum(e["duration_ms"] for e in events) / 1e3
        summary = dict(
            compiles=n_events,
            event=events[0]["event"] if events else "<overflowed>",
            step_kind=kind,
        )
        if self.recorder is not None:
            # record() is watchdog-bearing; a mid-serve compile IS the
            # anomaly, so let a long one trip the slow-step dump too
            self.recorder.record("serve_compile", total_s, **summary)
        self.blackbox.trigger("serve_compile")
        log.warning(
            "compile fence: %d serve-phase compile event(s) during a "
            "%s step (first: %s, %.0f ms total) — an unprewarmed jit "
            "signature compiled mid-serve",
            n_events, kind, summary["event"], total_s * 1e3,
        )
        if compile_fence.fatal():
            raise compile_fence.CompileFenceError(
                f"serve-phase compile under DYN_COMPILE_FENCE=fatal: "
                f"{n_events} event(s), first {summary['event']!r} "
                f"during a {kind} step"
            )

    def _check_transfer_fence(self, kind: str) -> None:
        """Escalate serve-phase implicit transfers the fence collected
        (DYN_TRANSFER_FENCE, utils/transfer_fence.py), mirroring the
        compile fence: ONE flight-recorder ``serve_transfer`` record
        per drain, one black-box bundle (its own rate limit applies),
        one counter bump, and a hard error under fatal mode.  Runs from
        ``_record_step`` each step and directly from the step-loop
        handler when the guard's RuntimeError aborts a dispatch (the
        aborted step may never reach ``_record_step``)."""
        if not transfer_fence.enabled():
            return
        events, n_events = transfer_fence.drain()
        if not n_events:
            return
        TRANSFER_FENCE_EVENTS.inc(n_events)
        summary = dict(
            transfers=n_events,
            error=events[0]["error"] if events else "<overflowed>",
            step_kind=kind,
        )
        if self.recorder is not None:
            self.recorder.record("serve_transfer", 0.0, **summary)
        self.blackbox.trigger("serve_transfer")
        log.warning(
            "transfer fence: %d serve-phase implicit transfer(s) "
            "during a %s step (first: %s) — a host<->device sync "
            "outside the dispatch/harvest contract",
            n_events, kind, summary["error"],
        )
        if transfer_fence.fatal():
            raise transfer_fence.TransferFenceError(
                f"serve-phase implicit transfer under "
                f"DYN_TRANSFER_FENCE=fatal: {n_events} event(s), "
                f"first {summary['error']!r} during a {kind} step"
            )

    def _one_step(self) -> None:
        sched = self.scheduler
        assert sched is not None
        # injected device-step faults (docs/robustness.md): a delay here
        # models a straggling dispatch, an error exercises the
        # quarantine path, a kill is a worker death. No-op without a plan.
        faults.fire("engine.step")
        t_plan = time.monotonic()
        # clear BEFORE plan(): a failure inside planning must not be
        # attributed to the previous step's (healthy) requests
        self._last_plan = None
        plan = sched.plan()
        self._last_plan = plan  # step-failure attribution (quarantine)
        plan_ms = round((time.monotonic() - t_plan) * 1e3, 3)
        # phase stamps from an earlier, never-recorded dispatch (e.g. a
        # dedicated prefill inside the window pipeline) must not leak
        # into this step's record
        self._last_phases = {}
        # per-step load gauges: two locked float stores per step, noise
        # next to a device dispatch
        ENGINE_BATCH_OCCUPANCY.set(
            sched.num_running / max(1, self.config.max_batch_size)
        )
        ENGINE_QUEUE_DEPTH.set(sched.num_waiting)
        if plan.kind == "idle":
            # blocking sleep is deliberate: _one_step executes on the
            # dedicated "jax-engine" thread, never on the event loop
            time.sleep(0.001)
            return
        if self._trace_enabled:
            self._trace(
                "plan", kind=plan.kind,
                prefill=len(plan.prefill_batch),
                decode=len(plan.decode_seqs),
                waiting=len(sched.waiting),
                plan_ms=round((time.monotonic() - t_plan) * 1e3, 1),
            )
        if plan.kind == "mixed":
            if self._mixed_step_fn is not None:
                t0 = time.monotonic()
                self._window_pipeline(
                    plan.prefill_batch, plan.decode_seqs, rect=plan.rect
                )
                ENGINE_STEP_SECONDS.labels("mixed").observe(
                    time.monotonic() - t0
                )
                self._trace(
                    "mixed", ms=round((time.monotonic() - t0) * 1e3, 1)
                )
                return
            plan.kind = "prefill"  # no fused window: prefill this step
        spec_fell_through = False
        if (
            plan.kind == "decode"
            and self._drafter is not None
            and not self.spec_suspended
            and plan.decode_seqs
            and not self._spec_divert(plan.decode_seqs)
        ):
            t0 = time.monotonic()
            if self._overlap_ok() and not self._overlap_divert(
                plan.decode_seqs
            ):
                # overlapped speculative decode (the tentpole of
                # docs/speculative_decoding.md's pipelined section):
                # host drafting for step N+1 runs WHILE the device
                # verifies step N
                ran = self._spec_pipeline(plan.decode_seqs, plan_ms=plan_ms)
            else:
                ran = self._run_spec_step(plan.decode_seqs)
            if ran:
                # per-STEP latency histograms are observed inside the
                # step bodies (_run_spec_step / _finish_spec_record) —
                # one pipeline call drains many steps, so observing the
                # whole drain here would poison the spec p99
                self._trace(
                    "spec", b=len(plan.decode_seqs),
                    ms=round((time.monotonic() - t0) * 1e3, 1),
                )
                return
            # no drafter had a proposal for any row: fall through to the
            # plain 1-token decode step — the [B, K+1] verify rectangle
            # would spend (K+1)x the attention/lm_head work to emit
            # exactly the same single token per sequence. Take ONE
            # serial step (not the plain pipeline, which would keep
            # speculation off for its whole drain) and retry drafting
            # at the next plan.
            spec_fell_through = True
        if (
            plan.kind == "decode"
            and self._multi_step_fn is None
            and not spec_fell_through
            and self._overlap_ok()
            and plan.decode_seqs
            and not self._overlap_divert(plan.decode_seqs)
        ):
            # spec-suspended (degradation rung 2) and opted-out batches
            # reach here too: the overlapped plain pipeline IS the
            # literal plain-decode path (bit-identical to serial), so
            # the opt-out contract holds
            # overlapped single-step decode (docs/performance.md):
            # dispatch N+1 before harvesting N so the TPU never idles
            # for the host's plan+unpack time. --no-overlap restores
            # the serial loop below.
            t0 = time.monotonic()
            self._decode_pipeline(plan.decode_seqs, plan_ms=plan_ms)
            self._trace(
                "decode_pipeline", b=len(plan.decode_seqs),
                ms=round((time.monotonic() - t0) * 1e3, 1),
            )
            return
        if (
            plan.kind == "prefill"
            and self._multi_step_fn is not None
            and self._overlap_ok()
            and plan.prefill_batch
            and all(w.is_last_chunk for w in plan.prefill_batch)
        ):
            # cohort graduation without the hard sync: the prefill
            # dispatch's first tokens chain on device into the first
            # decode window (_window_pipeline prefill-only entry) —
            # multimodal/penalty/top-logprobs batches fall back to the
            # dedicated serial prefill inside the pipeline
            t0 = time.monotonic()
            self._window_pipeline(plan.prefill_batch, [])
            ENGINE_STEP_SECONDS.labels("prefill").observe(
                time.monotonic() - t0
            )
            self._trace(
                "prefill_graduating", rows=len(plan.prefill_batch),
                ms=round((time.monotonic() - t0) * 1e3, 1),
            )
            return
        if plan.kind == "prefill":
            works = plan.prefill_batch
            assert works
            arrays = sched.build_prefill_batch_arrays(works)
            seqs = [w.seq for w in works]
        else:
            seqs = plan.decode_seqs
            if not seqs:
                return
            arrays = sched.build_decode_arrays(seqs)

        B = arrays["tokens"].shape[0]
        sampling = self._batch_sampling(seqs, B)
        gmask = self._guided_allow_mask(seqs, B)
        if gmask is not None:
            # guided rows constrain the sampled token (prefill's first
            # token and every serial decode step); selects the masked
            # jit variant (prewarmed under config.prewarm_guided)
            sampling.arrays["allow_mask"] = gmask

        if plan.kind == "decode" and self._multi_step_fn is not None:
            t0 = time.monotonic()
            self._window_pipeline([], seqs)
            ENGINE_STEP_SECONDS.labels("decode").observe(
                time.monotonic() - t0
            )
            self._trace(
                "window_seq",
                ms=round((time.monotonic() - t0) * 1e3, 1),
                b=len(seqs),
            )
            return

        t_step = time.monotonic()
        need_sync = plan.kind != "prefill" or any(
            w.is_last_chunk for w in plan.prefill_batch
        )
        s_out = self._run_device_step(
            arrays, sampling, sync=need_sync,
            origin="prefill:" + ",".join(
                w.seq.request_id for w in plan.prefill_batch
            ) if plan.kind == "prefill" else "",
        )
        if s_out is not None:
            next_tokens, logprobs = s_out[0], s_out[1]
            tops = s_out[2:] if len(s_out) > 2 else None
        else:
            next_tokens = logprobs = tops = None
        dt = time.monotonic() - t_step
        ENGINE_STEP_SECONDS.labels(plan.kind).observe(dt)
        self._record_step(
            plan.kind, dt,
            batch=len(seqs),
            prefill_rows=len(plan.prefill_batch),
            tokens=(
                sum(1 for w in plan.prefill_batch if w.is_last_chunk)
                if plan.kind == "prefill" else len(seqs)
            ),
            plan_ms=plan_ms,
            synced=need_sync,
        )
        self._trace(
            "dispatch_" + plan.kind,
            shape=arrays["tokens"].shape,
            ms=round(dt * 1e3, 1),
            sync=need_sync,
        )

        def top_row(i):
            return (tops[0][i], tops[1][i]) if tops is not None else None

        if plan.kind == "prefill":
            for i, work in enumerate(plan.prefill_batch):
                sched.complete_prefill_chunk(work)
                if work.is_last_chunk:
                    self._emit_token(
                        work.seq, int(next_tokens[i]), float(logprobs[i]),
                        top=top_row(i),
                    )
        else:
            for i, seq in enumerate(seqs):
                if seq.state != SeqState.RUNNING:
                    continue
                self._emit_token(
                    seq, int(next_tokens[i]), float(logprobs[i]),
                    top=top_row(i),
                )

    # ------------------------------------------------------------------
    # Speculative decoding (dynamo_tpu/spec; docs/speculative_decoding.md)
    # ------------------------------------------------------------------
    def _seq_spec_enabled(self, seq: Sequence) -> bool:
        """Per-request opt-out: PreprocessedRequest.speculative=False
        turns speculation off for one request; None/True follow the
        engine default (a configured drafter)."""
        return (
            self._drafter is not None
            and getattr(seq.request, "speculative", None) is not False
        )

    def _spec_divert(self, seqs: list) -> bool:
        """Batches that must take the plain decode step instead of the
        verify step: penalty/bias/top-logprobs sampling rides
        separately-compiled step variants the verify path deliberately
        doesn't replicate, and ANY opted-out request diverts its whole
        batch — the opt-out contract is the LITERAL plain-decode path,
        and the verify step computes logits through the T>1 prefill
        attention kernel (different reduction/tiling order than the
        T==1 decode kernel: near-tie argmax can flip on TPU) and draws
        sampled tokens from a different seeded RNG stream than
        sample(). Riding along would approximate, not honor, the
        request."""
        return (
            self._wants_toplp(seqs)
            or any(s.request.sampling.needs_penalties for s in seqs)
            or any(s.request.sampling.logit_bias for s in seqs)
            or any(not self._seq_spec_enabled(s) for s in seqs)
        )

    def _run_spec_step(self, seqs: list, proposals=None) -> bool:
        """One speculative decode step: draft on host, verify on device,
        roll back rejected drafts. Returns False — with NOTHING staged
        and no dispatch made — when no sequence got a proposal, so the
        caller can run the plain decode step instead. ``proposals``
        (aligned with ``seqs``) skips the draft loop — the spec
        pipeline's block-pressure fallback already drafted this batch,
        and re-drafting would double both the host cost and the
        exposed-draft accounting.

        Contract with the rest of the engine (this is the part that
        changes the 1-token/seq/step assumption): each sequence emits
        1..spec_tokens+1 tokens through _emit_window — the SAME
        multi-token append path fused windows use, so stop conditions,
        max_tokens clamping, logprobs emission, prefix-cache block
        commits and SSE multi-token deltas all behave as they do for
        windows. Draft tokens are staged into seq.tokens for array
        building (scheduler.reserve_spec_tokens) and ALWAYS unwound
        after the device sync (TokenBlockSequence.unwind) before the
        verified tokens are appended — so host token state, generated
        counts and block content-addressing only ever see verified
        tokens, and blocks speculatively grown for draft KV stay
        uncommitted until real tokens fill them."""
        sched = self.scheduler
        assert sched is not None and self._spec_step_fn is not None
        assert self._drafter is not None
        S = self.config.spec_tokens + 1
        t_step = time.monotonic()
        draft_s = 0.0
        if proposals is None:
            t_draft = time.monotonic()
            proposals = []
            for seq in seqs:
                # budget leaves room for the verify step's guaranteed
                # +1 token: drafts past it would be discarded by
                # _emit_window anyway, but their KV writes would still
                # need blocks the growth reserve never budgeted
                budget = self._spec_budget(seq)
                props = (
                    self._draft_tokens(seq, budget)
                    if self._seq_spec_enabled(seq)
                    else []
                )
                if props and seq.guided_state is not None:
                    # guided spec: proposals filter through the SAME
                    # automaton the verify masks apply — a draft the
                    # mask would reject can never be proposed, so the
                    # accepted prefix is exactly what serial guided
                    # decode would have committed
                    props = seq.guided_state.filter_drafts(props)
                proposals.append(props)
            # the draft-phase histogram covers PROPOSAL cost only (the
            # drafter-tuning signal) — staging/array/sampling prep
            # below is fixed per-step engine work, not drafter work
            draft_s = time.monotonic() - t_draft
            SPEC_STEP_SECONDS.labels("draft").observe(draft_s)
            self.spec_draft_exposed_s_total += draft_s
        if not any(proposals):
            return False  # nothing staged: caller runs plain decode
        works: list[tuple] = []
        staged = 0
        for seq, drafts in zip(seqs, proposals):
            # carry read BEFORE staging: reserve_spec_tokens appends the
            # drafts to token state, after which last_token() is a draft
            carry = seq.tokens.last_token()
            k = sched.reserve_spec_tokens(seq, drafts) if drafts else 0
            staged += k
            works.append((seq, [carry] + drafts[:k]))
        if staged == 0:
            # block pressure shrank every row's kept drafts to zero:
            # rows are bare [carry] tokens, nothing was appended to any
            # sequence — bail to plain decode instead of paying the
            # (K+1)x rectangle to emit 1 token per sequence
            return False
        arrays = sched.build_spec_arrays(works, S)
        B = arrays["tokens"].shape[0]
        sampling = self._batch_sampling(seqs, B)
        gmask = self._guided_spec_masks(works, S, B)
        if gmask is not None:
            # [B, S, V] per-position masks: verify applies the identical
            # transform the serial masked path would at each position
            sampling.arrays["allow_mask"] = gmask
        t0 = time.monotonic()
        try:
            packed = self._dispatch_spec_step(arrays, sampling)
            # _harvest_spec_step is the spec path's designated harvest
            # point (DL010): the device->host sync happens inside it
            toks, lps, n_emit, _ = self._harvest_spec_step(packed, S)
        except Exception:
            # host token state must not keep staged (unverified) drafts
            # when the step dies — the quarantine retry would otherwise
            # replan with drafts baked into every sequence's history
            for seq, row in works:
                if len(row) > 1:
                    seq.tokens.unwind(len(row) - 1)
            raise
        verify_s = time.monotonic() - t0
        SPEC_STEP_SECONDS.labels("verify").observe(verify_s)
        proposed = sum(len(row) - 1 for _, row in works)
        accepted = int(sum(n_emit[i] - 1 for i in range(len(works))))
        self._record_step(
            "spec", draft_s + verify_s,
            batch=len(works),
            tokens=len(works) + accepted,  # accepted prefix + 1 per row
            use_phases=False,  # draft/verify ms below ARE the phases
            draft_ms=round(draft_s * 1e3, 3),
            verify_ms=round(verify_s * 1e3, 3),
            spec_proposed=proposed,
            spec_accepted=accepted,
        )
        if proposed:
            SPEC_PROPOSED_TOKENS.labels(self._drafter.kind).inc(proposed)
            if accepted:
                SPEC_ACCEPTED_TOKENS.labels(self._drafter.kind).inc(accepted)
            SPEC_ACCEPT_RATE.set(accepted / proposed)
            self.spec_proposed_total += proposed
            self.spec_accepted_total += accepted
        for i, (seq, row) in enumerate(works):
            if len(row) > 1:
                seq.tokens.unwind(len(row) - 1)  # rejected AND accepted
                # drafts: the accepted prefix re-appends through
                # append_token below so commits/penalty counts take the
                # normal path
            if seq.state != SeqState.RUNNING:
                continue
            n = int(n_emit[i])
            self._emit_window(seq, toks[i, :n], lps[i, :n])
        ENGINE_STEP_SECONDS.labels("spec").observe(time.monotonic() - t_step)
        return True

    def _spec_budget(self, seq: Sequence, lag: int = 0) -> int:
        """Draft budget for one sequence: spec_tokens, clamped to leave
        room for the verify step's guaranteed +1 token. ``lag`` shifts
        the clamp past tokens a harvested-but-not-yet-emitted step will
        add (the pipelined planner's view of ``generated``)."""
        budget = self.config.spec_tokens
        if seq.max_new_tokens is not None:
            budget = min(
                budget, max(0, seq.max_new_tokens - seq.generated - lag - 1)
            )
        return budget

    def _draft_tokens(self, seq: Sequence, budget: int, suffix=()) -> list:
        """Proposals for one sequence — through the per-sequence
        incremental n-gram index when the drafter provides one
        (``seq.drafter_state``; ``NgramDrafter.make_index``), the plain
        windowed ``propose`` otherwise. The index appends committed
        tokens as they arrive and rebuilds only when the sequence
        SHRANK (unwind/truncation) — the from-scratch tail scan was
        O(window) host work per row per step. ``suffix`` = tokens that
        will exist once in-flight emits apply (the pipeline's pre-draft
        and repair contexts) — proposals are computed as if they were
        appended, but token state and the index never see them."""
        d = self._drafter
        assert d is not None
        if budget <= 0:
            return []
        # cap the history the drafter sees (Drafter.window, None = all):
        # a full all_tokens() per sequence per step is O(context) host
        # work on the serialized engine thread
        window = getattr(d, "window", None)
        make = getattr(d, "make_index", None)
        if make is None or not window:
            hist = (
                seq.tokens.tail_tokens(window)
                if window
                else seq.tokens.all_tokens()
            )
            if suffix:
                hist = hist + [int(t) for t in suffix]
                if window:
                    hist = hist[-window:]
            return list(d.propose(hist, budget))[:budget]
        T = len(seq.tokens)
        idx = seq.drafter_state
        if idx is None or idx.seq_len > T:
            # first draft, or the sequence shrank: rebuild from the tail
            idx = make(seq.tokens.tail_tokens(window), T)
            seq.drafter_state = idx
        elif idx.seq_len < T:
            # append what was committed since the last draft (emitted
            # tokens only: the paths that call this never leave staged
            # drafts in token state at draft time)
            idx.extend(seq.tokens.tail_tokens(T - idx.seq_len))
        return list(idx.propose(budget, suffix))[:budget]

    def _dispatch_spec_step(
        self, arrays: dict[str, np.ndarray], sampling: SamplingBatch,
        tokens_dev=None,
    ):
        """DISPATCH half of the speculative verify step (the spec twin
        of ``_dispatch_device_step``): launch the jitted verify, swap
        the donated caches, and return the packed [B, 2S+1] DEVICE
        output — no host sync. ``tokens_dev`` feeds the chain_spec'd
        device token column (the pipelined signature); None feeds the
        host rectangle. Callers harvest via ``_harvest_spec_step``;
        between the two, the host is free to emit the previous step and
        pre-draft the next one while the device verifies this one."""
        assert self._spec_step_fn is not None
        arrays, sampling = self._stage_step_inputs(arrays, sampling)
        idle_gap_s = self.overlap.note_dispatch()
        t0 = time.monotonic()
        packed, self.k_cache, self.v_cache = self._spec_step_fn(
            self.params, self.k_cache, self.v_cache,
            arrays["tokens"] if tokens_dev is None else tokens_dev,
            arrays["positions"], arrays["slot_mapping"],
            arrays["block_tables"], arrays["context_lens"],
            arrays["draft_lens"], sampling.arrays,
        )
        self._last_phases = {
            "dispatch_ms": round((time.monotonic() - t0) * 1e3, 3),
            "idle_gap_ms": round(idle_gap_s * 1e3, 3),
        }
        self._unsynced_steps.append("spec-verify")
        del self._unsynced_steps[:-8]  # bounded forensics window
        return packed

    def _harvest_spec_step(self, packed, S: int) -> tuple:
        """HARVEST half: the spec path's designated host-sync point
        (``harvest_spec_output`` does the one device->host read).
        Returns (toks, lps, n_emit, sync_s)."""
        from dynamo_tpu.spec.verify import harvest_spec_output

        t0 = time.monotonic()
        toks, lps, n_emit = harvest_spec_output(packed, S)
        self.overlap.note_complete(all_prior=True)
        # successful host sync: earlier async dispatches are known-good
        # (in-order execution) — retire deferred-error forensics
        self._unsynced_steps.clear()
        return toks, lps, n_emit, time.monotonic() - t0

    @staticmethod
    def _seq_dead(seq: Sequence) -> bool:
        """Late-detected stop: cancellation or deadline expiry observed
        after a step that includes the row went in flight."""
        if seq.is_cancelled and seq.is_cancelled():
            return True
        return bool(seq.deadline) and time.monotonic() >= seq.deadline

    def _spec_predraft(self, works: list) -> list:
        """Optimistic pre-draft for the NEXT verify step, computed
        while the CURRENT one runs on device (the hidden half of the
        spec pipeline's draft cost): for each row, predict the bonus
        token with the drafter itself (suffix = this step's drafts),
        then propose the next draft run from the predicted full-accept
        tail — exactly the context the row realizes IF every draft is
        accepted and the bonus matches the prediction. Returns per-row
        ``(predicted_bonus, proposals)`` or None when the drafter has
        no prediction — those rows re-draft at harvest. Host-only:
        reads token state and the per-sequence index, mutates
        neither."""
        out = []
        for seq, drafts in works:
            pre = None
            if self._seq_spec_enabled(seq):
                guess = self._draft_tokens(seq, 1, suffix=drafts)
                if guess:
                    k = len(drafts)
                    # budget as serial would compute it at the realized
                    # state (generated advances by k+1 on full accept)
                    budget = self._spec_budget(seq, k + 1)
                    pre = (
                        guess[0],
                        self._draft_tokens(
                            seq, budget, suffix=list(drafts) + guess
                        ),
                    )
            out.append(pre)
        return out

    def _dispatch_spec_entry(
        self, nxt: dict, plan_ms: float, draft_ms: float, tokens_dev,
    ) -> dict:
        """Build sampling and dispatch one pipelined verify step from a
        ``plan_pipelined_spec`` result; returns the pipeline entry."""
        works = nxt["works"]
        B = nxt["arrays"]["context_lens"].shape[0]
        sampling = self._batch_sampling(
            [s for s, _ in works], B, offset=nxt["offsets"]
        )
        packed = self._dispatch_spec_step(
            nxt["arrays"], sampling, tokens_dev=tokens_dev
        )
        return {
            "packed": packed,
            "works": works,
            "t_disp": time.monotonic(),
            "plan_ms": plan_ms,
            "draft_ms": draft_ms,
            # consumed by _finish_spec_record, not use_phases: at
            # record time _last_phases belongs to a LATER dispatch
            "phases": dict(self._last_phases),
        }

    def _emit_spec_entry(self, entry: dict, toks, lps, n_emit) -> bool:
        """Apply one harvested verify step to host state — the deferred
        emit, running while the NEXT step executes on device. Returns
        True when a late-detected stop DISCARDED a row's tokens (never
        appended, never content-addressed): the pipeline must then
        flush so the serial plan()'s reap frees the blocks with nothing
        in flight. Predicted finishes (max_tokens/model-len/block-cap)
        emit normally and do NOT flush — the next step excludes those
        rows, and nothing allocates until after its harvest, so their
        freed blocks cannot race its writes."""
        late = False
        proposed = accepted = emitted = 0
        for i, (seq, drafts) in enumerate(entry["works"]):
            if seq.state != SeqState.RUNNING:
                continue
            if self._seq_dead(seq):
                late = True
                continue
            # proposed counted ONLY for rows that emit: a discarded
            # row's drafts counting as proposals-without-acceptances
            # would bias accept_rate low vs the serial step's books
            n = int(n_emit[i])
            proposed += len(drafts)
            accepted += n - 1
            emitted += n
            self._emit_window(seq, toks[i, :n], lps[i, :n])
        entry["proposed"] = proposed
        entry["accepted"] = accepted
        entry["tokens"] = emitted
        if proposed:
            SPEC_PROPOSED_TOKENS.labels(self._drafter.kind).inc(proposed)
            if accepted:
                SPEC_ACCEPTED_TOKENS.labels(self._drafter.kind).inc(accepted)
            SPEC_ACCEPT_RATE.set(accepted / proposed)
            self.spec_proposed_total += proposed
            self.spec_accepted_total += accepted
        return late

    def _finish_spec_record(self, entry: dict, sync_s: float) -> None:
        """Flight-recorder + attribution row for one pipelined spec
        step (kind "spec", overlapped): exposed draft/plan time rides
        ``plan_ms`` (the ledger's overlapped branch bills the measured
        idle gap to plan first), the harvest block is ``sync_ms``, and
        the hidden pre-draft simply isn't loss — the device was busy
        under it, so it lands in the device-phase buckets and the
        fractions still sum to 1.0 by construction."""
        self.spec_pipeline_steps += 1
        tot = self.spec_draft_hidden_s_total + self.spec_draft_exposed_s_total
        if tot > 0:
            SPEC_DRAFT_HIDDEN_FRAC.set(
                self.spec_draft_hidden_s_total / tot
            )
        dt = time.monotonic() - entry["t_disp"]
        ENGINE_STEP_SECONDS.labels("spec").observe(dt)
        # the harvest block is the pipelined analogue of the serial
        # verify wall (device execution remainder when healthy)
        SPEC_STEP_SECONDS.labels("verify").observe(sync_s)
        self._record_step(
            "spec", dt,
            batch=len(entry["works"]),
            tokens=entry.get("tokens", 0),
            overlapped=True,
            use_phases=False,  # per-entry stamps below
            plan_ms=entry["plan_ms"],
            draft_ms=entry["draft_ms"],
            sync_ms=round(sync_s * 1e3, 3),
            spec_proposed=entry.get("proposed", 0),
            spec_accepted=entry.get("accepted", 0),
            **entry["phases"],
        )

    def _spec_pipeline(self, seqs: list, plan_ms: float = 0.0) -> bool:
        """Overlapped speculative decode — spec (PR 3) composed with
        the decode pipeline's double-buffering (PR 7), ROADMAP item 2's
        biggest unplayed lever. The serial spec loop pays host drafting
        as device idle every step (draft -> dispatch -> harvest ->
        emit, fully serialized); here the host drafts and plans step
        N+1 WHILE the device runs step N's verify:

        - at dispatch of step N the host PRE-DRAFTS step N+1 from the
          *optimistic* all-accepted tail (history + N's drafts + the
          drafter's own prediction of the bonus token — exactly the
          post-N history IF every draft is accepted and the bonus
          matches). At high accept rates most rows realize that tail,
          and their next proposals are already in hand when N's result
          lands;
        - the harvest (the designated sync) reveals each row's realized
          tail; rows that diverged are RE-DRAFTED from the actual tail
          at harvest, so the proposal stream is byte-identical to the
          serial loop's and output stays bit-identical to serial spec —
          greedy AND seeded-sampled (the sampled realization depends on
          the proposals, so a cheaper drop-the-drafts repair would
          break it);
        - ``plan_pipelined_spec`` mirrors every ``should_finish``
          condition using the EXACT emitted counts, reserves blocks for
          the in-flight tokens (up to K+1 per row) plus the next draft
          run with rollback on ``NoBlocksError``, and never
          preempts/admits — any irregularity (new arrivals, opt-outs,
          cancellation, deadline, block pressure, zero proposals)
          flushes back to the serial planner, the same divert
          discipline as ``_overlap_divert``;
        - step N's emit/bookkeeping (append_token, stop checks, block
          commits, SSE deltas) runs AFTER N+1 is dispatched, so the
          device-exposed host span between consecutive verifies is
          repair + plan only — the draft cost is hidden
          (``dynamo_spec_draft_hidden_frac`` reports how much);
        - the carry token chains ON DEVICE (``chain_spec``): column 0
          of N+1's rectangle gathers each row's last emitted token from
          N's packed output, so consecutive verifies exchange no token
          values through the host.

        Late-detected stops DISCARD the in-flight tokens for that row
        at emit and flush the pipeline so plan()'s reap runs with
        nothing in flight. Unlike the serial step, drafts are never
        staged into ``seq.tokens`` (array geometry comes from the
        planner's explicit lags), so a step failure leaves nothing to
        unwind and the quarantine retry replans from clean host state.

        Returns False — with NOTHING dispatched — when no row has a
        proposal, so the caller runs the plain step and retries
        drafting at the next plan."""
        sched = self.scheduler
        assert sched is not None and self._chain_spec_fn is not None
        S = self.config.spec_tokens + 1
        # first step: serial-style (exposed) draft over clean state
        t0 = time.monotonic()
        entries = []
        for seq in seqs:
            drafts = (
                self._draft_tokens(seq, self._spec_budget(seq))
                if self._seq_spec_enabled(seq)
                else []
            )
            entries.append((seq, 0, drafts))
        draft_s = time.monotonic() - t0
        SPEC_STEP_SECONDS.labels("draft").observe(draft_s)
        if not any(d for _, _, d in entries):
            return False  # nothing to verify: caller runs plain decode
        self.spec_draft_exposed_s_total += draft_s
        t_plan = time.monotonic()
        nxt = sched.plan_pipelined_spec(entries, S)
        if nxt is None:
            # block pressure or another irregularity at entry: the
            # serial spec step handles it (reserve_spec_tokens shrinks
            # draft runs instead of flushing) — identical to what a
            # serial-spec engine does at this state. Hand over the
            # proposals already drafted above rather than paying the
            # host scan twice.
            return self._run_spec_step(
                seqs, proposals=[d for _, _, d in entries]
            )
        if not any(d for _, d in nxt["works"]):
            return False  # clamping dropped every draft: plain step
        # first step chains from nothing: host carry column (the
        # prewarmed serial signature)
        arrays = nxt["arrays"]
        for i, (seq, _) in enumerate(nxt["works"]):
            arrays["tokens"][i, 0] = seq.tokens.last_token()
        entry = self._dispatch_spec_entry(
            nxt,
            plan_ms=plan_ms + round((time.monotonic() - t_plan) * 1e3, 3),
            draft_ms=round(draft_s * 1e3, 3),
            tokens_dev=None,
        )
        while True:
            # one logical engine step per turn: the fault point must
            # see it (docs/robustness.md) — fired BEFORE the pre-draft,
            # so an injected error propagates with host state only
            # advanced through the last emit and the quarantine retry
            # recomputes the abandoned in-flight verify bit-identically
            faults.fire("engine.step")
            # ---- device busy: hide the next step's drafting ----
            t0 = time.monotonic()
            pres = self._spec_predraft(entry["works"])
            predraft_s = time.monotonic() - t0
            SPEC_STEP_SECONDS.labels("predraft").observe(predraft_s)
            self.spec_draft_hidden_s_total += predraft_s
            self._drain_incoming_only()
            # ---- harvest step N (the designated sync) ----
            toks, lps, n_emit, sync_s = self._harvest_spec_step(
                entry["packed"], S
            )
            # ---- repair + plan + dispatch N+1 (the exposed span) ----
            # the repair loop is the exposed DRAFT cost (pre-draft
            # misses re-proposing from the realized tail); the plan +
            # chain + dispatch below are exposed PLAN cost. The split
            # matters: draft_hidden_frac compares hidden vs exposed
            # *drafting* only — folding constant per-step plan time
            # into it would understate the hiding at high hit rates.
            t_rep = time.monotonic()
            entries = []
            for i, (seq, drafts) in enumerate(entry["works"]):
                n = int(n_emit[i])
                emitted = [int(t) for t in toks[i, :n]]
                pre = pres[i]
                if (
                    pre is not None
                    and n == len(drafts) + 1
                    and emitted
                    and emitted[-1] == pre[0]
                ):
                    nxt_drafts = pre[1]
                    self.spec_predraft_hits += 1
                else:
                    # realized tail diverged from the optimistic one:
                    # re-draft from the actual tail so the proposal
                    # stream stays byte-identical to serial spec
                    nxt_drafts = self._draft_tokens(
                        seq, self._spec_budget(seq, n), suffix=emitted
                    )
                    self.spec_predraft_misses += 1
                entries.append((seq, n, nxt_drafts))
            repair_s = time.monotonic() - t_rep
            SPEC_STEP_SECONDS.labels("draft").observe(repair_s)
            self.spec_draft_exposed_s_total += repair_s
            flush = (
                bool(sched.waiting)
                or bool(sched.prefilling)
                or not self._running
                # a drain must reach the serial loop's migrate sweep:
                # the pipeline would otherwise hold its streams until
                # they finish naturally, riding out the whole deadline
                or self._draining
                or not self._control.empty()
                # degradation rung 2 (planner/degradation.py) flips
                # spec_suspended from the loop thread: the serial loop
                # honors it every plan, so the pipeline must not keep
                # paying the verify rectangle for a whole batch drain
                or self.spec_suspended
            )
            nxt = None if flush else sched.plan_pipelined_spec(entries, S)
            if nxt is not None and not any(d for _, d in nxt["works"]):
                # zero proposals across the batch: the [B, S] rectangle
                # would pay (K+1)x the work for 1 token/row — flush and
                # let the next plan take the plain step (no deadlock:
                # emit below still applies this step's tokens)
                nxt = None
            next_entry = None
            if nxt is not None:
                tokens_dev = self._chain_spec_fn(
                    entry["packed"], nxt["arrays"]["tokens"], nxt["src_idx"]
                )
                # the attribution ledger's plan_ms carries the WHOLE
                # exposed host span (repair + plan + chain): its
                # overlapped branch bills the measured idle gap to plan
                # first, which is exactly where exposed drafting should
                # land ("exposed draft stays plan")
                next_entry = self._dispatch_spec_entry(
                    nxt,
                    plan_ms=round((time.monotonic() - t_rep) * 1e3, 3),
                    draft_ms=round(repair_s * 1e3, 3),
                    tokens_dev=tokens_dev,
                )
            # ---- emit step N under N+1's device time ----
            late_stop = self._emit_spec_entry(entry, toks, lps, n_emit)
            self._finish_spec_record(entry, sync_s)
            if next_entry is None:
                return True
            if late_stop:
                # a stop landed while N+1 was planned: its rows may
                # include the stopped sequence — harvest it, discard
                # dead rows' tokens, and return with nothing in flight
                # so the serial reap frees the blocks safely
                toks, lps, n_emit, sync_s = self._harvest_spec_step(
                    next_entry["packed"], S
                )
                self._emit_spec_entry(next_entry, toks, lps, n_emit)
                self._finish_spec_record(next_entry, sync_s)
                return True
            entry = next_entry

    # ------------------------------------------------------------------
    # Overlapped single-step decode (docs/performance.md)
    # ------------------------------------------------------------------
    def _overlap_ok(self) -> bool:
        """The overlapped pipelines run single-host, pp=1, leader-less:
        the chained-dispatch announce protocol doesn't exist for
        followers, and the pp stage rotation keeps its serial step."""
        return (
            self.config.overlap
            and self._mh_broadcast is None
            and not self._is_follower
            and self._pp == 1
        )

    def _overlap_divert(self, seqs: list) -> bool:
        """Batches that must take the SERIAL step instead of the
        overlapped decode pipeline: penalty/bias generated-token counts
        live on host one step behind dispatch (a lagged count would
        change the sampled distribution), top-logprobs rides a
        separately-compiled step variant whose chained-token signature
        is deliberately not prewarmed (mirrors the window pipeline's
        penalties_in gate), and guided sequences FLUSH TO SERIAL by
        construction: step N+1's allow-mask is a function of step N's
        sampled token, so it cannot be known at N+1's dispatch time —
        the pipeline would have to dispatch with a stale mask
        (docs/guided_decoding.md "Divert conditions"). This covers the
        plain decode pipeline AND the overlapped spec pipeline (both
        gate on this predicate)."""
        return (
            self._wants_toplp(seqs)
            or any(s.request.sampling.needs_penalties for s in seqs)
            or any(s.request.sampling.logit_bias for s in seqs)
            or any(s.guided_state is not None for s in seqs)
        )

    def _decode_pipeline(self, seqs: list, plan_ms: float = 0.0) -> None:
        """Double-buffered single-step decode — the decode_steps == 1
        serving path restructured so the device never waits out the
        host's plan+unpack+emit time (ROADMAP item 2's host-side lever):

        - while device step N executes, the host plans AND dispatches
          step N+1, its token column chained ON DEVICE from N's sampled
          tokens (``chain_next``): per-step host->device traffic is the
          small position/slot/seed arrays only, and there is no host
          round trip between consecutive steps;
        - step N's packed [2B] output is harvested only after N+1 is in
          flight, so the hot-path sync waits on a result that is
          already (or nearly) done;
        - scheduler state (token appends, stop checks, block frees,
          prefix-cache commits) runs ONE STEP BEHIND dispatch.
          ``plan_pipelined_decode`` predicts every ``should_finish``
          condition a step ahead so an in-flight step never writes KV
          into blocks a harvest-time ``finish()`` frees; a token
          sampled past a late-detected stop (cancellation, deadline,
          backend stop-string) is DISCARDED at harvest — never
          appended, never emitted, never content-addressed — and the
          pipeline flushes so ``plan()`` reaps with nothing in flight;
        - the pipeline NEVER preempts and never admits: block pressure
          or new arrivals drain it back to the serial planner.

        Greedy output is bit-identical to the serial loop (same step
        program over the same values); sampled output draws the
        identical seed stream (seeds offset by the in-flight lag).
        """
        sched = self.scheduler
        assert sched is not None
        from collections import deque

        from dynamo_tpu.parallel.multihost import host_value

        lag: dict[int, int] = {}

        def _dead(seq) -> bool:
            if seq.is_cancelled and seq.is_cancelled():
                return True
            return bool(seq.deadline) and time.monotonic() >= seq.deadline

        def dispatch(seqs_, arrays, sampling, p_ms: float) -> dict:
            t0 = time.monotonic()
            outs = self._dispatch_device_step(
                arrays, sampling, origin="decode-pipeline"
            )
            packed = self._pack_pair_fn(outs[0], outs[1])
            return {
                "packed": packed,
                "toks": outs[0],  # device column the next step chains off
                "seqs": seqs_,
                "b": arrays["context_lens"].shape[0],
                "vmap": {id(s): 1 for s in seqs_},
                "t_disp": t0,
                "plan_ms": p_ms,
                # consumed here, not by _record_step's use_phases: at
                # harvest time _last_phases belongs to a LATER dispatch
                "phases": dict(self._last_phases),
            }

        def try_extend() -> bool:
            # each extension is one logical engine step: the fault point
            # (docs/robustness.md) must see it, or a whole decode inside
            # one _one_step call would evade per-step fault plans. Fired
            # BEFORE planning/allocation: an injected error propagates
            # with host state only advanced through the last harvest, so
            # the quarantine retry recomputes the abandoned in-flight
            # step bit-identically (KV slots rewritten with same values)
            faults.fire("engine.step")
            newest = pending[-1]
            self._drain_incoming_only()
            if sched.waiting or sched.prefilling:
                return False  # drain: the serial planner admits/prefills
            t_plan = time.monotonic()
            nxt = sched.plan_pipelined_decode(newest["seqs"], lag)
            if nxt is None:
                return False
            arrays = nxt["arrays"]
            arrays["tokens"] = self._chain_next_fn(
                newest["toks"], nxt["src_idx"]
            )
            sampling = self._batch_sampling(
                nxt["seqs"],
                arrays["context_lens"].shape[0],
                offset=nxt["offsets"],
            )
            e = dispatch(
                nxt["seqs"], arrays, sampling,
                round((time.monotonic() - t_plan) * 1e3, 3),
            )
            _lag_add(lag, e)
            pending.append(e)
            return True

        def harvest(e, depth: int) -> bool:
            t0 = time.monotonic()
            packed_h = host_value(e["packed"])
            self.overlap.note_complete()
            self._unsynced_steps.clear()
            sync_ms = round((time.monotonic() - t0) * 1e3, 3)
            B = e["b"]
            toks = packed_h[:B].astype(np.int32)
            lps = packed_h[B : 2 * B]
            finished = False
            for i, seq in enumerate(e["seqs"]):
                if seq.state != SeqState.RUNNING:
                    continue
                if _dead(seq):
                    # late-detected stop: DISCARD the in-flight token —
                    # nothing appended means nothing emitted and nothing
                    # the prefix cache could ever content-address
                    finished = True
                    continue
                self._emit_token(seq, int(toks[i]), float(lps[i]))
                if seq.state != SeqState.RUNNING:
                    finished = True
            _lag_sub(lag, e)
            dt = time.monotonic() - e["t_disp"]
            ENGINE_STEP_SECONDS.labels("decode").observe(dt)
            self._record_step(
                "decode", dt,
                batch=len(e["seqs"]),
                tokens=len(e["seqs"]),
                overlapped=True,
                use_phases=False,  # per-entry stamps below
                plan_ms=e["plan_ms"],
                sync_ms=sync_ms,
                pipeline_depth=depth,
                # host time this step ran UNDER (planning/dispatching
                # N+1, emitting N-1) — the overlapped span
                overlap_ms=round((t0 - e["t_disp"]) * 1e3, 3),
                **e["phases"],
            )
            self._trace(
                "pipe_decode", b=len(e["seqs"]), depth=depth,
                ms=round(dt * 1e3, 1), sync_ms=sync_ms,
            )
            return finished

        arrays = sched.build_decode_arrays(seqs)
        sampling = self._batch_sampling(seqs, arrays["tokens"].shape[0])
        entry = dispatch(seqs, arrays, sampling, plan_ms)
        _lag_add(lag, entry)
        pending = deque([entry])
        while pending:
            # extend BEFORE harvesting: nothing has been freed since the
            # last harvest, so planning here never touches blocks an
            # in-flight step writes. _running/_control: shutdown and
            # engine-thread calls flush rather than starve.
            while (
                len(pending) < self.PIPELINE_DEPTH
                and self._running
                and not self._draining
                and self._control.empty()
            ):
                if not try_extend():
                    break
            finished = harvest(pending.popleft(), depth=len(pending) + 1)
            if finished and pending:
                # a finish freed blocks (or a stop was detected) with a
                # step in flight: predicted finishes were already
                # excluded from it, and no allocation can occur until
                # the pipeline drains — flush so plan()/admission and
                # the reap run with nothing in flight
                while pending:
                    harvest(pending.popleft(), depth=len(pending))
                return

    def _batch_sampling(
        self, seqs: list, B: int, offset=0
    ) -> SamplingBatch:
        """Per-slot sampling params; ``offset`` (int, or per-seq list)
        advances the per-step seeds past tokens of an in-flight (not
        yet host-applied) window."""
        opts = [s.request.sampling.normalized() for s in seqs]
        pad = B - len(seqs)
        offs = offset if isinstance(offset, list) else [offset] * len(seqs)
        seeds = []
        for s, off in zip(seqs, offs):
            base = s.request.sampling.seed
            if base is None:
                # crc32, NOT hash(): Python's str hash is SipHash-salted
                # per process, and the unseeded base must be identical
                # on whichever worker serves (or RESUMES) the request
                base = zlib.crc32(s.request_id.encode()) & 0x7FFFFFFF
            # resume_offset: a migrated request's RNG stream continues
            # where the dead worker's delivery stopped (the request_id —
            # hence the unseeded base — survives migration unchanged),
            # so the continuation draws the same per-position samples
            # the original stream would have (docs/robustness.md)
            seeds.append(
                base + s.generated + s.request.resume_offset + off
            )
        seeds += [0] * pad
        gen_counts = prompt_ids = None
        if any(o.needs_penalties for o in opts):
            # sparse per-seq token state for the penalty path: generated
            # counts (freq/pres/rep) and distinct prompt ids (rep,
            # cached on the sequence — prompts are immutable)
            gen_counts = [dict(s.gen_counts) for s in seqs]
            for s in seqs:
                if s.prompt_unique is None:
                    # request.token_ids is a host python list; cached
                    # once per sequence, no device array involved
                    s.prompt_unique = np.unique(
                        np.asarray(s.request.token_ids, np.int32)  # dynalint: disable=transitive-host-sync-in-step-loop — host-list conversion
                    )
            prompt_ids = [s.prompt_unique for s in seqs]
            gen_counts += [{} for _ in range(pad)]
            prompt_ids += [np.zeros((0,), np.int32)] * pad
        opts += [opts[-1]] * pad
        top_lp = None
        if self._wants_toplp(seqs):
            top_lp = [
                (s.request.output.logprobs or 0) for s in seqs
            ] + [0] * pad
        return SamplingBatch.from_options(
            opts, seeds, gen_counts, prompt_ids, top_lp
        )

    # ------------------------------------------------------------------
    # Guided decoding (dynamo_tpu/guided; docs/guided_decoding.md)
    # ------------------------------------------------------------------
    def _guided_automaton(self, spec):
        """Resolve a request's guided spec to a TokenAutomaton through
        the process-wide compile LRU (submit thread: a compile or a
        tokenizer load never stalls the step loop)."""
        from dynamo_tpu.guided import automaton_for

        if self._guided_tokenizer is None:
            from dynamo_tpu.tokenizer import Tokenizer

            self._guided_tokenizer = Tokenizer.from_file(
                self.config.model_path
            )
        mc = self.model_config
        assert mc is not None
        eos = set(mc.eos_token_ids) | set(self.eos_token_ids)
        return automaton_for(
            spec,
            self._guided_tokenizer,
            self.config.model_path or self.config.model_name,
            mc.vocab_size,
            eos,
        )

    def _guided_allow_mask(
        self, seqs: list, B: int
    ) -> Optional[np.ndarray]:
        """[B, V_pad] bool allow-mask for a serial prefill/decode batch,
        or None when no sequence is guided. Unguided (and pad) rows are
        all-True — the mask variant constrains only the rows that asked
        for it. Pure host work over cached per-state masks (no device
        arrays; DL010-clean)."""
        if not any(s.guided_state is not None for s in seqs):
            return None
        assert self.model_config is not None
        m = np.ones((B, self.model_config.vocab_size), dtype=bool)
        for i, s in enumerate(seqs):
            if s.guided_state is not None:
                m[i] = s.guided_state.allow_mask()
        return m

    def _guided_spec_masks(
        self, works: list, S: int, B: int
    ) -> Optional[np.ndarray]:
        """[B, S, V_pad] per-position masks for a spec verify batch
        (``works`` rows are (seq, [carry] + kept_drafts)), or None when
        no row is guided. Position j of a guided row is the automaton
        state after its first j drafts commit — the SAME mask sequence
        the serial path would apply step by step, which is what makes
        guided speculative verification exact. Positions past a row's
        kept drafts (never emitted) and unguided rows stay all-True."""
        if not any(seq.guided_state is not None for seq, _ in works):
            return None
        assert self.model_config is not None
        V = self.model_config.vocab_size
        m = np.ones((B, S, V), dtype=bool)
        for i, (seq, row) in enumerate(works):
            gs = seq.guided_state
            if gs is None:
                continue
            m[i, : len(row)] = gs.masks_for_drafts(row[1:])
        return m

    def _dispatch_multi_step(
        self,
        arrays: dict[str, np.ndarray],
        sampling: SamplingBatch,
        tokens_dev=None,
    ):
        """Launch one fused window; returns DEVICE (toks, lps) [B, K] —
        callers sync when they need values, so the next window can be
        dispatched underneath. ``tokens_dev`` chains the previous
        window's device-resident last-token column (no host hop)."""
        assert self._multi_step_fn is not None
        if self._mh_broadcast is not None:
            self._mh_broadcast.announce_multi_step(arrays, sampling)
        # stage AFTER the announce: followers deserialize host numpy
        arrays, sampling = self._stage_step_inputs(arrays, sampling)
        self.overlap.note_dispatch()
        packed, last_tok, self.k_cache, self.v_cache = self._multi_step_fn(
            self.params,
            self.k_cache,
            self.v_cache,
            arrays["tokens"] if tokens_dev is None else tokens_dev,
            arrays["positions"],
            arrays["block_tables"],
            arrays["context_lens"],
            arrays["valid_steps"],
            sampling.arrays,
        )
        return packed, last_tok

    @staticmethod
    def _unpack_window(
        packed_host: np.ndarray, toplp: bool = False
    ) -> tuple[np.ndarray, ...]:
        """Split a window's packed [B, cols] output: (toks [B,K],
        lps [B,K]) base; with ``toplp`` additionally (top_ids [B,K,N]
        i32, top_lps [B,K,N]) — layout set by decode_window."""
        from dynamo_tpu.engine.sampling import TOPLP_N

        B = packed_host.shape[0]
        if not toplp:
            K = packed_host.shape[1] // 2
            return packed_host[:, :K].astype(np.int32), packed_host[:, K:]
        K = packed_host.shape[1] // (2 + 2 * TOPLP_N)
        toks = packed_host[:, :K].astype(np.int32)
        lps = packed_host[:, K : 2 * K]
        tids = packed_host[:, 2 * K : 2 * K + K * TOPLP_N].reshape(
            B, K, TOPLP_N
        ).astype(np.int32)
        tlps = packed_host[:, 2 * K + K * TOPLP_N :].reshape(B, K, TOPLP_N)
        return toks, lps, tids, tlps

    @staticmethod
    def _wants_toplp(seqs: list) -> bool:
        return any((s.request.output.logprobs or 0) > 0 for s in seqs)

    def _pad_prefill_rect(
        self, arrays: dict[str, np.ndarray], P: int, T: int, width: int
    ) -> dict[str, np.ndarray]:
        """Pad bucketed prefill arrays up to the mixed step's FIXED
        [P, T] rectangle (and ``width``-wide block tables). Pad rows
        write to the reserved garbage slot 0 and have ctx 0, exactly
        like batch-bucket padding."""
        B0, T0 = arrays["tokens"].shape
        w0 = arrays["block_tables"].shape[1]
        out = {
            "tokens": np.zeros((P, T), np.int32),
            "positions": np.zeros((P, T), np.int32),
            "slot_mapping": np.zeros((P * T,), np.int32),
            "block_tables": np.zeros((P, width), np.int32),
            "context_lens": np.zeros((P,), np.int32),
            "last_token_idx": np.zeros((P,), np.int32),
        }
        out["tokens"][:B0, :T0] = arrays["tokens"]
        out["positions"][:B0, :T0] = arrays["positions"]
        out["slot_mapping"].reshape(P, T)[:B0, :T0] = arrays[
            "slot_mapping"
        ].reshape(B0, T0)
        out["block_tables"][:B0, :w0] = arrays["block_tables"]
        out["context_lens"][:B0] = arrays["context_lens"]
        out["last_token_idx"][:B0] = arrays["last_token_idx"]
        return out

    def _dispatch_mixed(
        self,
        works: list,
        seqs: list,
        p_arrays: dict[str, np.ndarray],
        d_arrays: dict[str, np.ndarray],
        sampling_p: SamplingBatch,
        sampling_d: SamplingBatch,
        tokens_dev=None,
        rect: Optional[tuple[int, int]] = None,
    ):
        """Launch one mixed window; returns device (flat, last_tok,
        p_next) — callers sync `flat` when they need values."""
        assert self._mixed_step_fn is not None
        P, T = rect or (
            self.config.mixed_prefill_rows, self.config.mixed_prefill_len
        )
        width = max(
            p_arrays["block_tables"].shape[1],
            d_arrays["block_tables"].shape[1],
        )
        p_pad = self._pad_prefill_rect(p_arrays, P, T, width)
        if d_arrays["block_tables"].shape[1] < width:
            dt = np.zeros((d_arrays["block_tables"].shape[0], width), np.int32)
            dt[:, : d_arrays["block_tables"].shape[1]] = d_arrays["block_tables"]
            d_arrays["block_tables"] = dt
        if self._mh_broadcast is not None:
            self._mh_broadcast.announce_mixed(
                p_pad, sampling_p, d_arrays, sampling_d
            )
        # stage AFTER the announce: followers deserialize host numpy.
        # d_arrays' row count is read below, so keep the staged copy
        # separate from the host dict the caller may still hold.
        B_d = d_arrays["tokens"].shape[0]
        p_pad, sampling_p = self._stage_step_inputs(p_pad, sampling_p)
        d_staged, sampling_d = self._stage_step_inputs(d_arrays, sampling_d)
        self.overlap.note_dispatch()
        flat, last_tok, p_next, self.k_cache, self.v_cache = (
            self._mixed_step_fn(
                self.params,
                self.k_cache,
                self.v_cache,
                p_pad["tokens"],
                p_pad["positions"],
                p_pad["slot_mapping"],
                p_pad["block_tables"],
                p_pad["context_lens"],
                p_pad["last_token_idx"],
                sampling_p.arrays,
                d_staged["tokens"] if tokens_dev is None else tokens_dev,
                d_staged["positions"],
                d_staged["block_tables"],
                d_staged["context_lens"],
                d_staged["valid_steps"],
                sampling_d.arrays,
            )
        )
        return flat, last_tok, p_next, B_d, P

    def _emit_mixed(
        self, works: list, seqs: list, flat_h, B: int,
        P: Optional[int] = None,
    ) -> None:
        """Sync-side bookkeeping of one mixed window's flat output.
        ``P`` = the window's prefill-rectangle row count (narrow
        default, or the wide rect's). Mixed windows never carry the
        top-logprobs variant (the window pipeline diverts toplp batches
        to dedicated prefill + pure windows), so the flat layout is
        always the base one."""
        sched = self.scheduler
        assert sched is not None
        assert not (
            self._wants_toplp(seqs)
            or self._wants_toplp([w.seq for w in works])
        ), "top-logprobs batch reached the mixed step"
        K = sched.decode_lookahead
        if P is None:
            P = self.config.mixed_prefill_rows
        tok_m, lp_m = self._unpack_window(
            flat_h[: B * 2 * K].reshape(B, 2 * K)
        )
        p_next_h = flat_h[B * 2 * K : B * 2 * K + P].astype(np.int32)
        p_lp_h = flat_h[B * 2 * K + P :]
        for i, work in enumerate(works):
            sched.complete_prefill_chunk(work)
            if work.is_last_chunk:
                self._emit_token(work.seq, int(p_next_h[i]), float(p_lp_h[i]))
        for i, seq in enumerate(seqs):
            self._emit_window(seq, tok_m[i], lp_m[i])

    def _drain_incoming_only(self) -> None:
        """Drain ONLY the submit queue (not the control queue) — used
        inside the window pipeline, where control calls (KV export /
        import) must NOT run against host state that lags the in-flight
        window by up to K tokens."""
        assert self.scheduler is not None
        while True:
            try:
                item = self._incoming.get_nowait()
            except thread_queue.Empty:
                return
            self.scheduler.add_request(item)

    # in-flight windows: 2 hides the tunnel's per-window transfer
    # serialization behind compute (measured 705 -> 602 ms/window on
    # v5e; depth 3 adds nothing, depth 1 trades ~7% throughput for one
    # window less first-token latency). Set via DYN_PIPELINE_DEPTH
    # (read at engine construction; see __init__).
    PIPELINE_DEPTH = 2

    def _window_pipeline(
        self, works: list, seqs: list,
        rect: Optional[tuple[int, int]] = None,
    ) -> None:
        """THE serving loop: fused decode windows with optional prefill
        rectangles, PIPELINED to depth 2. While windows k and k+1 run
        on device, the host plans window k+2 — last-chunk prefills
        GRADUATE to decode rows of the following window, their first
        token chained on device from that window's outputs
        (scheduler.plan_pipelined_mixed + chain_tokens); new arrivals
        are admitted straight into the next rectangle; sequences
        finishing inside in-flight windows simply aren't rows of later
        ones. Per-sequence ``lag`` (sampled-but-unapplied tokens across
        all in-flight windows) drives positions/budgets. Multihost
        leaders pipeline too: chained windows send a KIND_CHAIN
        pre-announcement so followers derive the token column from
        their own device outputs (parallel/multihost.py). Any
        irregularity (stop-token finishes, cancellations, multimodal,
        penalties, control-plane calls, shutdown) flushes
        the pipeline: in-flight windows are synced in order, surviving
        sequences keep their tokens, finished ones discard theirs
        (their blocks stay allocated until the flush, so no reuse races
        in-flight writes). Multimodal prefill chunks fall back to a
        dedicated step — embedding injection doesn't ride the fixed
        rectangle."""
        sched = self.scheduler
        assert sched is not None
        from collections import deque

        from dynamo_tpu.parallel.multihost import host_value

        # multihost included: pipelined windows broadcast a KIND_CHAIN
        # pre-announcement so followers derive the token column from
        # their own retained device outputs (parallel/multihost.py)
        pipelining = True
        lag: dict[int, int] = {}

        def penalties_in(ws: list, ss: list) -> bool:
            # penalties, top-logprobs and logit-bias all flush/block the
            # pipeline: their windows run separately-compiled variants
            # whose chained-dispatch signatures aren't prewarmed
            return (
                any(w.seq.request.sampling.needs_penalties for w in ws)
                or any(s.request.sampling.needs_penalties for s in ss)
                or any(w.seq.request.sampling.logit_bias for w in ws)
                or any(s.request.sampling.logit_bias for s in ss)
                or self._wants_toplp([w.seq for w in ws])
                or self._wants_toplp(ss)
            )

        def make_entry(out, works_, seqs_, vmap: dict) -> dict:
            """One pipeline entry; the lag invariant (vmap = tokens this
            window adds per sequence, incl. +1 per graduating last
            chunk) lives HERE and nowhere else."""
            if out[0] == "pure":
                e = {"kind": "pure", "flat": out[1], "last": out[2],
                     "b": out[3]}
            elif out[0] == "prefill":
                # prefill-only cohort entry (overlap path): no decode
                # rows; the sampled first tokens chain on device into
                # the NEXT window via chain_next (try_extend)
                e = {"kind": "prefill", "packed": out[1], "p_next": out[2],
                     "p_rows": out[3], "b": 0}
            else:
                e = {"kind": "mixed", "flat": out[1], "last": out[2],
                     "p_next": out[3], "b": out[4], "p_rows": out[5]}
            e["works"] = works_
            e["seqs"] = seqs_
            e["vmap"] = dict(vmap)
            for w in works_:
                if w.is_last_chunk:
                    e["vmap"][id(w.seq)] = e["vmap"].get(id(w.seq), 0) + 1
            # overlap phase stamps for this entry's flight-recorder row
            e["t_disp"] = time.monotonic()
            e["idle_gap_ms"] = round(self.overlap.last_idle_gap_s * 1e3, 3)
            return e

        # dispatch the first window
        if works:
            p_arrays = sched.build_prefill_batch_arrays(works)
            # Multimodal chunks, top-logprobs AND penalty/bias batches
            # take a dedicated prefill step instead of the mixed
            # rectangle: embedding injection doesn't ride the fixed
            # rectangle, and the mixed jit variants for those sampling
            # features are deliberately NOT part of the prewarm set
            # (the opt-in prewarms cover dedicated prefill + pure
            # windows; an unwarmed variant is a multi-minute mid-serve
            # compile over a chip tunnel). Decode follows on the next
            # plan.
            if "extra_embeds" in p_arrays or penalties_in(works, seqs):
                sampling = self._batch_sampling(
                    [w.seq for w in works], p_arrays["tokens"].shape[0]
                )
                s_out = self._run_device_step(
                    p_arrays, sampling,
                    sync=any(w.is_last_chunk for w in works),
                    origin="prefill:" + ",".join(
                        w.seq.request_id for w in works
                    ),
                )
                for i, work in enumerate(works):
                    sched.complete_prefill_chunk(work)
                    if work.is_last_chunk:
                        top = (
                            (s_out[2][i], s_out[3][i])
                            if len(s_out) > 2
                            else None
                        )
                        self._emit_token(
                            work.seq, int(s_out[0][i]), float(s_out[1][i]),
                            top=top,
                        )
                return
            if not seqs:
                # prefill-only first entry (overlapped cohort
                # graduation, _one_step): dispatch the cohort WITHOUT a
                # hard sync — try_extend chains its sampled first
                # tokens on device into the first decode window, so the
                # prefill->decode boundary costs no host round trip
                assert all(w.is_last_chunk for w in works)
                sampling_p = self._batch_sampling(
                    [w.seq for w in works], p_arrays["tokens"].shape[0]
                )
                outs = self._dispatch_device_step(
                    p_arrays, sampling_p,
                    origin="prefill:" + ",".join(
                        w.seq.request_id for w in works
                    ),
                )
                out = (
                    "prefill",
                    self._pack_pair_fn(outs[0], outs[1]),
                    outs[0],
                    p_arrays["tokens"].shape[0],
                )
                d_arrays = None
            else:
                d_arrays = sched.build_decode_arrays(seqs)
                p_rows = (rect or (self.config.mixed_prefill_rows, 0))[0]
                sampling_p = self._batch_sampling(
                    [w.seq for w in works], p_rows
                )
                sampling_d = self._batch_sampling(
                    seqs, d_arrays["tokens"].shape[0]
                )
                pipelining = pipelining and not (
                    sampling_p.has_penalties or sampling_d.has_penalties
                    or sampling_p.has_toplp or sampling_d.has_toplp
                    or sampling_p.has_bias or sampling_d.has_bias
                )
                out = ("mixed",) + self._dispatch_mixed(
                    works, seqs, p_arrays, d_arrays, sampling_p, sampling_d,
                    rect=rect,
                )
        else:
            d_arrays = sched.build_decode_arrays(seqs)
            sampling_d = self._batch_sampling(seqs, d_arrays["tokens"].shape[0])
            pipelining = pipelining and not (
                sampling_d.has_penalties or sampling_d.has_toplp
                or sampling_d.has_bias
            )
            out = ("pure",) + self._dispatch_multi_step(d_arrays, sampling_d) \
                + (d_arrays["tokens"].shape[0],)
        vmap0 = (
            {id(s): int(d_arrays["valid_steps"][i])
             for i, s in enumerate(seqs)}
            if d_arrays is not None
            else {}
        )
        entry = make_entry(out, works, seqs, vmap0)
        _lag_add(lag, entry)
        pending = deque([entry])

        def harvest_entry(e) -> None:
            t0 = time.monotonic()
            if e["kind"] == "prefill":
                # cohort-graduation entry: one packed [2P] transfer
                # carrying first tokens + logprobs; the decode window
                # chained off them is already in flight behind it
                ph = host_value(e["packed"])
                P = e["p_rows"]
                p_next_h = ph[:P].astype(np.int32)
                p_lp_h = ph[P : 2 * P]
                for i, work in enumerate(e["works"]):
                    sched.complete_prefill_chunk(work)
                    if work.is_last_chunk:
                        self._emit_token(
                            work.seq, int(p_next_h[i]), float(p_lp_h[i])
                        )
            elif e["kind"] == "mixed":
                self._emit_mixed(
                    e["works"], e["seqs"], host_value(e["flat"]), e["b"],
                    P=e["p_rows"],
                )
            else:
                tlp = self._wants_toplp(e["seqs"])
                win = self._unpack_window(host_value(e["flat"]), tlp)
                for i, seq in enumerate(e["seqs"]):
                    tops = (win[2][i], win[3][i]) if tlp else None
                    self._emit_window(seq, win[0][i], win[1][i], tops=tops)
            self.overlap.note_complete()
            # window sync succeeded: earlier async dispatches are
            # known-good (in-order execution) — retire deferred-error
            # forensics
            self._unsynced_steps.clear()
            _lag_sub(lag, e)
            win_s = time.monotonic() - t0
            # one flight-recorder entry per WINDOW (the serving-path
            # unit of work): duration is the host-side sync+emit wait —
            # the dispatch overlapped earlier windows by design
            self._record_step(
                "window_" + e["kind"], win_s,
                batch=len(e["seqs"]),
                prefill_rows=len(e["works"]),
                tokens=sum(e["vmap"].values()),
                overlapped=True,
                pipeline_depth=len(pending),
                use_phases=False,  # dispatched via the window fns, not
                # _run_device_step — its phase stamps belong elsewhere
                # overlap phase stamps (telemetry/overlap.py): the span
                # this window ran under other host work, and the device
                # idle gap that preceded its dispatch
                overlap_ms=round((t0 - e["t_disp"]) * 1e3, 3),
                idle_gap_ms=e["idle_gap_ms"],
            )
            self._trace(
                "window", kind=e["kind"], b=len(e["seqs"]),
                p=len(e["works"]), wait=len(sched.waiting),
                pref=len(sched.prefilling), run=len(sched.running),
                depth=len(pending),
                ms=round(win_s * 1e3, 1),
            )

        def try_extend() -> bool:
            """Plan + dispatch one more window chained off the newest
            in-flight one. False = the pipeline can't grow further."""
            newest = pending[-1]
            self._drain_incoming_only()
            nxt = sched.plan_pipelined_mixed(
                newest["seqs"], newest["works"], lag,
                # a prefill-only entry's token vector is the prefill
                # rows alone — graduated row r chains from index r
                grad_base=0 if newest["kind"] == "prefill" else None,
            )
            if nxt is None or penalties_in(nxt["works2"], nxt["seqs"]):
                return False
            p2 = None
            if nxt["works2"]:
                p2 = sched.build_prefill_batch_arrays(nxt["works2"])
                if "extra_embeds" in p2:
                    return False  # multimodal never rides the pipeline
            if self._mh_broadcast is not None:
                # multihost pipelining: followers chain the SAME token
                # column from their own retained device outputs — the
                # next announce's host token values are placeholders.
                # (prefill-only entries exist only single-host:
                # _one_step gates them on _overlap_ok)
                assert newest["kind"] != "prefill"
                self._mh_broadcast.announce_chain(
                    nxt["src_idx"], newest["kind"] == "mixed"
                )
            if newest["kind"] == "prefill":
                chained = self._chain_next_fn(
                    newest["p_next"], nxt["src_idx"]
                )
            elif newest["kind"] == "mixed":
                chained = self._chain_fn(
                    newest["last"], newest["p_next"], nxt["src_idx"]
                )
            else:
                chained = self._chain_pure_fn(newest["last"], nxt["src_idx"])
            s_d2 = self._batch_sampling(
                nxt["seqs"],
                nxt["arrays"]["tokens"].shape[0],
                offset=nxt["offsets"],
            )
            if p2 is not None:
                s_p2 = self._batch_sampling(
                    [w.seq for w in nxt["works2"]], nxt["rect"][0]
                )
                out = ("mixed",) + self._dispatch_mixed(
                    nxt["works2"], nxt["seqs"], p2, nxt["arrays"],
                    s_p2, s_d2, tokens_dev=chained, rect=nxt["rect"],
                )
            else:
                out = ("pure",) + self._dispatch_multi_step(
                    nxt["arrays"], s_d2, tokens_dev=chained
                ) + (nxt["arrays"]["tokens"].shape[0],)
            e = make_entry(out, nxt["works2"], nxt["seqs"], nxt["vmap"])
            _lag_add(lag, e)
            pending.append(e)
            return True

        while pending:
            # fill the pipeline BEFORE syncing (nothing has been freed
            # yet, so planning here can never reallocate blocks an
            # in-flight window still writes).
            # _running: a shutdown() mid-stream must flush and return,
            # not keep dispatching until the batch drains
            while (
                len(pending) < self.PIPELINE_DEPTH
                and pipelining
                and self._running
                and self._control.empty()
            ):
                if not try_extend():
                    break
            harvest_entry(pending.popleft())
            if any(
                s.state != SeqState.RUNNING for e in pending for s in e["seqs"]
            ) or any(
                w.seq.state != SeqState.PREFILL
                for e in pending
                for w in e["works"]
            ):
                # composition changed under in-flight windows: flush
                while pending:
                    harvest_entry(pending.popleft())
                return

    @staticmethod
    def _top_entry(seq: Sequence, ids, lps) -> dict[int, float]:
        """One token's top-logprob alternatives, trimmed to the count
        the request asked for ({} when this seq only wants the chosen
        logprob but rode a top-lp batch)."""
        k = seq.request.output.logprobs or 0
        return {int(i): float(l) for i, l in zip(ids[:k], lps[:k])}

    def _emit_token(
        self, seq: Sequence, token: int, logprob: float, top=None
    ) -> None:
        sched = self.scheduler
        assert sched is not None
        sched.append_token(seq, token)
        ENGINE_TOKENS_GENERATED.inc()
        self.tokens_generated_total += 1
        reason = sched.should_finish(seq)
        if reason is not None:
            # finalize SLO + autopsy BEFORE the last token item hits the
            # output queue: the serving layer ships the autopsy payload
            # ahead of each item, and consumers abandon the stream at
            # this token (max_tokens), never reaching the finish item
            self._finalize_observability(seq, reason)
        if seq.emit is not None:
            tl = None
            if top is not None and (seq.request.output.logprobs or 0) > 0:
                tl = [self._top_entry(seq, top[0], top[1])]
            seq.emit(
                LLMEngineOutput(
                    request_id=seq.request_id,
                    token_ids=[token],
                    log_probs=[logprob],
                    top_logprobs=tl,
                )
            )
        if reason is not None:
            sched.finish(seq, reason)

    def _emit_window(self, seq: Sequence, tokens, logprobs, tops=None) -> None:
        """Append a fused-decode window's tokens, stopping at the first
        finish condition (the rest of the window is discarded), and emit
        ONE output carrying all kept tokens — the backend consumes
        multi-token deltas, so there's no per-token queue hop.
        ``tops`` = (top_ids [K, N], top_lps [K, N]) on the top-logprobs
        variant."""
        sched = self.scheduler
        assert sched is not None
        kept_toks: list[int] = []
        kept_lps: list[float] = []
        kept_tops: list[dict[int, float]] = []
        want_tl = tops is not None and (seq.request.output.logprobs or 0) > 0
        finish: Optional[FinishReason] = None
        for j in range(len(tokens)):
            if seq.state != SeqState.RUNNING:
                break
            sched.append_token(seq, int(tokens[j]))
            kept_toks.append(int(tokens[j]))
            kept_lps.append(float(logprobs[j]))
            if want_tl:
                kept_tops.append(self._top_entry(seq, tops[0][j], tops[1][j]))
            finish = sched.should_finish(seq)
            if finish is not None:
                break
        if kept_toks:
            ENGINE_TOKENS_GENERATED.inc(len(kept_toks))
            self.tokens_generated_total += len(kept_toks)
        if finish is not None:
            # see _emit_token: the autopsy payload must be pending
            # before the last token item is queued
            self._finalize_observability(seq, finish)
        if kept_toks and seq.emit is not None:
            seq.emit(
                LLMEngineOutput(
                    request_id=seq.request_id,
                    token_ids=kept_toks,
                    log_probs=kept_lps,
                    top_logprobs=kept_tops if want_tl else None,
                )
            )
        if finish is not None:
            sched.finish(seq, finish)

    def _emit_finish(self, seq: Sequence, reason: FinishReason) -> None:
        """Scheduler on_finish hook: close the request's output stream,
        bump finish counters, evaluate the request against the SLO
        targets, and emit the request's engine-side span tree (queue
        wait → prefill → decode) from the lifecycle stamps the
        scheduler recorded."""
        ENGINE_REQUESTS_FINISHED.labels(str(reason.value)).inc()
        self._finalize_observability(seq, reason)
        self._emit_lifecycle_spans(seq, reason)
        if seq.emit is not None:
            seq.emit(
                LLMEngineOutput(
                    request_id=seq.request_id,
                    finish_reason=reason,
                    prompt_tokens=len(seq.request.token_ids),
                    completion_tokens=seq.generated,
                )
            )
            seq.emit(None)  # sentinel: stream closed

    def _finalize_observability(
        self, seq: Sequence, reason: FinishReason
    ) -> None:
        """SLO verdict + autopsy segment, exactly once per request.

        Called EARLY — before the last token item is emitted — from the
        decode paths (consumers abandon the stream at max_tokens, so a
        payload published at the finish item would never ship), and
        again from the on_finish hook for paths that end without a
        trailing token (aborts, deadline kills, prefill-only finishes);
        the guard makes the second call a no-op."""
        if seq.observability_done:
            return
        seq.observability_done = True
        slo_met = self._observe_slo(seq, reason)
        self._publish_autopsy(seq, reason, slo_met)

    def _observe_slo(
        self, seq: Sequence, reason: FinishReason
    ) -> Optional[bool]:
        """Per-request TTFT/ITL vs the configured targets (telemetry/
        slo.py). Engine-side TTFT = submit → first appended token; ITL
        = mean decode inter-token latency. Requests that never produced
        a token (errors/cancellations before first emit) don't score —
        they'd poison attainment with infrastructure failures the SLO
        targets don't describe. An SLO miss trips the flight recorder's
        request watchdog so the steps that served the slow request are
        preserved on disk. Returns the verdict (None = unscored) so the
        autopsy segment can carry the slo_miss flag."""
        if reason in (
            FinishReason.ERROR, FinishReason.CANCELLED, FinishReason.TIMEOUT,
            # a drain handoff is a planned partial segment, not a served
            # request: the resumed continuation scores on the peer
            FinishReason.MIGRATE,
        ):
            # infrastructure failures and client disconnects don't
            # score: counting an errored request's fast partial tokens
            # as 'met' goodput would report a fleet in an error loop as
            # HEALTHY — the opposite of what the Planner signal means
            return None
        if not seq.t_submit or not seq.t_first_token:
            return None
        ttft_s = seq.t_first_token - seq.t_submit
        itl_s = None
        if seq.generated > 1:
            itl_s = (time.monotonic() - seq.t_first_token) / (
                seq.generated - 1
            )
        met = self.slo.observe(ttft_s, itl_s, completion_tokens=seq.generated)
        if not met and self.recorder is not None:
            dump = self.recorder.note_slow_request(
                seq.request_id,
                ttft_ms=round(ttft_s * 1e3, 3),
                itl_ms=round(itl_s * 1e3, 3) if itl_s is not None else None,
                tokens=seq.generated,
                finish_reason=str(reason.value),
            )
            if dump is not None:
                # the ring dump fired: preserve the rest of the state
                # too (both limiters gate independently — a suppressed
                # ring dump means a recent bundle already exists)
                self.blackbox.trigger(f"slo_miss:{seq.request_id}")
        return met

    def _publish_autopsy(
        self, seq: Sequence, reason: FinishReason, slo_met: Optional[bool]
    ) -> None:
        """Publish the request's engine-side autopsy segment under its
        rid (telemetry/autopsy.py). In the frontend's process it lands
        straight on the active record; on a remote worker it parks in
        the pending table and the endpoint server ships it on the
        ``seg`` wire frame before fin. One bounded dict per request —
        the per-step decode summary comes from the flight recorder's
        ring tail and only for requests that missed their SLO, so the
        happy path stays O(1)."""
        try:
            now = time.monotonic()
            seg: dict = {
                "source": "engine",
                "pid": os.getpid(),
                "finish_reason": str(reason.value),
                "prompt_tokens": len(seq.request.token_ids),
                "cached_prompt_tokens": seq.num_cached_prompt,
                "tokens": seq.generated,
                "resume_offset": int(
                    getattr(seq.request, "resume_offset", 0) or 0
                ),
                "guided": seq.guided_state is not None,
                "slo_miss": slo_met is False,
            }
            if seq.t_submit:
                if seq.t_admit:
                    seg["queue_wait_ms"] = round(
                        (seq.t_admit - seq.t_submit) * 1e3, 3
                    )
                if seq.t_admit and seq.t_prefill_done:
                    seg["prefill_ms"] = round(
                        (seq.t_prefill_done - seq.t_admit) * 1e3, 3
                    )
                if seq.t_prefill_done:
                    seg["decode_ms"] = round(
                        (now - seq.t_prefill_done) * 1e3, 3
                    )
                if seq.t_first_token:
                    seg["ttft_ms"] = round(
                        (seq.t_first_token - seq.t_submit) * 1e3, 3
                    )
            sched = self.scheduler
            if sched is not None:
                seg["preemptions_total"] = sched.preemptions
            if self.spec_proposed_total:
                seg["spec"] = {
                    "proposed_total": self.spec_proposed_total,
                    "accepted_total": self.spec_accepted_total,
                    "accept_rate": round(
                        self.spec_accepted_total
                        / max(1, self.spec_proposed_total),
                        4,
                    ),
                }
            if slo_met is False and self.recorder is not None:
                steps = [
                    r for r in self.recorder.snapshot(32)
                    if r.get("kind") in ("decode", "mixed", "spec")
                ]
                if steps:
                    durs = [
                        float(r.get("duration_ms") or 0.0) for r in steps
                    ]
                    seg["decode_window"] = {
                        "steps": len(steps),
                        "mean_ms": round(sum(durs) / len(durs), 3),
                        "max_ms": round(max(durs), 3),
                        "slow_steps": sum(
                            1 for r in steps if r.get("slow")
                        ),
                    }
            autopsy.publish_segment(
                seq.autopsy_rid or seq.request_id, seg
            )
        except Exception:
            # the autopsy plane must never take down a finishing request
            log.exception("autopsy segment publish failed")

    def _emit_lifecycle_spans(self, seq: Sequence, reason: FinishReason) -> None:
        """Record the engine's per-request spans at finish time. Span
        boundaries come from the scheduler's monotonic stamps, anchored
        to the submit instant's wall clock so cross-process nesting
        holds. No-op (two attribute reads) when tracing is disabled."""
        tracer = get_tracer()
        if not tracer.enabled or not seq.t_submit:
            return
        parent = seq.trace
        if parent is None:
            # untraced caller: WE are the trace head — one sampling
            # decision and ONE minted trace for the request, so its
            # three spans stay correlated (three independent record()
            # calls would each sample separately and root a separate
            # trace)
            import random

            from dynamo_tpu.telemetry import new_trace_id

            if tracer.sample < 1.0 and random.random() >= tracer.sample:
                return
            parent = {"trace_id": new_trace_id(), "span_id": None}

        def wall(mono: float) -> float:
            return seq.t_submit_wall + (mono - seq.t_submit)

        now = time.monotonic()
        attrs = {"service": "engine"}
        if seq.t_admit:
            tracer.record(
                "engine.queue_wait", start=seq.t_submit_wall,
                duration_s=seq.t_admit - seq.t_submit, parent=parent,
                attrs=attrs,
            )
        if seq.t_admit and seq.t_prefill_done:
            tracer.record(
                "engine.prefill", start=wall(seq.t_admit),
                duration_s=seq.t_prefill_done - seq.t_admit, parent=parent,
                attrs={**attrs, "prompt_tokens": len(seq.request.token_ids),
                       "cached_tokens": seq.num_cached_prompt},
            )
            tracer.record(
                "engine.decode", start=wall(seq.t_prefill_done),
                duration_s=now - seq.t_prefill_done, parent=parent,
                attrs={**attrs, "tokens": seq.generated,
                       "finish_reason": str(reason.value)},
            )

    def _annotate_deferred_error(self, exc: BaseException) -> None:
        """A device error from an earlier ``sync=False`` prefill dispatch
        only SURFACES at the next synced step (async dispatch defers
        device-side failures to the first host read). Annotate the
        raised error so quarantine forensics don't blame the batch the
        exception happened to be raised under (ADVICE r5)."""
        if not self._unsynced_steps:
            return
        note = (
            f"{len(self._unsynced_steps)} earlier sync=False prefill "
            f"dispatch(es) were never synced "
            f"[{'; '.join(self._unsynced_steps)}]; a deferred device "
            "error from those chunks can surface at this later synced "
            "step — the current batch may not be the origin"
        )
        log.warning("step failure may be deferred: %s", note)
        add_note = getattr(exc, "add_note", None)  # PEP 678, 3.11+
        if add_note is not None:
            add_note(note)
        else:
            exc.args = exc.args + (note,)
        self._unsynced_steps.clear()

    def _quarantine_step_failure(self) -> bool:
        """Try to contain a step failure to the requests most likely to
        have caused it instead of killing every in-flight stream
        (VERDICT r2 weak #6: one poisoned request must not fail all).

        Heuristic: the FIRST failure is retried outright — host state is
        untouched (emission happens after the device sync, which never
        completed), so a transient fault (device hiccup, allocator
        pressure) costs one replanned step instead of innocent requests'
        lives (ADVICE r3: don't terminate requests on transient faults).
        A repeat failure in a step that was PREFILLING new requests is
        attributed to those requests — their data is the new input.
        Further repeats (or repeat failures in pure-decode steps, where
        no single culprit is identifiable) fall back to _fail_all.
        Returns True when contained."""
        sched = self.scheduler
        plan = self._last_plan
        self._last_plan = None
        if self._step_failures == 1 and sched is not None and plan is not None:
            log.exception(
                "engine step failed (kind=%s); retrying once before "
                "quarantining", plan.kind,
            )
            return True
        if (
            sched is None
            or plan is None
            or not plan.prefill_batch
            or self._step_failures > 3
        ):
            return False
        ids = [w.seq.request_id for w in plan.prefill_batch]
        log.exception(
            "engine step failed while prefilling %s; quarantining those "
            "requests and keeping %d decode streams alive",
            ids, len(plan.decode_seqs),
        )
        for w in plan.prefill_batch:
            seq = w.seq
            if seq in sched.prefilling:
                sched.prefilling.remove(seq)
            sched.finish(seq, FinishReason.ERROR)
        return True

    def _fail_all(self) -> None:
        assert self.scheduler is not None
        for seq in list(self.scheduler.running) + list(
            self.scheduler.prefilling
        ) + list(self.scheduler.waiting):
            self.scheduler.finish(seq, FinishReason.ERROR)
        self.scheduler.running.clear()
        self.scheduler.prefilling.clear()
        self.scheduler.waiting.clear()

    # ------------------------------------------------------------------
    # Graceful drain (runtime/drain.py; docs/robustness.md)
    # ------------------------------------------------------------------
    def begin_drain(self) -> None:
        """Thread-safe: stop admitting and hand off in-flight streams.

        submit() rejects from the next call; the step loop finishes
        every MIGRATABLE sequence with ``FinishReason.MIGRATE`` at the
        next step boundary, which the routers turn into a proactive
        resume on a healthy peer. Ineligible streams (guided,
        penalty-sampling, opted out — the same set migration.resumable
        refuses) keep running until they complete or the drain
        deadline's reactive fallback ends them."""
        self._draining = True
        self._wake.set()

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drain_migrated(self) -> int:
        """Streams handed off with MIGRATE since begin_drain() (feeds
        dynamo_drain_streams_migrated_total)."""
        return self._drain_migrated

    def active_streams(self) -> int:
        """Sequences still attached to a client stream (advisory; the
        drain coordinator polls this toward zero)."""
        sched = self.scheduler
        if sched is None:
            return 0
        return sched.num_running + sched.num_waiting

    @staticmethod
    def _drain_migratable(request) -> bool:
        """Engine-side mirror of migration.resumable()'s *request*
        eligibility: only streams the router could actually resume get
        the MIGRATE handoff — the rest finish naturally or ride the
        deadline fallback."""
        if getattr(request, "migration", None) is False:
            return False
        if getattr(request, "guided", None) is not None:
            return False
        sampling = getattr(request, "sampling", None)
        if sampling is not None and getattr(sampling, "needs_penalties", False):
            return False
        return True

    def _migrate_eligible(self) -> None:
        """Engine thread: finish every migratable sequence with MIGRATE.
        Runs each loop iteration while draining, so a submit that raced
        the flag is swept on the next boundary too."""
        assert self.scheduler is not None
        sched = self.scheduler
        for pool in (sched.running, sched.prefilling, sched.waiting):
            for seq in list(pool):
                if not self._drain_migratable(seq.request):
                    continue
                try:
                    if seq in pool:
                        pool.remove(seq)
                    sched.finish(seq, FinishReason.MIGRATE)
                    self._drain_migrated += 1
                except Exception:
                    # a failed handoff must not take the engine thread
                    # down mid-drain: this stream rides the deadline and
                    # the reactive resume path instead
                    log.exception(
                        "drain handoff failed for %s", seq.request_id
                    )

    # ------------------------------------------------------------------
    # Async interface
    # ------------------------------------------------------------------
    def submit(
        self, request: PreprocessedRequest, context: Context
    ) -> asyncio.Queue:
        """Thread-safe submit; returns the asyncio output queue."""
        assert self._loop is not None
        if self._draining:
            # routers stop placing here the moment the DRAINING flag
            # lands in discovery; a submit that still arrives (flag
            # propagation race) must fail fast so the caller's failover
            # re-dispatches it to a healthy peer
            raise RuntimeError("engine is draining; not admitting new requests")
        out: asyncio.Queue = asyncio.Queue()
        loop = self._loop

        def emit(item) -> None:
            loop.call_soon_threadsafe(out.put_nowait, item)

        # Validate HERE, where a bad request errors on its own: garbage
        # reaching the jitted step would fail or corrupt the whole batch
        # (out-of-range ids silently clamp in the embedding gather).
        assert self.model_config is not None
        if not request.token_ids:
            raise ValueError("empty token_ids")
        V = self.model_config.vocab_size
        ids = np.asarray(request.token_ids)
        if not np.issubdtype(ids.dtype, np.integer):
            raise ValueError("token_ids must be integers")
        if ids.min() < 0 or ids.max() >= V:
            raise ValueError(
                f"token id out of range [0, {V}): "
                f"{int(ids.min())}..{int(ids.max())}"
            )
        mm_segments = []
        salt = DEFAULT_SALT
        if request.mm_embeds:
            from dynamo_tpu.multimodal.embeds import unpack_segments

            if self._pp > 1:
                raise ValueError(
                    "multimodal embedding injection is not supported with "
                    "pipeline parallelism yet"
                )

            # Validate HERE, where a bad request errors on its own — a
            # malformed shape surfacing inside the jitted step would
            # fail every in-flight request (_fail_all).
            mm_segments = unpack_segments(request.mm_embeds)
            assert self.model_config is not None
            D = self.model_config.hidden_size
            for offset, arr in mm_segments:
                if arr.shape[1] != D:
                    raise ValueError(
                        f"mm embedding dim {arr.shape[1]} != model hidden {D}"
                    )
                if not (0 <= offset and offset + arr.shape[0] <= len(request.token_ids)):
                    raise ValueError(
                        f"mm segment [{offset},+{arr.shape[0]}) outside prompt "
                        f"of {len(request.token_ids)} tokens"
                    )
            # Salt the block hashes with the embedding content: two
            # prompts with identical placeholder tokens but different
            # images must NOT share prefix-cache KV (and must not match
            # text-only requests either).
            h = hashlib.blake2b(digest_size=8)
            for offset, arr in mm_segments:
                h.update(offset.to_bytes(8, "little"))
                h.update(np.ascontiguousarray(arr).tobytes())
            salt = DEFAULT_SALT ^ int.from_bytes(h.digest(), "little")
        guided_automaton = None
        if request.guided is not None:
            # guided decoding (docs/guided_decoding.md): compile (or
            # LRU-fetch) the token automaton HERE, on the submit thread
            # — a bad schema fails this request alone, and a compile
            # never stalls the engine thread mid-step
            if self.config.decode_steps != 1:
                raise ValueError(
                    "guided decoding requires decode_steps == 1 (the "
                    "allow-mask advances on host per committed token; "
                    "fused windows sample K tokens per dispatch)"
                )
            if request.resume_offset:
                # a migrated request's generated tokens are folded into
                # token_ids with no boundary marker — the automaton
                # cursor cannot be reconstructed (the router refuses to
                # resume guided requests for the same reason)
                raise ValueError(
                    "guided requests cannot resume mid-stream"
                )
            guided_automaton = self._guided_automaton(request.guided)
            GUIDED_REQUESTS.labels(guided_automaton.kind).inc()
        seq = Sequence(
            request=request,
            tokens=TokenBlockSequence(
                request.token_ids, block_size=self.config.block_size, salt=salt
            ),
            emit=emit,
            is_cancelled=lambda: context.is_stopped,
            mm_segments=mm_segments,
            autopsy_rid=getattr(context, "id", "") or "",
        )
        if guided_automaton is not None:
            from dynamo_tpu.guided import GuidedState

            seq.guided_state = GuidedState(guided_automaton)
        # lifecycle stamps + trace link: _emit_finish turns these into
        # engine.{queue_wait,prefill,decode} spans (cheap plain fields
        # when tracing is off)
        seq.t_submit = time.monotonic()
        seq.t_submit_wall = time.time()
        seq.trace = context.trace_context()
        if context.deadline is not None:
            # same-process monotonic instant: the scheduler reaps the
            # sequence (and frees its KV blocks) once this passes
            seq.deadline = context.deadline
        self._incoming.put(seq)
        self._wake.set()
        return out

    def as_async_engine(self) -> "JaxEngineAdapter":
        return JaxEngineAdapter(self)

    def stats(self) -> ForwardPassMetrics:
        sched, alloc = self.scheduler, self.allocator
        assert sched is not None and alloc is not None
        # cached rollup (refreshed every GAUGE_EVERY steps): stats()
        # feeds admission control per HTTP request and the metrics
        # publisher per interval — neither may pay an O(window) pass
        attr = self.attribution.summary_cached()
        return ForwardPassMetrics(
            request_active_slots=sched.num_running,
            request_total_slots=self.config.max_batch_size,
            kv_active_blocks=alloc.num_blocks - 1 - alloc.num_free,
            kv_total_blocks=alloc.num_blocks - 1,
            num_requests_waiting=sched.num_waiting,
            gpu_cache_usage_perc=alloc.usage,
            gpu_prefix_cache_hit_rate=(
                sched.prefix_hits / sched.prefix_queries
                if sched.prefix_queries
                else 0.0
            ),
            slo_enabled=self.slo.config.enabled,
            slo_attainment=self.slo.attainment,
            goodput_tokens_total=self.slo.goodput_tokens,
            roofline_frac=(
                attr["roofline_frac"]
                if attr["roofline_frac"] is not None else -1.0
            ),
            top_loss_bucket=attr["top_loss_bucket"],
        )

    def attribution_state(self) -> dict:
        """Provider behind ``/debug/attribution``: the ledger window +
        recent per-step rows and the black-box capture stats."""
        # gauges refresh here too, so /metrics scraped next to the
        # endpoint agrees with the snapshot (mirrors _update_pool_gauges)
        self.attribution.refresh_gauges()
        return {
            "attribution": self.attribution.snapshot(),
            "blackbox": self.blackbox.stats(),
        }

    def debug_state(self) -> dict:
        """Live snapshot for ``/debug/state`` (telemetry/debug.py):
        scheduler slots, KV block pool occupancy/fragmentation, prefill
        queue depth, in-flight requests, recent flight-recorder steps,
        SLO attainment, HBM accounting.

        Reads live structures WITHOUT stopping the engine thread — a
        snapshot that waited for the step loop would hang exactly when
        the loop is stuck, which is when you need it. Values may be a
        step apart from each other; every field is advisory."""
        sched, alloc = self.scheduler, self.allocator
        out: dict = {
            "model": self.config.model_name,
            "running": self._running,
            "max_batch_size": self.config.max_batch_size,
            "decode_steps": self.config.decode_steps,
            "block_size": self.config.block_size,
            "tokens_generated_total": self.tokens_generated_total,
            # graceful drain flag ("top" renders the DRAIN state from
            # this; absent on older builds → the '-' rule)
            "draining": self._draining,
        }
        if sched is not None:
            def req_row(seq) -> dict:
                return {
                    "request_id": seq.request_id,
                    "state": str(seq.state.value),
                    "prompt_tokens": len(seq.request.token_ids),
                    "generated": seq.generated,
                    "computed": seq.num_computed,
                    "blocks": len(seq.block_table),
                }

            running = list(sched.running)
            prefilling = list(sched.prefilling)
            waiting = list(sched.waiting)
            out["scheduler"] = {
                "running": len(running),
                "prefilling": len(prefilling),
                "waiting": len(waiting),
                "queue_depth": len(waiting) + len(prefilling),
                "preemptions": sched.preemptions,
                "prefix_queries": sched.prefix_queries,
                "prefix_hits": sched.prefix_hits,
                # bounded: the fleet view needs the shape of the batch,
                # not one row per request at max_batch_size=256
                "requests": [
                    req_row(s) for s in (running + prefilling + waiting)[:64]
                ],
            }
        if alloc is not None:
            self._update_pool_gauges()
            usable = alloc.num_blocks - 1
            free = alloc.num_free
            cached_free = alloc.num_cached_free
            out["kv_pool"] = {
                "total_blocks": usable,
                "active_blocks": usable - free,
                "free_blocks": free,
                "cached_free_blocks": cached_free,
                "usage": alloc.usage,
                # fraction of the free pool still holding reusable
                # content-addressed KV (the prefix cache's evictable
                # working set — high is GOOD until allocation pressure
                # starts evicting it)
                "cached_free_fraction": (cached_free / free) if free else 0.0,
            }
        out["hbm"] = self.hbm.refresh()
        out["slo"] = self.slo.stats()
        # overlapped-pipeline health (docs/performance.md): device
        # idle-gap accounting — read device_idle_frac as
        # idle_gap_s_total growth over wall time under load
        out["overlap"] = {
            "enabled": self.config.overlap,
            **self.overlap.stats(),
        }
        # serve-phase compile fence (DYN_COMPILE_FENCE): mode + lifetime
        # escalation count, so `top`//debug/state show whether a fenced
        # worker has compiled anything mid-serve
        out["compile_fence"] = compile_fence.stats()
        out["transfer_fence"] = transfer_fence.stats()
        # perf attribution (telemetry/attribution.py): where the decode
        # window's wall time went, the live roofline fraction, and the
        # black-box capture state — what `top`'s ROOF%/LOSS columns read
        out["attribution"] = self.attribution.snapshot()
        out["blackbox"] = self.blackbox.stats()
        if self.recorder is not None:
            out["flight_recorder"] = self.recorder.stats()
            out["recent_steps"] = self.recorder.snapshot(32)
        if self._drafter is not None:
            hid = self.spec_draft_hidden_s_total
            exp = self.spec_draft_exposed_s_total
            out["spec"] = {
                "drafter": getattr(self._drafter, "kind", "?"),
                "proposed_total": self.spec_proposed_total,
                "accepted_total": self.spec_accepted_total,
                # overlapped spec pipeline health (docs/
                # speculative_decoding.md): how much host draft wall
                # time the pipeline hid under device execution, and how
                # often the optimistic pre-draft matched the realized
                # tail (a miss re-drafts on the exposed critical path)
                "pipelined": self._overlap_ok(),
                "pipeline_steps": self.spec_pipeline_steps,
                "draft_hidden_s": round(hid, 6),
                "draft_exposed_s": round(exp, 6),
                "draft_hidden_frac": (
                    round(hid / (hid + exp), 4) if (hid + exp) > 0 else 0.0
                ),
                "predraft_hits": self.spec_predraft_hits,
                "predraft_misses": self.spec_predraft_misses,
            }
        if sched is not None and alloc is not None:
            out["load"] = self.stats().to_dict()
        return out

    async def wait_for_state(
        self, predicate: Callable[["JaxEngine"], bool],
        timeout: float = 30.0, poll_s: float = 0.005,
    ) -> None:
        """Await an engine-state condition (e.g. ``lambda e:
        e.scheduler.num_running >= 3``) instead of sleeping a guessed
        wall-clock interval — the injectable-event replacement for
        timing-based test choreography. Raises asyncio.TimeoutError."""
        deadline = time.monotonic() + timeout
        last_exc: Optional[BaseException] = None
        while True:
            try:
                if predicate(self):
                    return
                last_exc = None
            except Exception as exc:
                # tolerated (scheduler mid-mutation races) but REMEMBERED:
                # a predicate that raises every poll (typo'd attribute)
                # must surface its error, not a bare timeout
                last_exc = exc
            if time.monotonic() >= deadline:
                detail = (
                    f"; predicate raised every poll: {last_exc!r}"
                    if last_exc is not None else ""
                )
                raise asyncio.TimeoutError(
                    f"engine state predicate not met within {timeout}s"
                    + detail
                )
            await asyncio.sleep(poll_s)

    async def shutdown(self) -> None:
        self._running = False  # dynalint: handoff=stop-flag — one-way bool, each side only ever writes False; readers poll per step/await
        self._wake.set()
        if self._debug_name is not None:
            unregister_debug_provider(self._debug_name, self.debug_state)
            unregister_attribution_provider(
                self._debug_name, self.attribution_state
            )
            self._debug_name = None
        from dynamo_tpu.models.llama import (
            get_attention_mesh,
            set_attention_mesh,
        )

        if get_attention_mesh() is self.mesh:
            set_attention_mesh(None)  # don't leak into later engines
        if self._thread is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, functools.partial(self._thread.join, timeout=10)
            )
        # let an in-flight black-box bundle finish writing — its
        # forensics are the reason the process is probably going down
        await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self.blackbox.flush, 5.0)
        )
        if self._mh_broadcast is not None:
            # release follower ranks blocked on the next control
            # broadcast (strictly after the step thread has joined, so
            # STOP orders after every step announcement)
            await asyncio.get_running_loop().run_in_executor(
                None, self._mh_broadcast.announce_stop
            )
        if self.kvbm is not None:
            self.kvbm.close()


class JaxEngineAdapter(AsyncEngine):
    """AsyncEngine facade: PreprocessedRequest in → LLMEngineOutput stream."""

    def __init__(self, engine: JaxEngine):
        self.engine = engine

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.model_validate(request)
        out = self.engine.submit(request, context)
        while True:
            item = await out.get()
            if item is None:
                return
            yield item
            if isinstance(item, LLMEngineOutput) and item.is_final:
                return

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)
