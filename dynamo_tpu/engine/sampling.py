"""On-device batched sampling.

One jitted function samples the whole batch: greedy and
temperature/top-k/top-p/min-p paths are blended with `jnp.where` so a
mixed batch compiles once (no per-request Python branching —
XLA-friendly).

Full sampling surface (reference: lib/llm/src/protocols/common.rs
:263-309 SamplingOptions — the reference carries these into its vLLM
engines; here they execute on device):

- temperature / top_k / top_p / min_p / seed
- logit_bias: sparse per-slot (token id, bias) pairs scatter-added into
  the logits (OpenAI semantics) — base path, always compiled.
- frequency/presence/repetition penalties: need per-slot token-count
  state, so they ride a SEPARATELY-COMPILED step variant whose
  SamplingBatch carries sparse count tables ([B, N] ids + counts,
  bucketed). Inside a fused K-step decode window the counts are
  scattered into a dense [B, V] table once, carried through the scan,
  and updated on device after every sampled token — so window outputs
  match K single steps exactly.

Semantics follow vLLM (the reference's serving engine): frequency and
presence penalties count GENERATED tokens only; repetition penalty
applies to prompt + generated tokens (HF-style divide/multiply).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.protocols.common import SamplingOptions

NEG_INF = -1e30

# Sparse tables are pinned to ONE width each (not bucketed): a width
# change is a new jit signature, and a mid-serve AOT compile over a
# chip tunnel is a multi-minute TTFT stall (ADVICE r3: the bucketed
# widths were reachable by any logit_bias request with >4 entries).
# BIAS_W covers OpenAI's 300-entry logit_bias cap outright; COUNT_W
# truncates penalty token-count tables at 4096 distinct ids (documented
# bound — beyond it the least-recently-sorted ids stop contributing).
BIAS_W = 512
COUNT_W = 4096
# top-logprob alternatives returned by the "top_lp" step variant
# (OpenAI caps top_logprobs at 20)
TOPLP_N = 20

# Back-compat aliases (tests/benchmarks referenced the bucket lists)
BIAS_BUCKETS = [BIAS_W]
COUNT_BUCKETS = [COUNT_W]


@dataclass
class SamplingBatch:
    """Host-side per-slot sampling params, uploaded each step.

    ``arrays`` is a flat dict of numpy arrays (a jit-friendly pytree):

    base keys (always present):
      temperature [B] f32 (0 = greedy), top_k [B] i32 (0 = off),
      top_p [B] f32 (1 = off), min_p [B] f32 (0 = off), seeds [B] u32

    bias keys (only when a request in the batch carries logit_bias —
    presence selects the bias jit variant):
      bias_ids [B, BIAS_W] i32, bias_vals [B, BIAS_W] f32 (pad id 0/0)

    penalty keys (only when a request in the batch uses them — selects
    the penalty-variant compiled step):
      freq_pen [B] f32, pres_pen [B] f32, rep_pen [B] f32 (1 = off),
      gen_ids [B, NP] i32 + gen_counts [B, NP] f32 (generated tokens),
      prompt_ids [B, NR] i32 + prompt_counts [B, NR] f32 (presence=1)

    guided key (only when a request in the batch carries a guided
      constraint — selects the masked jit variant;
      docs/guided_decoding.md):
      allow_mask [B, V_pad] bool (unguided rows all-True); the spec
      verify step carries [B, S, V_pad] instead (per fed position)
    """

    arrays: dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def temperature(self) -> np.ndarray:
        return self.arrays["temperature"]

    @property
    def seeds(self) -> np.ndarray:
        return self.arrays["seeds"]

    @property
    def has_penalties(self) -> bool:
        return "rep_pen" in self.arrays

    @property
    def has_bias(self) -> bool:
        return "bias_ids" in self.arrays

    @property
    def has_toplp(self) -> bool:
        return "top_lp_n" in self.arrays

    @property
    def has_guided(self) -> bool:
        return "allow_mask" in self.arrays

    @classmethod
    def from_options(
        cls,
        opts: list[SamplingOptions],
        step_seeds: list[int],
        gen_token_counts: Optional[list[dict[int, int]]] = None,
        prompt_token_ids: Optional[list[np.ndarray]] = None,
        top_lp: Optional[list[int]] = None,
    ) -> "SamplingBatch":
        """``gen_token_counts``/``prompt_token_ids`` (parallel to opts)
        supply the per-sequence token state the penalty path needs; they
        may be None when no option in the batch needs penalties.
        ``top_lp`` (per-slot requested alternative counts, any > 0)
        selects the top-logprobs step variant: sample() additionally
        returns the TOPLP_N most likely ids + logprobs per slot."""
        n = len(opts)
        a: dict[str, np.ndarray] = {
            "temperature": np.zeros((n,), np.float32),
            "top_k": np.zeros((n,), np.int32),
            "top_p": np.ones((n,), np.float32),
            "min_p": np.zeros((n,), np.float32),
            # host python list -> ndarray; no device array involved
            "seeds": np.asarray(step_seeds, np.uint32),  # dynalint: disable=transitive-host-sync-in-step-loop — host-list conversion
        }
        for i, o in enumerate(opts):
            if not o.use_greedy and o.temperature is not None:
                a["temperature"][i] = max(o.temperature, 1e-4)
            elif not o.use_greedy:
                a["temperature"][i] = 1.0
            if o.top_k:
                a["top_k"][i] = o.top_k
            if o.top_p is not None:
                a["top_p"][i] = o.top_p
            if o.min_p:
                a["min_p"][i] = o.min_p
        # sparse logit bias: PRESENCE-KEYED like the penalty tables —
        # batches with no bias (approximately all of them) ship nothing
        # and select the bias-free jit variant; bias batches carry one
        # fixed BIAS_W width (OpenAI caps logit_bias at 300 entries, so
        # nothing real ever truncates, and one width = one signature).
        if any(o.logit_bias for o in opts):
            a["bias_ids"] = np.zeros((n, BIAS_W), np.int32)
            a["bias_vals"] = np.zeros((n, BIAS_W), np.float32)
            for i, o in enumerate(opts):
                items = sorted((o.logit_bias or {}).items())[:BIAS_W]
                for j, (tok, v) in enumerate(items):
                    a["bias_ids"][i, j] = tok
                    a["bias_vals"][i, j] = v
        if any(o.needs_penalties for o in opts):
            a.update(
                cls._penalty_arrays(opts, gen_token_counts, prompt_token_ids)
            )
        if top_lp is not None and any(k > 0 for k in top_lp):
            # host python list -> ndarray; no device array involved
            a["top_lp_n"] = np.asarray(  # dynalint: disable=transitive-host-sync-in-step-loop — host-list conversion
                [min(max(k, 0), TOPLP_N) for k in top_lp], np.int32
            )
        return cls(a)

    @staticmethod
    def _penalty_arrays(
        opts: list[SamplingOptions],
        gen_token_counts: Optional[list[dict[int, int]]],
        prompt_token_ids: Optional[list[np.ndarray]],
    ) -> dict[str, np.ndarray]:
        n = len(opts)
        gen_token_counts = gen_token_counts or [{} for _ in opts]
        prompt_token_ids = prompt_token_ids or [
            np.zeros((0,), np.int32) for _ in opts
        ]
        a: dict[str, np.ndarray] = {
            "freq_pen": np.zeros((n,), np.float32),
            "pres_pen": np.zeros((n,), np.float32),
            "rep_pen": np.ones((n,), np.float32),
        }
        for i, o in enumerate(opts):
            if o.frequency_penalty:
                a["freq_pen"][i] = o.frequency_penalty
            if o.presence_penalty:
                a["pres_pen"][i] = o.presence_penalty
            if o.repetition_penalty:
                a["rep_pen"][i] = o.repetition_penalty
        # fixed COUNT_W width (one compiled penalty variant — see the
        # BIAS_W/COUNT_W note at the top of the module)
        a["gen_ids"] = np.zeros((n, COUNT_W), np.int32)
        a["gen_counts"] = np.zeros((n, COUNT_W), np.float32)
        a["prompt_ids"] = np.zeros((n, COUNT_W), np.int32)
        a["prompt_counts"] = np.zeros((n, COUNT_W), np.float32)
        for i, counts in enumerate(gen_token_counts):
            for j, (tok, c) in enumerate(sorted(counts.items())[:COUNT_W]):
                a["gen_ids"][i, j] = tok
                a["gen_counts"][i, j] = c
        for i, toks in enumerate(prompt_token_ids):
            # host python list -> ndarray; no device array involved
            t = np.asarray(toks, np.int32)[:COUNT_W]  # dynalint: disable=transitive-host-sync-in-step-loop — host-list conversion
            a["prompt_ids"][i, : len(t)] = t
            a["prompt_counts"][i, : len(t)] = 1.0
        return a


# ---------------------------------------------------------------------------
# Device side
# ---------------------------------------------------------------------------


def dense_gen_counts(s: dict, vocab: int) -> jax.Array:
    """Scatter the sparse generated-token table into a dense [B, V] f32
    (the fused-window carry: updated on device after each sampled
    token)."""
    B = s["gen_ids"].shape[0]
    rows = jnp.arange(B)[:, None]
    return (
        jnp.zeros((B, vocab), jnp.float32).at[rows, s["gen_ids"]].add(
            s["gen_counts"]
        )
    )


def dense_prompt_presence(s: dict, vocab: int) -> jax.Array:
    """Dense [B, V] f32 presence (>=1 where the token occurs in the
    prompt) — constant across a fused window."""
    B = s["prompt_ids"].shape[0]
    rows = jnp.arange(B)[:, None]
    return (
        jnp.zeros((B, vocab), jnp.float32).at[rows, s["prompt_ids"]].add(
            s["prompt_counts"]
        )
    )


def apply_penalties(
    logits: jax.Array,  # [B, V] f32
    s: dict,
    gen_dense: jax.Array,  # [B, V] f32 generated-token counts
    prompt_dense: jax.Array,  # [B, V] f32 prompt presence
) -> jax.Array:
    """HF-style repetition penalty over prompt+generated, then OpenAI
    frequency/presence over generated only (vLLM order)."""
    rp = s["rep_pen"][:, None]
    seen_any = (gen_dense + prompt_dense) > 0
    rep = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen_any, rep, logits)
    logits = (
        logits
        - s["freq_pen"][:, None] * gen_dense
        - s["pres_pen"][:, None] * (gen_dense > 0)
    )
    return logits


def filter_keep_mask(
    vals: jax.Array,  # [..., KF] descending top-KF slice of scaled logits
    lse: jax.Array,  # [..., 1] full-vocab logsumexp of the scaled logits
    top_k: jax.Array,  # broadcastable against vals[..., :1]
    top_p: jax.Array,
    min_p: jax.Array,
    vocab: int,
) -> jax.Array:
    """Boolean keep mask implementing top-k/top-p/min-p shaping over a
    descending top-KF logit slice. ONE definition shared by sample()'s
    filtered path and the speculative verifier (spec/verify.py) — the
    two must agree exactly or speculative acceptance would target a
    different distribution than non-speculative sampling draws from.

    Probabilities are normalized against the FULL vocab (via ``lse``),
    so the top_p cutoff is exact whenever it falls inside the slice; the
    only approximation is truncating ultra-flat tails (or top_k > KF) to
    the KF most likely tokens."""
    KF = vals.shape[-1]
    ranks = jnp.arange(KF, dtype=jnp.int32)
    k = jnp.where(top_k > 0, top_k, vocab)[..., None]
    k_mask = ranks < k
    sprobs = jnp.exp(vals - lse)  # true full-vocab probabilities
    cum = jnp.cumsum(sprobs, axis=-1)
    p_mask = (cum - sprobs) < top_p[..., None]
    m_mask = sprobs >= (min_p[..., None] * sprobs[..., :1])
    return k_mask & p_mask & m_mask


def sample(
    logits: jax.Array,  # [B, V] f32
    s: dict,  # SamplingBatch.arrays (device-side pytree)
    gen_dense: Optional[jax.Array] = None,  # [B, V] carried counts
    prompt_dense: Optional[jax.Array] = None,
) -> tuple[jax.Array, ...]:
    """Returns (next_tokens [B] i32, logprobs_of_chosen [B] f32); when
    ``s`` carries the "top_lp_n" marker (top-logprobs step variant),
    additionally (top_ids [B, TOPLP_N] i32, top_lps [B, TOPLP_N] f32) —
    the most likely alternatives of the SAME post-bias/penalty
    distribution the chosen logprob is measured on.

    The penalty tables (``gen_dense``/``prompt_dense``) are passed
    explicitly by fused-window callers so the carry survives across
    steps; single-step callers omit them and they are built from the
    sparse tables when present.
    """
    B, V = logits.shape
    rows = jnp.arange(B)[:, None]
    # logit bias first (OpenAI: bias applies before sampling of any
    # kind). Presence-keyed: bias-free batches (the common case) select
    # a variant without the scatter at all.
    if "bias_ids" in s:
        logits = logits.at[rows, s["bias_ids"]].add(s["bias_vals"])
    if "rep_pen" in s:
        if gen_dense is None:
            gen_dense = dense_gen_counts(s, V)
        if prompt_dense is None:
            prompt_dense = dense_prompt_presence(s, V)
        logits = apply_penalties(logits, s, gen_dense, prompt_dense)
    if "allow_mask" in s:
        # guided decoding (docs/guided_decoding.md): disallowed tokens
        # drop to NEG_INF BEFORE the greedy argmax, the filter pipeline,
        # and the logprob computation below, so greedy, seeded sampling,
        # top-k/top-p/min-p, and returned logprobs all see the SAME
        # constrained distribution. Presence-keyed like bias/penalties:
        # unguided batches select the mask-free jit variant.
        logits = jnp.where(s["allow_mask"], logits, NEG_INF)

    temperature, top_k, top_p, min_p, seeds = (
        s["temperature"], s["top_k"], s["top_p"], s["min_p"], s["seeds"]
    )
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # rows that need top-k/top-p/min-p shaping (vs free sampling)
    need_filter = (top_k > 0) | (top_p < 1.0) | (min_p > 0.0)

    def sampled_path(_) -> jax.Array:
        # EXACT free sampling via the gumbel-max trick — NO vocab sort.
        # A full [B, V] argsort per step was ~60% of a fused decode
        # step at V=128k (measured 2.5 s vs 0.95 s windows on v5e) and
        # the OpenAI default (temperature=1, no filters) hits it on
        # every HTTP request.
        temp = jnp.maximum(temperature, 1e-4)[:, None]
        scaled = logits / temp
        keys = jax.vmap(jax.random.key)(seeds)
        gumbel = jax.vmap(
            lambda key, shape=(V,): jax.random.gumbel(key, shape, jnp.float32)
        )(keys)
        free_tok = jnp.argmax(scaled + gumbel, axis=-1).astype(jnp.int32)

        def filtered(_) -> jax.Array:
            # top-k / top-p / min-p shaping on the top-KF slice only.
            # Probabilities are normalized against the FULL vocab
            # (logsumexp over scaled — no sort needed), so the top_p
            # cutoff is exact whenever it falls inside the slice; the
            # only approximation is truncating ultra-flat tails (or
            # top_k > KF) to the KF most likely tokens.
            KF = min(128, V)
            vals, idx = jax.lax.top_k(scaled, KF)  # [B, KF] descending
            lse = jax.nn.logsumexp(scaled, axis=-1, keepdims=True)
            keep = filter_keep_mask(vals, lse, top_k, top_p, min_p, V)
            fvals = jnp.where(keep, vals, NEG_INF)
            g = jnp.take_along_axis(gumbel, idx, axis=-1)
            choice = jnp.argmax(fvals + g, axis=-1)
            return jnp.take_along_axis(idx, choice[:, None], axis=-1)[
                :, 0
            ].astype(jnp.int32)

        # the top-k machinery only runs when some row filters
        sampled_tok = jax.lax.cond(
            jnp.any(need_filter & (temperature > 0.0)),
            filtered,
            lambda _: free_tok,
            None,
        )
        sampled_tok = jnp.where(need_filter, sampled_tok, free_tok)
        is_greedy = temperature <= 0.0
        return jnp.where(is_greedy, greedy_tok, sampled_tok)

    # skip sampling entirely when the whole batch decodes greedily
    # (runtime-dependent branch — both sides compiled, one executes)
    next_tok = jax.lax.cond(
        jnp.all(temperature <= 0.0), lambda _: greedy_tok, sampled_path, None
    )
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    chosen_lp = jnp.take_along_axis(logprobs, next_tok[:, None], axis=-1)[:, 0]
    if "top_lp_n" in s:
        top_lps, top_ids = jax.lax.top_k(logprobs, min(TOPLP_N, V))
        if top_ids.shape[-1] < TOPLP_N:  # tiny test vocabs
            pad = TOPLP_N - top_ids.shape[-1]
            top_ids = jnp.pad(top_ids, ((0, 0), (0, pad)))
            top_lps = jnp.pad(
                top_lps, ((0, 0), (0, pad)), constant_values=NEG_INF
            )
        return next_tok, chosen_lp, top_ids.astype(jnp.int32), top_lps
    return next_tok, chosen_lp


def reference_sample_numpy(
    logits: np.ndarray, s: dict, row: int
) -> np.ndarray:
    """Pure-numpy reference of the logits transform for row ``row`` —
    bias + penalties + filtering masks (no RNG; used by parity tests to
    check the device pipeline's distribution shaping)."""
    x = logits.astype(np.float64).copy()
    if "bias_ids" in s:
        for tok, v in zip(s["bias_ids"][row], s["bias_vals"][row]):
            x[int(tok)] += float(v)
    if "rep_pen" in s:
        gen = np.zeros_like(x)
        for tok, c in zip(s["gen_ids"][row], s["gen_counts"][row]):
            gen[int(tok)] += float(c)
        prompt = np.zeros_like(x)
        for tok, c in zip(s["prompt_ids"][row], s["prompt_counts"][row]):
            prompt[int(tok)] += float(c)
        rp = float(s["rep_pen"][row])
        seen = (gen + prompt) > 0
        x = np.where(seen, np.where(x > 0, x / rp, x * rp), x)
        x = x - float(s["freq_pen"][row]) * gen
        x = x - float(s["pres_pen"][row]) * (gen > 0)
    if "allow_mask" in s:
        x = np.where(s["allow_mask"][row], x, NEG_INF)
    return x
