"""On-device batched sampling.

One jitted function samples the whole batch: greedy and
temperature/top-k/top-p paths are blended with `jnp.where` so a mixed
batch compiles once (no per-request Python branching — XLA-friendly).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.protocols.common import SamplingOptions

NEG_INF = -1e30


@dataclass
class SamplingBatch:
    """Host-side per-slot sampling params, uploaded each step."""

    temperature: np.ndarray  # [B] f32 (0 = greedy)
    top_k: np.ndarray  # [B] i32 (0 = off)
    top_p: np.ndarray  # [B] f32 (1.0 = off)
    seeds: np.ndarray  # [B] u32 per-slot RNG streams

    @classmethod
    def from_options(cls, opts: list[SamplingOptions], step_seeds: list[int]) -> "SamplingBatch":
        n = len(opts)
        temp = np.zeros((n,), np.float32)
        top_k = np.zeros((n,), np.int32)
        top_p = np.ones((n,), np.float32)
        seeds = np.asarray(step_seeds, np.uint32)
        for i, o in enumerate(opts):
            if not o.use_greedy and o.temperature is not None:
                temp[i] = max(o.temperature, 1e-4)
            elif not o.use_greedy:
                temp[i] = 1.0
            if o.top_k:
                top_k[i] = o.top_k
            if o.top_p is not None:
                top_p[i] = o.top_p
        return cls(temp, top_k, top_p, seeds)


def sample(
    logits: jax.Array,  # [B, V] f32
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B]
    top_p: jax.Array,  # [B]
    seeds: jax.Array,  # [B] u32
) -> tuple[jax.Array, jax.Array]:
    """Returns (next_tokens [B] i32, logprobs_of_chosen [B] f32)."""
    B, V = logits.shape
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled_path(_) -> jax.Array:
        # top-k / top-p filtering on sorted logits
        temp = jnp.maximum(temperature, 1e-4)[:, None]
        scaled = logits / temp
        sort_idx = jnp.argsort(-scaled, axis=-1)  # descending
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        ranks = jnp.arange(V, dtype=jnp.int32)[None, :]
        # top-k mask (0 = disabled)
        k = jnp.where(top_k > 0, top_k, V)[:, None]
        k_mask = ranks < k
        # top-p mask on the sorted distribution (always keep rank 0)
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumprobs = jnp.cumsum(sorted_probs, axis=-1)
        p_mask = (cumprobs - sorted_probs) < top_p[:, None]
        keep = k_mask & p_mask
        filtered = jnp.where(keep, sorted_logits, NEG_INF)
        # per-slot independent RNG streams
        keys = jax.vmap(jax.random.key)(seeds)
        gumbel = jax.vmap(
            lambda key, shape=(V,): jax.random.gumbel(key, shape, jnp.float32)
        )(keys)
        choice_sorted = jnp.argmax(filtered + gumbel, axis=-1)
        sampled_tok = jnp.take_along_axis(
            sort_idx, choice_sorted[:, None], axis=-1
        )[:, 0].astype(jnp.int32)
        is_greedy = temperature <= 0.0
        return jnp.where(is_greedy, greedy_tok, sampled_tok)

    # the sort/gumbel machinery is ~30% of a fused decode step: skip it
    # entirely when the whole batch decodes greedily (runtime-dependent
    # branch — both sides are compiled, only one executes)
    next_tok = jax.lax.cond(
        jnp.all(temperature <= 0.0), lambda _: greedy_tok, sampled_path, None
    )
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    chosen_lp = jnp.take_along_axis(logprobs, next_tok[:, None], axis=-1)[:, 0]
    return next_tok, chosen_lp
