"""Continuous-batching scheduler: admission, chunked prefill, decode batches.

The reference relies on vLLM's scheduler for this (reference: SURVEY.md §1
L3); here it is native and shaped for XLA's compilation model:

- every device step has **bucketed static shapes** (batch, chunk length,
  block-table width are rounded up to a small set of sizes) so the jitted
  step function compiles a handful of variants and then never recompiles;
- prefill is **chunked** (prefill_chunk_size) so long prompts can't starve
  decode; one prefill chunk or one decode batch per engine step;
- admission is capacity-checked against the block allocator, with
  vLLM-style recompute preemption: if decode can't grow a sequence, the
  youngest sequence is rolled back to the waiting queue and its blocks
  freed.

Pure host-side logic — fully unit-testable without a device.
"""

from __future__ import annotations

import enum
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from dynamo_tpu.engine.allocator import BlockAllocator, NoBlocksError
from dynamo_tpu.protocols.common import FinishReason, PreprocessedRequest
from dynamo_tpu.telemetry import autopsy
from dynamo_tpu.telemetry.instruments import (
    DEADLINE_EXPIRED,
    ENGINE_PREEMPTIONS,
    ENGINE_QUEUE_WAIT,
)
from dynamo_tpu.tokens import TokenBlockSequence

log = logging.getLogger("dynamo_tpu.engine.scheduler")


from dynamo_tpu.utils.bucketing import next_bucket  # noqa: F401 (re-export)


class SeqState(str, enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    RUNNING = "running"
    FINISHED = "finished"


@dataclass
class Sequence:
    request: PreprocessedRequest
    tokens: TokenBlockSequence
    state: SeqState = SeqState.WAITING
    block_table: list[int] = field(default_factory=list)
    num_computed: int = 0  # tokens whose KV is in cache
    num_cached_prompt: int = 0  # prefix-cache hit length (tokens)
    committed_blocks: int = 0  # prefix of block_table already content-addressed
    generated: int = 0
    arrival: int = 0
    # engine-facing hooks
    emit: Optional[Callable] = None  # called with LLMEngineOutput-shaped dicts
    is_cancelled: Optional[Callable[[], bool]] = None
    finish_reason: Optional[FinishReason] = None
    # request deadline (monotonic instant; 0.0 = none): expired
    # sequences are reaped in plan() — queue, prefill, and decode alike
    # — so their KV blocks free instead of burning further steps
    deadline: float = 0.0
    # multimodal: [(token offset, embeds[n, D])] to inject during prefill
    mm_segments: list = field(default_factory=list)
    # generated-token counts for frequency/presence/repetition penalties
    # (only maintained when the request's sampling options need them)
    gen_counts: dict = field(default_factory=dict)
    # cached distinct prompt ids for the repetition penalty (immutable;
    # computed once — np.unique over a long prompt must not sit on the
    # per-step host path)
    prompt_unique: Optional[Any] = None
    # per-sequence drafter state (spec/drafter.py NgramIndex): the
    # engine keeps the incremental n-gram index here so the per-step
    # proposal is a hashed lookup instead of an O(window) re-scan;
    # rebuilt whenever the sequence shrinks (unwind/truncation)
    drafter_state: Optional[Any] = None
    # per-sequence guided-decoding cursor (guided/automaton.GuidedState,
    # docs/guided_decoding.md): advanced in append_token as tokens
    # COMMIT — staged speculative drafts are unwound before verified
    # tokens re-append, so the automaton only ever sees committed tokens
    guided_state: Optional[Any] = None
    # request-lifecycle stamps (telemetry): monotonic except the wall
    # anchor; the engine emits queue-wait/prefill/decode spans from
    # these at finish time (engine.py _emit_finish)
    t_submit: float = 0.0  # engine.submit() (monotonic)
    t_submit_wall: float = 0.0  # same instant, wall clock
    t_admit: float = 0.0  # first admission into prefilling
    t_prefill_done: float = 0.0  # last prompt chunk computed
    t_first_token: float = 0.0  # first generated token appended (TTFT)
    # propagated trace context ({"trace_id", "span_id"}) or None
    trace: Optional[dict] = None
    # the CALLER's request id (Context.id — the frontend's autopsy key),
    # distinct from request.request_id (the preprocessor's cmpl-… id):
    # engine-side autopsy segments/events must key on this or the
    # endpoint server's take_pending(ctx.id) never finds them
    autopsy_rid: str = ""
    # SLO + autopsy finalization must run BEFORE the last token item is
    # emitted (consumers abandon the stream at max_tokens, ahead of the
    # finish-marked item) — this guard keeps the early call and the
    # on_finish hook from double-counting
    observability_done: bool = False

    @property
    def request_id(self) -> str:
        return self.request.request_id

    @property
    def total_len(self) -> int:
        return len(self.tokens)

    @property
    def max_new_tokens(self) -> Optional[int]:
        return self.request.stop.max_tokens

    def blocks_needed(self, for_len: int, block_size: int) -> int:
        return (for_len + block_size - 1) // block_size


@dataclass
class PrefillWork:
    """One chunk of prompt to run this step."""

    seq: Sequence
    tokens: np.ndarray  # [t] token ids for this chunk
    start_pos: int  # absolute position of tokens[0]
    is_last_chunk: bool


@dataclass
class StepPlan:
    """What the engine should run this step.

    kind "mixed" carries BOTH a bounded prefill batch and the decode
    batch: the engine fuses them into one dispatch (prefill rectangle +
    K-step decode window) so a straggler's prefill no longer costs a
    dedicated full-weight pass while decode stalls — the serving-layer
    half of continuous batching (reference: vLLM's mixed scheduler,
    container/deps/vllm/...-patch :535, docs/architecture.md:55-68).
    """

    kind: str  # "prefill" | "decode" | "mixed" | "idle"
    prefill_batch: list[PrefillWork] = field(default_factory=list)
    decode_seqs: list[Sequence] = field(default_factory=list)
    # mixed plans: the [rows, len] prefill rectangle this window was
    # planned against (narrow or wide — engine pads to exactly this)
    rect: Optional[tuple[int, int]] = None

    @property
    def prefill(self) -> Optional[PrefillWork]:
        """First prefill work item (derived — cannot drift from the batch)."""
        return self.prefill_batch[0] if self.prefill_batch else None


class Scheduler:
    def __init__(
        self,
        allocator: BlockAllocator,
        block_size: int,
        max_batch_size: int = 64,
        prefill_chunk_size: int = 1024,
        max_model_len: Optional[int] = None,
        max_prefill_tokens: Optional[int] = None,
    ):
        self.allocator = allocator
        self.block_size = block_size
        self.max_batch_size = max_batch_size
        self.prefill_chunk_size = prefill_chunk_size
        self.max_model_len = max_model_len
        # total token budget for one BATCHED prefill step (several
        # sequences' chunks fused into one dispatch); per-seq chunks
        # still cap at prefill_chunk_size
        self.max_prefill_tokens = max_prefill_tokens or prefill_chunk_size
        self.waiting: deque[Sequence] = deque()
        self.prefilling: deque[Sequence] = deque()
        self.running: list[Sequence] = []
        # fused multi-step decode: how many tokens one device step emits
        # (engine sets this from EngineConfig.decode_steps); block
        # allocation must cover the whole window up front
        self.decode_lookahead = 1
        # mixed prefill+decode: when decode has work AND prefill chunks
        # are pending, emit a "mixed" plan whose prefill batch fits the
        # engine's fixed [mixed_prefill_rows, mixed_prefill_len]
        # rectangle (0 rows = mixed planning off)
        self.mixed_prefill_rows = 0
        self.mixed_prefill_len = 256
        # adaptive wide rectangle (engine sets these; 0 rows = off):
        # at low decode occupancy the mixed window swaps to
        # [wide_rows, wide_len] — same token budget, fewer rows — so a
        # long prompt stops trickling at mixed_prefill_len per window
        self.mixed_prefill_wide_rows = 0
        self.mixed_prefill_wide_len = 0
        self.mixed_wide_max_running: Optional[int] = None
        # static serving shapes (engine sets these): every jit variant
        # costs a multi-minute AOT compile on a tunneled chip, and
        # composition-dependent buckets compile MID-SERVE. Padding the
        # decode batch to one fixed size and the block-table width to
        # the max_model_len cap makes the decode/mixed dispatch ONE
        # compiled shape — padded rows are ctx=0 no-ops the Pallas
        # kernel skips, and decode is weight-read-bound so the extra
        # rows are ~free. Coarse prefill buckets bound that path's
        # variant count too.
        self.decode_batch_pad: Optional[int] = None
        # optional SMALL decode bucket below the pad (e.g. 4): low
        # concurrency decodes in a lighter window at the cost of a few
        # extra prewarmed variants
        self.decode_batch_small: Optional[int] = None
        # optional MID bucket between small and pad (engine sets pad/2
        # for wide pads): a max_batch=64 engine otherwise pads a
        # 32-deep population to 64 rows (~11% measured at c=32)
        self.decode_batch_mid: Optional[int] = None
        self.table_width_pad: Optional[int] = None
        self.prefill_batch_buckets: list[int] = list(self.BATCH_BUCKETS)
        self.prefill_chunk_buckets: list[int] = list(self.CHUNK_BUCKETS)
        self._arrival = 0
        # invoked on every finish (incl. cancellations reaped inside plan())
        self.on_finish: Optional[Callable[[Sequence, FinishReason], None]] = None
        # KVBM hook: (remaining_hashes, their_device_blocks) -> n onboarded
        # from host/disk tiers (dynamo_tpu/kvbm/manager.py onboard())
        self.onboard: Optional[Callable[[list[int], list[int]], int]] = None
        # prefix-cache stats (one query per admitted request)
        self.prefix_queries = 0
        self.prefix_hits = 0
        # recompute-preemption count (observability: healthy serving
        # should sit at ~0 — see _growth_reserve)
        self.preemptions = 0

    # -- intake -----------------------------------------------------------
    def add_request(self, seq: Sequence) -> None:
        seq.arrival = self._arrival
        self._arrival += 1
        self.waiting.append(seq)

    @property
    def num_waiting(self) -> int:
        return len(self.waiting) + len(self.prefilling)

    @property
    def num_running(self) -> int:
        return len(self.running)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.prefilling or self.running)

    # -- planning ---------------------------------------------------------
    def plan(self) -> StepPlan:
        self._reap_cancelled()
        self._admit()
        backlog = self._prefill_backlog() if self.prefilling else 0
        rows, rlen = self._mixed_rect(backlog=backlog)
        # a COHORT (more prompts than rectangle rows, whole backlog
        # fits one dedicated step) takes the dedicated step: trickling
        # it 'rows' per window staggers the population into waves that
        # decode at partial width for their whole lifetime, while one
        # dedicated dispatch costs the decoders ~a quarter-window
        # (measured at B=64/128-token prompts: 924 vs 1505+ tok/s)
        cohort = (
            len(self.prefilling) > rows
            and backlog <= self.max_prefill_tokens
        )
        if (
            self.prefilling
            and self.running
            and rows > 0
            and not cohort
            and backlog <= 2 * rows * rlen
            and (
                len(self.prefilling) <= rows
                or len(self.running) >= len(self.prefilling)
            )
        ):
            # mixed step: prefill rides the decode window's dispatch,
            # bounded to the chosen rectangle (narrow, or wide at low
            # decode occupancy — _mixed_rect). Large backlogs
            # (cold-start bursts, long prompts) and prefill-heavy
            # moments (a synchronized cohort with few decoders — the
            # rectangle would RAMP the batch 8 rows per window while
            # decode runs near-empty) fall through to the dedicated
            # batched-prefill step below.
            works = self._plan_prefill_batch(
                budget=rows * rlen,
                max_seqs=rows,
                max_chunk_len=rlen,
            )
            decode = self._plan_decode()
            if works and decode:
                return StepPlan(
                    kind="mixed", prefill_batch=works, decode_seqs=decode,
                    rect=(rows, rlen),
                )
            if works:
                return StepPlan(kind="prefill", prefill_batch=works)
            if decode:
                return StepPlan(kind="decode", decode_seqs=decode)
            return StepPlan(kind="idle")
        if self.prefilling:
            works = self._plan_prefill_batch()
            if works:
                return StepPlan(kind="prefill", prefill_batch=works)
        if self.running:
            return StepPlan(kind="decode", decode_seqs=self._plan_decode())
        return StepPlan(kind="idle")

    def _prefill_backlog(self) -> int:
        """TRUE pending prompt tokens across prefilling sequences — NOT
        chunk-capped: a single long prompt must trip the dedicated-
        prefill fallback rather than trickle through the mixed
        rectangle at mixed_prefill_len tokens per decode window."""
        return sum(
            max(1, s.total_len - s.num_computed) for s in self.prefilling
        )

    def _mixed_rect(
        self,
        n_running: Optional[int] = None,
        prefill_seqs: Optional[list[Sequence]] = None,
        backlog: Optional[int] = None,
    ) -> tuple[int, int]:
        """The mixed window's prefill rectangle for a given population
        (defaults: the scheduler's current one; plan_pipelined_mixed
        passes the NEXT window's): the wide [wide_rows, wide_len]
        variant when decode occupancy is low, few prompts are
        prefilling, and at least one needs more than a narrow chunk —
        a long prompt then prefills in backlog/wide_len windows instead
        of backlog/len, while decode keeps riding along (dedicated
        prefill instead starves it: benchmarks/RESULTS.md ISL-3000
        negative result). Otherwise the narrow rectangle's extra rows
        graduate more stragglers per window."""
        if n_running is None:
            n_running = len(self.running)
        if prefill_seqs is None:
            prefill_seqs = self.prefilling
        if backlog is None:
            backlog = sum(
                max(1, s.total_len - s.num_computed) for s in prefill_seqs
            )
        if (
            self.mixed_prefill_wide_rows > 0
            and (
                self.mixed_wide_max_running is None
                or n_running <= self.mixed_wide_max_running
            )
            and len(prefill_seqs) <= self.mixed_prefill_wide_rows
            and backlog > self.mixed_prefill_len
        ):
            return self.mixed_prefill_wide_rows, self.mixed_prefill_wide_len
        return self.mixed_prefill_rows, self.mixed_prefill_len

    def _reap_cancelled(self) -> None:
        """Remove cancelled AND deadline-expired sequences from every
        pool. finish() frees their KV blocks, so an expired request
        costs nothing past the step that notices it."""
        now = time.monotonic()

        def _expired(seq: Sequence) -> bool:
            return bool(seq.deadline) and now >= seq.deadline

        for pool, stage in ((self.waiting, "queue"), (self.prefilling, "prefill")):
            for seq in list(pool):
                if seq.is_cancelled and seq.is_cancelled():
                    pool.remove(seq)
                    self.finish(seq, FinishReason.CANCELLED)
                elif _expired(seq):
                    pool.remove(seq)
                    DEADLINE_EXPIRED.labels(stage).inc()
                    log.warning(
                        "request %s deadline expired in %s; cancelling",
                        seq.request_id, stage,
                    )
                    self.finish(seq, FinishReason.TIMEOUT)
        for seq in list(self.running):
            if seq.is_cancelled and seq.is_cancelled():
                self.running.remove(seq)
                self.finish(seq, FinishReason.CANCELLED)
            elif _expired(seq):
                self.running.remove(seq)
                DEADLINE_EXPIRED.labels("decode").inc()
                log.warning(
                    "request %s deadline expired mid-decode; cancelling",
                    seq.request_id,
                )
                self.finish(seq, FinishReason.TIMEOUT)

    def _growth_reserve(self) -> int:
        """Blocks the CURRENT population still needs to finish its
        generations (exact when max_tokens is known; one decode window
        otherwise). Admission leaves this many blocks free: without the
        reserve, blocks freed by a preemption are instantly consumed by
        the next waiting prompt, and the following decode window
        preempts again — a recompute cascade in which every admission
        costs a running request its entire prompt's prefill windows
        (observed as a c=64 ISL-3000 collapse to 35 out tok/s with
        ~9-minute TTFT outliers; 20 preemptions per 120 s even in
        healthy runs)."""
        r = 0
        for seq in self.running:
            if seq.max_new_tokens is not None:
                end = seq.total_len + max(
                    0, seq.max_new_tokens - seq.generated
                )
            else:
                end = seq.total_len + self.decode_lookahead
            r += max(
                0,
                seq.blocks_needed(end, self.block_size)
                - len(seq.block_table),
            )
        for seq in self.prefilling:
            # a prefilling seq holds its full prompt's blocks already;
            # reserve its generation growth
            if seq.max_new_tokens is not None:
                end = seq.total_len + seq.max_new_tokens
            else:
                end = seq.total_len + self.decode_lookahead
            r += max(
                0,
                seq.blocks_needed(end, self.block_size)
                - len(seq.block_table),
            )
        return r

    def _admit(self) -> None:
        reserve = None  # computed lazily, refreshed per admission
        while self.waiting and (
            len(self.running) + len(self.prefilling) < self.max_batch_size
        ):
            seq = self.waiting[0]
            if self.max_model_len and seq.total_len >= self.max_model_len:
                self.waiting.popleft()
                self.finish(seq, FinishReason.ERROR)
                continue
            seq_hashes = seq.tokens.sequence_hashes()
            # blocks for the whole prompt + 1 growth block
            n_prompt_blocks = seq.blocks_needed(seq.total_len, self.block_size)
            if reserve is None:
                reserve = self._growth_reserve()
            # charge only what admission actually takes from the free
            # pool: actively-shared prefix blocks are already pinned
            free_need = self.allocator.free_need(
                seq_hashes[:n_prompt_blocks], n_prompt_blocks
            )
            if self.allocator.num_free < free_need + reserve:
                break  # backpressure: the population's growth comes first
            # admitting this seq adds its own growth to the reserve
            reserve += seq.blocks_needed(
                seq.total_len + (seq.max_new_tokens or self.decode_lookahead),
                self.block_size,
            ) - n_prompt_blocks
            try:
                complete = seq_hashes[: n_prompt_blocks]
                blocks, cached = self.allocator.allocate_prefix(complete)
                if self.onboard is not None and cached < len(complete):
                    # the onboard hook is (hashes, blocks) -> n with no
                    # request identity — park the admitting seq's rid in
                    # the autopsy thread-local so the fleet fabric's
                    # prefetch (same thread, synchronous chain) can
                    # stamp its hit/miss onto this request's record
                    autopsy.set_onboard_rid(
                        seq.autopsy_rid or seq.request_id
                    )
                    try:
                        n_on = self.onboard(
                            complete[cached:], blocks[cached : len(complete)]
                        )
                    finally:
                        autopsy.set_onboard_rid(None)
                    for i in range(n_on):
                        self.allocator.commit_block(
                            blocks[cached + i], complete[cached + i]
                        )
                    cached += n_on
                extra = n_prompt_blocks - len(complete)
                try:
                    for _ in range(max(0, extra)):
                        blocks.append(self.allocator.allocate_block())
                except NoBlocksError:
                    # roll back the whole allocation (reused pins + fresh
                    # + onboarded blocks) or they leak with a permanent ref
                    self.allocator.free_sequence(blocks)
                    raise
            except NoBlocksError:
                break  # backpressure: try again next step
            self.waiting.popleft()
            if seq.t_admit == 0.0:
                # first admission only: a preempted-and-readmitted seq
                # keeps its original queue-wait measurement
                seq.t_admit = time.monotonic()
                if seq.t_submit:
                    ENGINE_QUEUE_WAIT.observe(seq.t_admit - seq.t_submit)
            seq.block_table = blocks
            seq.num_cached_prompt = cached * self.block_size
            seq.num_computed = seq.num_cached_prompt
            seq.committed_blocks = cached  # reused blocks are already addressed
            seq.state = SeqState.PREFILL
            self.prefilling.append(seq)
            # prefix-cache stats: one query per admitted request
            self.prefix_queries += 1
            if cached > 0:
                self.prefix_hits += 1

    def _plan_prefill_batch(
        self,
        budget: Optional[int] = None,
        max_seqs: Optional[int] = None,
        max_chunk_len: Optional[int] = None,
    ) -> list[PrefillWork]:
        """One chunk from each of several prefilling sequences, fused
        into a single step (total tokens bounded by max_prefill_tokens)
        — continuous batching's batched-prefill half. ``max_chunk_len``
        additionally caps each row's chunk (the mixed-step rectangle)."""
        budget = budget if budget is not None else self.max_prefill_tokens
        max_seqs = max_seqs if max_seqs is not None else self.max_batch_size
        works: list[PrefillWork] = []
        max_chunk = 0
        for seq in self.prefilling:
            if len(works) >= max_seqs:
                break
            prompt = seq.tokens.all_tokens()
            start = seq.num_computed
            remaining = len(prompt) - start
            if remaining <= 0:
                # fully cached prompt: recompute the last token so we
                # have its logits to sample from
                start = max(0, len(prompt) - 1)
                remaining = len(prompt) - start
            chunk = min(remaining, self.prefill_chunk_size, budget)
            if max_chunk_len is not None:
                chunk = min(chunk, max_chunk_len)
            # the dispatch cost is the PADDED B×T rectangle (every row
            # pads to the longest chunk's bucket), so the budget bounds
            # that area, not the sum of real tokens — one long chunk
            # plus many short ones must not inflate into a huge step
            new_max = max(max_chunk, chunk)
            area = (
                next_bucket(len(works) + 1, self.prefill_batch_buckets)
                * next_bucket(new_max, self.prefill_chunk_buckets)
            )
            cur_area = (
                next_bucket(len(works), self.prefill_batch_buckets)
                * next_bucket(max_chunk, self.prefill_chunk_buckets)
                if works
                else 0
            )
            # a row whose admission leaves the padded rectangle unchanged
            # is free — only reject when it actually GROWS the dispatch
            # past the budget
            if works and area > budget and area > cur_area:
                break
            tokens = np.asarray(prompt[start : start + chunk], dtype=np.int32)
            works.append(
                PrefillWork(
                    seq=seq,
                    tokens=tokens,
                    start_pos=start,
                    is_last_chunk=(start + chunk >= len(prompt)),
                )
            )
            max_chunk = new_max
        return works

    def complete_prefill_chunk(self, work: PrefillWork) -> None:
        seq = work.seq
        seq.num_computed = work.start_pos + len(work.tokens)
        self._commit_full_blocks(seq)
        if work.is_last_chunk:
            self.prefilling.remove(seq)
            seq.state = SeqState.RUNNING
            if seq.t_prefill_done == 0.0:
                seq.t_prefill_done = time.monotonic()
            self.running.append(seq)

    def _seq_lookahead(self, seq: Sequence) -> int:
        """Fused-decode window steps this sequence can actually keep:
        clamped to its remaining-token budget. Near max_tokens the
        window's surplus is discarded, and allocating blocks for it would
        trigger phantom preemptions under pressure. Block allocation
        (_plan_decode) and the device-side KV-write mask
        (build_decode_arrays' valid_steps) MUST use the same value — if
        writes outrun allocation they land in another sequence's
        possibly-shared block."""
        lookahead = self.decode_lookahead
        if seq.max_new_tokens is not None:
            lookahead = min(lookahead, max(1, seq.max_new_tokens - seq.generated))
        return lookahead

    def _plan_decode(self) -> list[Sequence]:
        """Ensure each running seq has a slot for its next token; on block
        exhaustion preempt the YOUNGEST running sequence (possibly the
        requester itself) back to waiting — recompute preemption."""
        batch = sorted(self.running, key=lambda s: s.arrival)[: self.max_batch_size]
        safe: list[Sequence] = []
        for seq in batch:
            if seq.state != SeqState.RUNNING:
                continue  # preempted earlier in this pass
            lookahead = self._seq_lookahead(seq)
            needed_blocks = seq.blocks_needed(
                seq.total_len + lookahead, self.block_size
            )
            while (
                seq.state == SeqState.RUNNING
                and len(seq.block_table) < needed_blocks
            ):
                try:
                    seq.block_table.append(self.allocator.allocate_block())
                except NoBlocksError:
                    if not self.running:
                        break
                    victim = max(self.running, key=lambda s: s.arrival)
                    self._preempt(victim)
                    if victim is seq:
                        break
            if seq.state == SeqState.RUNNING:
                safe.append(seq)
        return safe

    def plan_pipelined_decode(
        self, seqs: list[Sequence], lag: dict
    ) -> Optional[dict]:
        """Plan the NEXT single-token decode step while one is in
        flight (the decode_steps == 1 overlapped pipeline,
        engine._decode_pipeline / docs/performance.md).

        ``lag`` maps id(seq) -> tokens sampled by in-flight steps but
        not yet applied to host state (one per step here). Sequences
        that FINISH inside the in-flight lag — max_tokens reached,
        max_model_len hit, or block-table cap — are simply not rows of
        the next step, mirroring ``should_finish`` one step ahead so a
        predicted finish never leaves an in-flight step writing KV into
        blocks a harvest-time ``finish()`` just freed. Returns None
        (flush the pipeline) on anything irregular: cancellation,
        deadline expiry, a non-RUNNING state, or block exhaustion —
        this path NEVER preempts (a preemption would free blocks an
        in-flight step still writes); the outer serial plan() handles
        pressure with nothing in flight.

        Returns {"seqs", "arrays", "src_idx", "offsets", "vmap"}: the
        next step's rows, its decode arrays (the token column is a
        placeholder — the engine chains it on device from the in-flight
        step's sampled tokens via ``src_idx``), per-row seed offsets
        (= lags), and the one token each row will add.
        """
        now = time.monotonic()
        survivors: list[Sequence] = []
        for seq in seqs:
            if seq.state != SeqState.RUNNING:
                return None
            if seq.is_cancelled and seq.is_cancelled():
                return None
            if bool(seq.deadline) and now >= seq.deadline:
                return None
            gl = lag.get(id(seq), 0)
            if (
                seq.max_new_tokens is not None
                and seq.max_new_tokens - seq.generated <= gl
            ):
                continue  # finishes inside the in-flight step
            if self.max_model_len and seq.total_len + gl >= self.max_model_len:
                continue
            if len(seq.block_table) >= self.allocator.num_blocks - 1:
                continue  # should_finish's can't-grow-further clause
            survivors.append(seq)
        if not survivors:
            return None
        bs = self.block_size
        # block growth for the next step's KV write (the in-flight
        # token's slot) — no preemption; rollback on exhaustion
        added: list[Sequence] = []
        ok = True
        for seq in survivors:
            needed = seq.blocks_needed(
                seq.total_len + lag.get(id(seq), 0) + 1, bs
            )
            while len(seq.block_table) < needed:
                try:
                    seq.block_table.append(self.allocator.allocate_block())
                    added.append(seq)
                except NoBlocksError:
                    ok = False
                    break
            if not ok:
                break
        if not ok:
            for seq in reversed(added):
                self.allocator.free_sequence([seq.block_table.pop()])
            return None
        old_row = {id(s): j for j, s in enumerate(seqs)}
        n = len(survivors)
        B = self._decode_batch(n)
        max_blocks = max(len(s.block_table) for s in survivors)
        width = self._table_width(max_blocks)
        positions = np.zeros((B, 1), np.int32)
        slot_mapping = np.zeros((B,), np.int32)
        tables = np.zeros((B, width), np.int32)
        ctx = np.zeros((B,), np.int32)
        src_idx = np.zeros((B,), np.int32)
        offsets = [0] * n
        vmap: dict[int, int] = {}
        for i, s in enumerate(survivors):
            gl = lag.get(id(s), 0)
            src_idx[i] = old_row[id(s)]
            pos = s.total_len - 1 + gl
            positions[i, 0] = pos
            slot_mapping[i] = s.block_table[pos // bs] * bs + pos % bs
            tables[i, : len(s.block_table)] = s.block_table
            ctx[i] = s.total_len + gl
            offsets[i] = gl
            vmap[id(s)] = 1
        arrays = {
            "tokens": np.zeros((B, 1), np.int32),  # device chain overrides
            "positions": positions,
            "slot_mapping": slot_mapping,
            "block_tables": tables,
            "context_lens": ctx,
            "last_token_idx": np.zeros((B,), np.int32),
        }
        return {
            "seqs": survivors,
            "arrays": arrays,
            "src_idx": src_idx,
            "offsets": offsets,
            "vmap": vmap,
        }

    def plan_pipelined_mixed(
        self, seqs: list[Sequence], works: list[PrefillWork], lag: dict,
        grad_base: Optional[int] = None,
    ) -> Optional[dict]:
        """Plan the NEXT window while one or more windows are in flight.

        ``lag`` maps id(seq) -> tokens generated by in-flight windows
        but not yet applied to host state (decode rows contribute their
        valid steps per window; a last-chunk prefill contributes its
        one sampled token). The newest in-flight window is decoding for
        ``seqs`` AND prefilling ``works``; last-chunk works GRADUATE to
        decode rows of the next window (their first sampled token is
        device-resident in that window's outputs — the engine chains it
        via an on-device gather, indexed by ``src_idx``: row j of the
        newest decode batch -> j, graduated work r -> grad_base + r,
        where ``grad_base`` defaults to the newest window's padded
        decode width; a prefill-only in-flight entry — the cohort
        dispatch the overlapped window pipeline chains its first window
        off — passes 0, its token vector being the prefill rows alone).
        Returns None (flush the pipeline) whenever anything irregular
        appears: a non-final chunk, cancellations, budget inside the
        in-flight windows, batch overflow, or block exhaustion (never
        preempts here).

        Returns {"seqs", "works2", "arrays", "src_idx", "offsets",
        "vmap"}: the next window's decode seqs (old + graduated), its
        prefill works, the decode arrays (tokens are placeholders), the
        token-source gather index, per-row seed offsets (= lags), and
        the valid-step counts this window will add per sequence (the
        engine folds them into ``lag`` on dispatch).
        """
        if self.waiting:
            self._admit()
        now = time.monotonic()

        def _dead(seq: Sequence) -> bool:
            if seq.is_cancelled and seq.is_cancelled():
                return True
            return bool(seq.deadline) and now >= seq.deadline

        for w in works:
            if not w.is_last_chunk:
                return None
            if _dead(w.seq):
                return None
        survivors: list[Sequence] = []
        for seq in seqs:
            if seq.state != SeqState.RUNNING:
                return None
            if _dead(seq):
                return None
            if (
                seq.max_new_tokens is not None
                and seq.max_new_tokens - seq.generated <= lag.get(id(seq), 0)
            ):
                # finishes INSIDE an in-flight window: simply not a
                # row of the next one (its blocks are freed at sync,
                # which the next window never touches) — refusing to
                # pipeline here would block the chain whenever ANY
                # sequence nears its budget, i.e. almost always
                continue
            survivors.append(seq)
        graduated = [w.seq for w in works]
        grad_row = {id(w.seq): r for r, w in enumerate(works)}
        old_row = {id(s): j for j, s in enumerate(seqs)}
        next_seqs = survivors + graduated
        if not next_seqs or len(next_seqs) > self.max_batch_size:
            return None
        K = self.decode_lookahead
        # block allocation for the whole next window (no preemption on
        # this path; rollback on exhaustion). lag covers a graduated
        # row's in-flight sampled token, so one formula serves all.
        added: list[Sequence] = []
        ok = True
        for seq in next_seqs:
            needed = seq.blocks_needed(
                seq.total_len + lag.get(id(seq), 0) + K, self.block_size
            )
            while len(seq.block_table) < needed:
                try:
                    seq.block_table.append(self.allocator.allocate_block())
                    added.append(seq)
                except NoBlocksError:
                    ok = False
                    break
            if not ok:
                break
        if not ok:
            for seq in reversed(added):
                self.allocator.free_sequence([seq.block_table.pop()])
            return None
        # next window's prefill rows: pending chunks excluding the
        # in-flight works' seqs
        works2: list[PrefillWork] = []
        rows, rlen = self.mixed_prefill_rows, self.mixed_prefill_len
        if self.mixed_prefill_rows > 0:
            busy = set(id(s) for s in graduated)
            avail = [s for s in self.prefilling if id(s) not in busy]
            # adaptive rect for the NEXT window: its decode population
            # is next_seqs (not self.running, which lags the pipeline)
            avail_backlog = sum(
                max(1, s.total_len - s.num_computed) for s in avail
            )
            rows, rlen = self._mixed_rect(
                n_running=len(next_seqs), prefill_seqs=avail,
                backlog=avail_backlog,
            )
            if len(avail) > rows and (
                len(next_seqs) < len(avail)
                or avail_backlog <= self.max_prefill_tokens
            ):
                # prefill-heavy or a one-dispatch COHORT: break the
                # chain so the outer plan can run a dedicated batched
                # prefill instead of ramping the batch 'rows' per
                # window (a trickled cohort decodes at partial width
                # for its whole lifetime — see plan()'s cohort gate)
                for seq in reversed(added):
                    self.allocator.free_sequence([seq.block_table.pop()])
                return None
            saved = self.prefilling
            self.prefilling = deque(avail)
            try:
                works2 = self._plan_prefill_batch(
                    budget=rows * rlen,
                    max_seqs=rows,
                    max_chunk_len=rlen,
                )
            finally:
                self.prefilling = saved

        bs = self.block_size
        n = len(next_seqs)
        B = self._decode_batch(n)
        max_blocks = max(len(s.block_table) for s in next_seqs)
        width = self._table_width(max_blocks)
        positions = np.zeros((B, 1), np.int32)
        tables = np.zeros((B, width), np.int32)
        ctx = np.zeros((B,), np.int32)
        valid_steps = np.zeros((B,), np.int32)
        src_idx = np.zeros((B,), np.int32)
        offsets = [0] * n
        vmap: dict[int, int] = {}
        if grad_base is None:
            grad_base = self._decode_batch(len(seqs)) if seqs else 0
        for i, s in enumerate(next_seqs):
            gen_after = lag.get(id(s), 0)
            if id(s) in grad_row:
                src_idx[i] = grad_base + grad_row[id(s)]
            else:
                src_idx[i] = old_row[id(s)]
            # the sampled-but-unapplied tokens occupy slots up to
            # total_len - 1 + lag; the next window starts there
            positions[i, 0] = s.total_len - 1 + gen_after
            tables[i, : len(s.block_table)] = s.block_table
            ctx[i] = s.total_len + gen_after
            v = K
            if s.max_new_tokens is not None:
                v = min(v, max(1, s.max_new_tokens - s.generated - gen_after))
            valid_steps[i] = v
            offsets[i] = gen_after
            vmap[id(s)] = v
        arrays = {
            "tokens": np.zeros((B, 1), np.int32),  # device chain overrides
            "positions": positions,
            "block_tables": tables,
            "context_lens": ctx,
            "valid_steps": valid_steps,
        }
        return {
            "seqs": next_seqs,
            "works2": works2,
            "arrays": arrays,
            "src_idx": src_idx,
            "offsets": offsets,
            "vmap": vmap,
            "rect": (rows, rlen),
        }

    # -- speculative decoding (dynamo_tpu/spec) ---------------------------
    def reserve_spec_tokens(self, seq: Sequence, drafts: list[int]) -> int:
        """Stage up to ``len(drafts)`` draft tokens for one verify step:
        allocate the blocks their KV writes need (positions
        [total_len-1, total_len-1+k) — the verify forward writes every
        draft's KV speculatively), then append the kept drafts to the
        sequence's token state so array building sees them. The engine
        UNWINDS the appended drafts after the device sync
        (TokenBlockSequence.unwind) and re-appends only the accepted
        prefix through append_token — so block content-addressing
        (committed_blocks / _commit_full_blocks) never sees unverified
        draft tokens: num_computed is untouched here, and a block is
        only committed once real appended tokens cover it.

        Never preempts (speculation is an optimization): on block
        exhaustion the draft count shrinks to what the sequence's
        current table already covers. Returns the kept draft count.
        """
        k = len(drafts)
        bs = self.block_size
        while k > 0:
            needed = seq.blocks_needed(seq.total_len + k, bs)
            try:
                while len(seq.block_table) < needed:
                    seq.block_table.append(self.allocator.allocate_block())
                break
            except NoBlocksError:
                # keep what fits in the blocks already held — blocks
                # speculatively appended above stay on the table (plain
                # growth the sequence will need anyway) but are never
                # committed/content-addressed until real tokens fill them
                k = min(k, len(seq.block_table) * bs - seq.total_len)
        if k > 0:
            seq.tokens.extend(drafts[:k])
        return max(0, k)

    def _fill_spec_row(
        self, arrays: dict[str, np.ndarray], i: int, seq: Sequence,
        base: int, k: int, S: int,
    ) -> None:
        """One verify-step row's tensor geometry — THE shared layout
        for both spec planners (serial ``build_spec_arrays`` over
        staged drafts, pipelined ``plan_pipelined_spec`` over explicit
        lags): positions contiguous from the carry token at ``base``,
        the k+1 real slots resolved through the block table (row pads
        write the reserved garbage slot 0), ``context_lens`` = real
        tokens including drafts (= base+1+k). The pipelined path's
        bit-identity-to-serial contract depends on the two callers
        producing identical rows for identical states, so the layout
        lives here and nowhere else."""
        bs = self.block_size
        arrays["positions"][i, :] = np.arange(base, base + S)
        for j in range(k + 1):
            pos = base + j
            arrays["slot_mapping"][i * S + j] = (
                seq.block_table[pos // bs] * bs + pos % bs
            )
        arrays["block_tables"][i, : len(seq.block_table)] = seq.block_table
        arrays["context_lens"][i] = base + 1 + k
        arrays["draft_lens"][i] = k

    def build_spec_arrays(
        self, works: list[tuple[Sequence, list[int]]], S: int
    ) -> dict[str, np.ndarray]:
        """Verify-step tensors for [(seq, row_tokens)] rows, where
        ``row_tokens`` is the CONTIGUOUS run [last committed token,
        draft_0, ..., draft_{k-1}] (the engine already holds these —
        re-materializing each sequence's full history here would put a
        second O(context) copy on the per-step host path), padded to the
        fixed width ``S`` (= spec_tokens+1 — one compiled shape). Call
        AFTER reserve_spec_tokens (seq.total_len includes the staged
        drafts). Row-internal pads keep contiguous positions (the Pallas
        prefill kernel derives per-token positions from positions[:, 0])
        but write to the reserved garbage slot 0; context_lens covers
        only real tokens, so attention never reads a pad's KV."""
        n = len(works)
        B = self._decode_batch(n)
        max_blocks = max(len(s.block_table) for s, _ in works)
        width = self._table_width(max_blocks)
        arrays = {
            "tokens": np.zeros((B, S), np.int32),
            "positions": np.zeros((B, S), np.int32),
            "slot_mapping": np.zeros((B * S,), np.int32),
            "block_tables": np.zeros((B, width), np.int32),
            "context_lens": np.zeros((B,), np.int32),
            "draft_lens": np.zeros((B,), np.int32),
            "last_token_idx": np.zeros((B,), np.int32),
        }
        for i, (seq, row) in enumerate(works):
            k = len(row) - 1
            # carry position: total_len here INCLUDES the staged drafts
            base = seq.total_len - k - 1
            arrays["tokens"][i, : k + 1] = row
            self._fill_spec_row(arrays, i, seq, base, k, S)
        return arrays

    def plan_pipelined_spec(
        self, entries: list, S: int
    ) -> Optional[dict]:
        """Plan the NEXT speculative verify step while the PREVIOUS
        one's emitted tokens are not yet applied to host state (the
        overlapped spec pipeline, engine._spec_pipeline /
        docs/speculative_decoding.md).

        ``entries`` is the previous step's row list as
        ``(seq, lag, drafts)``: ``lag`` = tokens that step emitted for
        the row (EXACT — the spec pipeline plans between harvest and
        emit, so unlike ``plan_pipelined_decode`` the in-flight token
        count is known, 1..K+1), ``drafts`` = the repaired proposals
        for the next step. Same discipline as the other pipelined
        planners: sequences that FINISH inside the lag (max_tokens,
        max_model_len, block-table cap — ``should_finish`` mirrored one
        emit ahead) are simply not rows of the next step; anything
        irregular (cancellation, deadline expiry, a non-RUNNING state,
        block exhaustion) returns None — flush to the serial planner,
        which admits/preempts/reaps with nothing in flight. This path
        NEVER preempts. Block growth reserves the row's in-flight
        tokens plus its draft run (``total_len + lag + k`` — the same
        coverage ``reserve_spec_tokens`` gives the serial step), with
        rollback on ``NoBlocksError``. Drafts are clamped to the
        remaining ``max_tokens`` budget exactly as the serial draft
        loop clamps them (bit-identity of the proposal stream).

        Returns {"works", "arrays", "src_idx", "offsets"}: ``works`` =
        (seq, kept_drafts) rows of the next step; ``arrays`` = the
        verify-step tensors, with token column 0 a placeholder — the
        engine chains each row's carry token ON DEVICE from the
        previous step's packed output (``chain_spec``), gathered by
        ``src_idx`` (= the row's index in ``entries``); ``offsets`` =
        per-row seed offsets (= lags). Unlike the serial path, nothing
        is staged into ``seq.tokens`` — array geometry comes from the
        explicit (lag, drafts) and host token state stays clean for the
        overlapped emit/bookkeeping.
        """
        now = time.monotonic()
        survivors: list[tuple[int, Sequence, int, list[int]]] = []
        for row, (seq, gl, drafts) in enumerate(entries):
            if seq.state != SeqState.RUNNING:
                return None
            if seq.is_cancelled and seq.is_cancelled():
                return None
            if bool(seq.deadline) and now >= seq.deadline:
                return None
            if (
                seq.max_new_tokens is not None
                and seq.max_new_tokens - seq.generated <= gl
            ):
                continue  # finishes inside the in-flight emit
            if self.max_model_len and seq.total_len + gl >= self.max_model_len:
                continue
            if len(seq.block_table) >= self.allocator.num_blocks - 1:
                continue  # should_finish's can't-grow-further clause
            k = len(drafts)
            if seq.max_new_tokens is not None:
                # leave room for the verify step's guaranteed +1 token
                # (the serial draft loop's budget clamp, shifted by lag)
                k = min(
                    k, max(0, seq.max_new_tokens - seq.generated - gl - 1)
                )
            survivors.append((row, seq, gl, drafts[: min(k, S - 1)]))
        if not survivors:
            return None
        bs = self.block_size
        added: list[Sequence] = []
        ok = True
        for _, seq, gl, drafts in survivors:
            needed = seq.blocks_needed(seq.total_len + gl + len(drafts), bs)
            while len(seq.block_table) < needed:
                try:
                    seq.block_table.append(self.allocator.allocate_block())
                    added.append(seq)
                except NoBlocksError:
                    ok = False
                    break
            if not ok:
                break
        if not ok:
            for seq in reversed(added):
                self.allocator.free_sequence([seq.block_table.pop()])
            return None
        n = len(survivors)
        B = self._decode_batch(n)
        max_blocks = max(len(s.block_table) for _, s, _, _ in survivors)
        width = self._table_width(max_blocks)
        arrays = {
            # tokens column 0 = placeholder (device chain fills it)
            "tokens": np.zeros((B, S), np.int32),
            "positions": np.zeros((B, S), np.int32),
            "slot_mapping": np.zeros((B * S,), np.int32),
            "block_tables": np.zeros((B, width), np.int32),
            "context_lens": np.zeros((B,), np.int32),
            "draft_lens": np.zeros((B,), np.int32),
            "last_token_idx": np.zeros((B,), np.int32),
        }
        src_idx = np.zeros((B,), np.int32)
        offsets = [0] * n
        works: list[tuple[Sequence, list[int]]] = []
        for i, (row, seq, gl, drafts) in enumerate(survivors):
            k = len(drafts)
            # carry position: total_len + lag - 1 (the emit has not yet
            # applied; same row a serial plan would build post-emit)
            base = seq.total_len + gl - 1
            if k:
                arrays["tokens"][i, 1 : k + 1] = drafts
            self._fill_spec_row(arrays, i, seq, base, k, S)
            src_idx[i] = row
            offsets[i] = gl
            works.append((seq, drafts))
        return {
            "works": works,
            "arrays": arrays,
            "src_idx": src_idx,
            "offsets": offsets,
        }

    def _preempt(self, victim: Sequence) -> None:
        self.preemptions += 1
        ENGINE_PREEMPTIONS.inc()
        log.warning("preempting %s (recompute)", victim.request_id)
        self.running.remove(victim)
        self.allocator.free_sequence(victim.block_table)
        victim.block_table = []
        victim.num_computed = 0
        victim.num_cached_prompt = 0
        victim.committed_blocks = 0
        victim.state = SeqState.WAITING
        self.waiting.appendleft(victim)

    # -- post-step bookkeeping -------------------------------------------
    def append_token(self, seq: Sequence, token: int) -> None:
        seq.tokens.append(int(token))
        seq.generated += 1
        if seq.t_first_token == 0.0:
            # TTFT stamp (telemetry/slo.py): every emit path — plain
            # step, fused window, spec verify — funnels through here
            seq.t_first_token = time.monotonic()
        if seq.request.sampling.needs_penalties:
            seq.gen_counts[int(token)] = seq.gen_counts.get(int(token), 0) + 1
        if seq.guided_state is not None:
            # every emit path — plain step, spec verify — funnels
            # through here, so the automaton cursor tracks exactly the
            # committed token stream (guided requires decode_steps == 1;
            # fused windows never carry guided sequences)
            seq.guided_state.advance(int(token))
        # the just-sampled token's KV is NOT in the cache yet — it only gets
        # written when it is fed as input on the next step. Counting it as
        # computed would let _commit_full_blocks content-address a block
        # whose last slot holds garbage, poisoning the prefix cache.
        seq.num_computed = seq.total_len - 1
        self._commit_full_blocks(seq)

    def _commit_full_blocks(self, seq: Sequence) -> None:
        """Content-address newly completed, fully-computed blocks."""
        hashes = seq.tokens.sequence_hashes()
        n_complete_computed = min(
            seq.num_computed // self.block_size, len(seq.block_table), len(hashes)
        )
        for i in range(seq.committed_blocks, n_complete_computed):
            self.allocator.commit_block(seq.block_table[i], hashes[i])
        seq.committed_blocks = max(seq.committed_blocks, n_complete_computed)

    def should_finish(self, seq: Sequence) -> Optional[FinishReason]:
        if seq.max_new_tokens is not None and seq.generated >= seq.max_new_tokens:
            return FinishReason.LENGTH
        if self.max_model_len and seq.total_len >= self.max_model_len:
            return FinishReason.LENGTH
        if len(seq.block_table) >= (
            self.allocator.num_blocks - 1
        ):  # can't possibly grow further
            return FinishReason.LENGTH
        return None

    def finish(self, seq: Sequence, reason: FinishReason) -> None:
        if seq.state == SeqState.FINISHED:
            return
        seq.state = SeqState.FINISHED
        seq.finish_reason = reason
        if seq in self.running:
            self.running.remove(seq)
        if seq.block_table:
            self.allocator.free_sequence(seq.block_table)
            seq.block_table = []
        if self.on_finish is not None:
            self.on_finish(seq, reason)

    # -- step-tensor construction (static-shaped, bucketed) ---------------
    BATCH_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128, 256]
    CHUNK_BUCKETS = [16, 32, 64, 128, 256, 512, 1024, 2048, 4096]
    TABLE_BUCKET = 8  # block-table width rounded to multiples of this

    def _table_width(self, max_blocks: int) -> int:
        """Block-table width for a step: the fixed serving cap when set
        (one compiled shape), bucketed otherwise — growing past the cap
        degrades to a wider bucket rather than corrupting tables."""
        w = max(
            self.TABLE_BUCKET,
            -(-max_blocks // self.TABLE_BUCKET) * self.TABLE_BUCKET,
        )
        if self.table_width_pad is not None and w <= self.table_width_pad:
            return self.table_width_pad
        return w

    def _decode_batch(self, n: int) -> int:
        if (
            self.decode_batch_small is not None
            and n <= self.decode_batch_small
        ):
            return self.decode_batch_small
        if self.decode_batch_mid is not None and n <= self.decode_batch_mid:
            return self.decode_batch_mid
        b = next_bucket(n, self.BATCH_BUCKETS)
        if self.decode_batch_pad is not None and b <= self.decode_batch_pad:
            return self.decode_batch_pad
        return b

    def build_prefill_batch_arrays(
        self, works: list[PrefillWork]
    ) -> dict[str, np.ndarray]:
        """Fuse several sequences' prefill chunks into one [B, T] step
        (rows padded to the chunk bucket, batch padded to the batch
        bucket; pads write to the garbage slot 0 like decode pads)."""
        bs = self.block_size
        n = len(works)
        B = next_bucket(n, self.prefill_batch_buckets)
        T = next_bucket(
            max(len(w.tokens) for w in works), self.prefill_chunk_buckets
        )
        max_blocks = max(len(w.seq.block_table) for w in works)
        width = self._table_width(max_blocks)
        tokens = np.zeros((B, T), np.int32)
        positions = np.zeros((B, T), np.int32)
        slot_mapping = np.zeros((B * T,), np.int32)
        tables = np.zeros((B, width), np.int32)
        ctx = np.zeros((B,), np.int32)
        last_idx = np.zeros((B,), np.int32)
        mm_extra = None
        mm_mask = None
        for i, w in enumerate(works):
            t = len(w.tokens)
            tokens[i, :t] = w.tokens
            positions[i, :t] = np.arange(w.start_pos, w.start_pos + t)
            for j in range(t):
                pos = w.start_pos + j
                slot_mapping[i * T + j] = (
                    w.seq.block_table[pos // bs] * bs + pos % bs
                )
            tables[i, : len(w.seq.block_table)] = w.seq.block_table
            ctx[i] = w.start_pos + t
            last_idx[i] = t - 1
            mm = self._mm_chunk_arrays(w.seq, w.start_pos, t, T)
            if mm is not None:
                if mm_extra is None:
                    D = mm["extra_embeds"].shape[-1]
                    mm_extra = np.zeros((B, T, D), np.float32)
                    mm_mask = np.zeros((B, T), bool)
                mm_extra[i] = mm["extra_embeds"][0]
                mm_mask[i] = mm["embeds_mask"][0]
        arrays = {
            "tokens": tokens,
            "positions": positions,
            "slot_mapping": slot_mapping,
            "block_tables": tables,
            "context_lens": ctx,
            "last_token_idx": last_idx,
        }
        if mm_extra is not None:
            arrays["extra_embeds"] = mm_extra
            arrays["embeds_mask"] = mm_mask
        return arrays

    @staticmethod
    def _mm_chunk_arrays(
        seq: Sequence, start: int, t: int, T: int
    ) -> Optional[dict[str, np.ndarray]]:
        """Embedding-injection arrays for the chunk [start, start+t), or
        None if no multimodal segment overlaps it (models/llama.py
        forward(extra_embeds=, embeds_mask=))."""
        if not seq.mm_segments:
            return None
        end = start + t
        D = seq.mm_segments[0][1].shape[-1]
        extra = np.zeros((1, T, D), np.float32)
        mask = np.zeros((1, T), bool)
        hit = False
        for offset, arr in seq.mm_segments:
            lo = max(start, offset)
            hi = min(end, offset + arr.shape[0])
            if lo >= hi:
                continue
            hit = True
            extra[0, lo - start : hi - start] = arr[lo - offset : hi - offset]
            mask[0, lo - start : hi - start] = True
        if not hit:
            return None
        return {"extra_embeds": extra, "embeds_mask": mask}

    def build_decode_arrays(self, seqs: list[Sequence]) -> dict[str, np.ndarray]:
        bs = self.block_size
        n = len(seqs)
        B = self._decode_batch(n)
        max_blocks = max(len(s.block_table) for s in seqs)
        width = self._table_width(max_blocks)
        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros((B, 1), np.int32)
        slot_mapping = np.zeros((B,), np.int32)
        tables = np.zeros((B, width), np.int32)
        ctx = np.zeros((B,), np.int32)
        # steps of the fused decode window each sequence will actually
        # keep — mirrors _plan_decode's lookahead clamp, so the device
        # step never writes KV past the blocks allocated for the seq
        valid_steps = np.zeros((B,), np.int32)
        for i, s in enumerate(seqs):
            all_toks = s.tokens.all_tokens()
            tokens[i, 0] = all_toks[-1]
            pos = s.total_len - 1
            positions[i, 0] = pos
            slot_mapping[i] = s.block_table[pos // bs] * bs + pos % bs
            tables[i, : len(s.block_table)] = s.block_table
            ctx[i] = s.total_len
            valid_steps[i] = self._seq_lookahead(s)
        return {
            "valid_steps": valid_steps,
            "tokens": tokens,
            "positions": positions,
            "slot_mapping": slot_mapping,
            "block_tables": tables,
            "context_lens": ctx,
            "last_token_idx": np.zeros((B,), np.int32),
        }
