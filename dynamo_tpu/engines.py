"""Built-in test engines + engine glue types.

Analogue of the reference's engines glue (reference:
lib/llm/src/engines.rs:41-296 — EchoEngineCore/EchoEngineFull,
MultiNodeConfig). Echo engines validate the full pipeline without a model:
``EchoEngineCore`` is tokens-in/tokens-out (sits behind the preprocessor +
backend), ``EchoEngineFull`` is OpenAI-in/OpenAI-out.
"""

from __future__ import annotations

import asyncio
import os
from dataclasses import dataclass
from typing import Any, AsyncIterator

from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream

# Per-token delay knob, ≈ reference DYN_TOKEN_ECHO_DELAY_MS (engines.rs:66-75)
TOKEN_ECHO_DELAY_MS = float(os.environ.get("DYN_TOKEN_ECHO_DELAY_MS", "10"))


@dataclass
class MultiNodeConfig:
    """Multi-host engine bring-up settings (reference: engines.rs:41-58).

    For JAX engines these feed jax.distributed.initialize: the leader is the
    coordinator address, node_rank the process index.
    """

    num_nodes: int = 1
    node_rank: int = 0
    leader_addr: str = ""


class EchoEngineCore(AsyncEngine):
    """Tokens-in/tokens-out echo: streams the prompt back one token at a
    time, honoring max_tokens and cancellation."""

    async def _gen(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[LLMEngineOutput]:
        if not isinstance(request, PreprocessedRequest):
            request = PreprocessedRequest.model_validate(request)
        delay = TOKEN_ECHO_DELAY_MS / 1000.0
        max_tokens = request.stop.max_tokens
        if max_tokens is None:
            max_tokens = len(request.token_ids)
        emitted = 0
        for tok in request.token_ids:
            if context.is_stopped or emitted >= max_tokens:
                break
            if delay:
                await asyncio.sleep(delay)
            yield LLMEngineOutput(request_id=request.request_id, token_ids=[int(tok)])
            emitted += 1
        reason = (
            FinishReason.CANCELLED if context.is_stopped else FinishReason.LENGTH
        )
        yield LLMEngineOutput(
            request_id=request.request_id,
            finish_reason=reason,
            prompt_tokens=len(request.token_ids),
            completion_tokens=emitted,
        )

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)


class PythonStrEngine(AsyncEngine):
    """Hosts a user Python file as a text-in/text-out streaming engine
    (reference: lib/engines/python hosting a user generator as a
    StreamingEngine, lib/engines/python/src/lib.rs:77-132; CLI
    ``out=pystr:<file.py>``).

    The file must define ``async def generate(request)`` yielding string
    deltas. ``request`` is a plain dict: ``{"model", "messages"|"prompt",
    "max_tokens", "temperature"}`` — the OpenAI request flattened to what
    a bring-your-own-engine script needs.
    """

    def __init__(self, path: str):
        import importlib.util

        spec = importlib.util.spec_from_file_location("dynamo_pystr_engine", path)
        if spec is None or spec.loader is None:
            raise ValueError(f"cannot load python engine from {path!r}")
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        if not hasattr(module, "generate"):
            raise ValueError(f"{path!r} defines no generate()")
        self._generate = module.generate
        self.path = path

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        from dynamo_tpu.protocols.openai import (
            ChatCompletionRequest,
            ChatDeltaGenerator,
            CompletionDeltaGenerator,
            CompletionRequest,
        )

        payload: dict[str, Any] = {"model": getattr(request, "model", "")}
        if isinstance(request, ChatCompletionRequest):
            payload["messages"] = [
                {"role": m.role, "content": m.text_content()}
                for m in request.messages
            ]
            gen = ChatDeltaGenerator(model=request.model)
        else:
            assert isinstance(request, CompletionRequest)
            if not isinstance(request.prompt, str):
                # list-of-prompts / token-id forms would silently become
                # "" — surface a client error instead
                raise ValueError("pystr engine requires a string prompt")
            payload["prompt"] = request.prompt
            gen = CompletionDeltaGenerator(model=request.model)
        for field in ("max_tokens", "temperature"):
            val = getattr(request, field, None)
            if val is not None:
                payload[field] = val
        async for delta in self._generate(payload):
            if context.is_stopped:
                break
            yield gen.text_chunk(str(delta))
        yield gen.finish_chunk(FinishReason.STOP)

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)


class EchoEngineFull(AsyncEngine):
    """OpenAI-in/OpenAI-out echo: no tokenization at all; streams the last
    message's text back word by word."""

    async def _gen(self, request: Any, context: Context) -> AsyncIterator[Any]:
        from dynamo_tpu.protocols.openai import (
            ChatCompletionRequest,
            ChatDeltaGenerator,
            CompletionDeltaGenerator,
            CompletionRequest,
        )

        delay = TOKEN_ECHO_DELAY_MS / 1000.0
        if isinstance(request, ChatCompletionRequest):
            text = request.messages[-1].text_content() if request.messages else ""
            gen = ChatDeltaGenerator(model=request.model)
        else:
            assert isinstance(request, CompletionRequest)
            text = request.prompt if isinstance(request.prompt, str) else ""
            gen = CompletionDeltaGenerator(model=request.model)
        for word in text.split(" "):
            if context.is_stopped:
                break
            if delay:
                await asyncio.sleep(delay)
            yield gen.text_chunk(word + " ")
        yield gen.finish_chunk(FinishReason.STOP)

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)
