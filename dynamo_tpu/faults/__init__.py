"""Deterministic, seed-driven fault injection (docs/robustness.md).

Usage::

    from dynamo_tpu import faults

    # sync hot path (engine thread):
    faults.fire("engine.step", kind=plan.kind)

    # async hot path — guard so no coroutine is created when disabled:
    if faults.ACTIVE is not None:
        await faults.ACTIVE.fire_async("store.call", op=op)

Activate via ``DYN_FAULTS`` (CLI startup calls ``init_from_env()``), a
JSON plan file (``DYN_FAULTS=@plan.json``), or programmatically with
``activate(FaultPlan(...))`` in tests.
"""

from dynamo_tpu.faults import injector as _injector
from dynamo_tpu.faults.injector import (
    ENV_VAR,
    FaultInjector,
    activate,
    deactivate,
    fire,
    init_from_env,
)
from dynamo_tpu.faults.plan import (
    DroppedFrameError,
    FaultInjectedError,
    FaultPlan,
    FaultRule,
    parse_plan,
    parse_rule,
)


def __getattr__(name: str):
    # ACTIVE lives on the injector module (activate/deactivate rebind
    # it); forward attribute access so `faults.ACTIVE` is always current
    if name == "ACTIVE":
        return _injector.ACTIVE
    raise AttributeError(name)


__all__ = [
    "ACTIVE",
    "ENV_VAR",
    "DroppedFrameError",
    "FaultInjectedError",
    "FaultInjector",
    "FaultPlan",
    "FaultRule",
    "activate",
    "deactivate",
    "fire",
    "init_from_env",
    "parse_plan",
    "parse_rule",
]
