"""FaultInjector: the runtime half of the fault-injection subsystem.

One process-global injector (module attribute ``ACTIVE``) evaluates the
active :class:`FaultPlan` at named injection points. The points live at
the stack's existing failure seams (docs/robustness.md catalogs them):

    http.request        frontend request handling (async)
    transport.send      worker data-plane frame send (async)
    transport.recv      worker data-plane frame receive (async)
    store.call          coordinator-store client op (async; ctx: op)
    prefill.dequeue     prefill-queue pop (async)
    kv_transfer.put     disagg KV block shipment, sender side (async)
    kv_transfer.get     disagg KV block delivery, receiver side (async)
    engine.step         one engine device step (sync, engine thread)
    worker.liveness     engine step-loop heartbeat (sync; kill target)
    store.publish_drain DRAINING-flag publish during graceful drain
                        (async; ctx: instance — error = routers learn
                        from lease expiry instead)
    worker.drain        proactive stream handoff during graceful drain
                        (async; ctx: instance — stall/error exercises
                        the drain-deadline reactive fallback)

Hot-path contract: when no plan is active, every hook is a module
attribute load plus an ``is None`` check — no coroutine creation, no
locking, no allocation. Call sites therefore guard explicitly::

    from dynamo_tpu import faults
    ...
    if faults.ACTIVE is not None:
        await faults.ACTIVE.fire_async("transport.send", request_id=rid)

Sync points use :func:`fire`, which does the same guard internally.

Every fired fault increments ``dynamo_faults_fired_total{point,kind}``,
lands in the injector's bounded ring (served under ``/debug/state`` →
``"faults"``), and is forwarded to any registered listeners (the engine
forwards engine-thread faults into its flight recorder).
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from dynamo_tpu.faults.plan import (
    FaultPlan,
    FaultRule,
    RuleState,
    parse_plan,
)
from dynamo_tpu.telemetry.debug import (
    register_debug_provider,
    unregister_debug_provider,
)
from dynamo_tpu.telemetry.instruments import FAULTS_FIRED

log = logging.getLogger("dynamo_tpu.faults")

ENV_VAR = "DYN_FAULTS"

# how a `kill` rule takes the process down: os._exit skips atexit /
# finally blocks, which is the point — a SIGKILL'd worker doesn't clean
# up either. Tests monkeypatch this module attribute.
_kill_process: Callable[[int], None] = os._exit
KILL_EXIT_CODE = 70


class _RuleState(RuleState):
    """The shared eligibility state (plan.RuleState — the sim driver
    runs the identical ``step()``) plus the injector-only ``ephemeral``
    flag: request-scoped (header-armed) rules are pruned once exhausted
    so a chaos soak never accumulates dead rules."""

    __slots__ = ("ephemeral",)

    def __init__(self, rule: FaultRule, rng, ephemeral: bool = False):
        super().__init__(rule, rng)
        self.ephemeral = ephemeral


class FaultInjector:
    # cross-thread contract (dynalint DL103 vocabulary): fire() is
    # called from every domain at once — the engine thread
    # (engine.step, worker.liveness), the event loop (http.request,
    # transport), planner-side store calls. All mutable state
    # (_states counters incl. the one-shot kill arming, fired_total,
    # _fired_ring) flips only under _lock — the declared handoff.
    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._states = [
            _RuleState(rule, plan.rule_rng(i))
            for i, rule in enumerate(plan.rules)
        ]
        # (point -> states) index so a pass through a point only touches
        # its own rules
        self._by_point: dict[str, list[_RuleState]] = {}
        for st in self._states:
            self._by_point.setdefault(st.rule.point, []).append(st)
        self.fired_total = 0
        # bounded forensic ring, mirrored into /debug/state
        self._fired_ring: deque = deque(maxlen=256)
        self._listeners: list[Callable[[dict], None]] = []

    # -- evaluation -------------------------------------------------------
    def _due(self, point: str, ctx: dict) -> list[FaultRule]:
        """Advance counters for one pass through ``point``; return the
        rules that fire (usually 0 or 1)."""
        states = self._by_point.get(point)
        if not states:
            return []
        due: list[FaultRule] = []
        prune = False
        with self._lock:
            for st in states:
                if st.step(ctx):
                    due.append(st.rule)
                prune = prune or (st.ephemeral and st.exhausted)
            if prune:
                # header-armed rules die with their last fire; plan
                # rules keep their state for stats()
                self._prune_exhausted_ephemerals_locked()
        for rule in due:
            self._note_fired(rule, ctx)
        return due

    def _note_fired(self, rule: FaultRule, ctx: dict) -> None:
        FAULTS_FIRED.labels(rule.point, rule.kind).inc()
        rec = {
            "ts": time.time(),
            "point": rule.point,
            "kind": rule.kind,
            "value": rule.value,
        }
        rec.update({k: str(v) for k, v in ctx.items()})
        with self._lock:
            self.fired_total += 1
            self._fired_ring.append(rec)
        log.warning(
            "fault fired: %s %s%s ctx=%s", rule.point, rule.kind,
            f"={rule.value}" if rule.value is not None else "", ctx,
        )
        rid = ctx.get("request_id")
        if rid:
            # request autopsy: a fault that fired WITH a request id in
            # scope lands on that request's timeline and flags it for
            # exemplar retention (import here: faults is imported by
            # layers below telemetry)
            from dynamo_tpu.telemetry import autopsy

            autopsy.note_event(
                str(rid), "fault", flag="faulted",
                point=rule.point, fault_kind=rule.kind,
            )
        for listener in list(self._listeners):
            try:
                listener(rec)
            except Exception:
                log.exception("fault listener failed")

    def _act_sync(self, rule: FaultRule) -> None:
        if rule.kind in ("delay", "stall"):
            time.sleep(rule.delay_s)
        elif rule.kind == "kill":
            log.error("fault kill at %s: exiting process", rule.point)
            _kill_process(KILL_EXIT_CODE)
        else:
            raise rule.exc()

    async def _act_async(self, rule: FaultRule) -> None:
        if rule.kind in ("delay", "stall"):
            await asyncio.sleep(rule.delay_s)
        elif rule.kind == "kill":
            log.error("fault kill at %s: exiting process", rule.point)
            _kill_process(KILL_EXIT_CODE)
        else:
            raise rule.exc()

    # -- public hooks -----------------------------------------------------
    def fire(self, point: str, **ctx) -> None:
        """Sync injection point (engine thread / non-async code)."""
        for rule in self._due(point, ctx):
            self._act_sync(rule)

    async def fire_async(self, point: str, **ctx) -> None:
        """Async injection point (event-loop code). Delays await."""
        for rule in self._due(point, ctx):
            await self._act_async(rule)

    # -- request-scoped rules (X-Dyn-Fault header) ------------------------
    # hard cap on live header-armed rules: rules whose request never
    # reaches their point would otherwise accumulate for the plan's
    # lifetime (the oldest are dropped past the cap)
    MAX_REQUEST_RULES = 256

    def _prune_exhausted_ephemerals_locked(self) -> None:
        dead = [
            st for st in self._states if st.ephemeral and st.exhausted
        ]
        if not dead:
            return
        dead_set = set(map(id, dead))
        self._states = [
            st for st in self._states if id(st) not in dead_set
        ]
        for point in {st.rule.point for st in dead}:
            self._by_point[point] = [
                st for st in self._by_point.get(point, ())
                if id(st) not in dead_set
            ]

    def _drop_oldest_ephemerals_locked(self, keep: int) -> None:
        live = [st for st in self._states if st.ephemeral]
        for st in live[: max(0, len(live) - keep)]:
            self._states.remove(st)
            self._by_point[st.rule.point].remove(st)

    def arm_request(self, spec: str, request_id: str) -> int:
        """Append header-supplied rules scoped to ``request_id`` (their
        ``match`` is forced to the id; ``max`` defaults to 1). Only
        honored when the active plan opted in (``allow_request_rules``).
        Armed rules are EPHEMERAL: pruned once exhausted, and capped at
        MAX_REQUEST_RULES live rules overall. Returns the number armed."""
        if not self.plan.allow_request_rules:
            return 0
        plan = parse_plan(spec)
        armed = 0
        with self._lock:
            base = len(self._states)
            for i, rule in enumerate(plan.rules):
                rule.match = request_id
                if rule.max_fires is None:
                    rule.max_fires = 1
                st = _RuleState(
                    rule, _rng_for(self.plan.seed, rule, base + i),
                    ephemeral=True,
                )
                self._states.append(st)
                self._by_point.setdefault(rule.point, []).append(st)
                armed += 1
            self._drop_oldest_ephemerals_locked(self.MAX_REQUEST_RULES)
        return armed

    def add_listener(self, listener: Callable[[dict], None]) -> None:
        self._listeners.append(listener)

    # -- introspection ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "seed": self.plan.seed,
                "rules": [
                    {**st.rule.to_dict(), "passes": st.passes,
                     "fires": st.fires}
                    for st in self._states
                ],
                "fired_total": self.fired_total,
                "recent": list(self._fired_ring)[-32:],
            }


def _rng_for(seed: int, rule: FaultRule, index: int):
    """Per-rule rng for request-scoped (header-armed) rules; index-keyed
    seeding keeps them deterministic for a fixed arrival order."""
    import random

    return random.Random(f"{seed}:{rule.point}:{index}")


# ---------------------------------------------------------------------------
# Process-global activation
# ---------------------------------------------------------------------------

ACTIVE: Optional[FaultInjector] = None


def activate(plan: FaultPlan) -> FaultInjector:
    """Install ``plan`` as the process's active fault plan."""
    global ACTIVE
    deactivate()
    ACTIVE = FaultInjector(plan)
    register_debug_provider("faults", ACTIVE.stats)
    log.warning(
        "fault injection ACTIVE: seed=%d, %d rule(s)",
        plan.seed, len(plan.rules),
    )
    return ACTIVE


def deactivate() -> None:
    global ACTIVE
    if ACTIVE is not None:
        unregister_debug_provider("faults", ACTIVE.stats)
        ACTIVE = None


def init_from_env() -> Optional[FaultInjector]:
    """Activate a plan from ``DYN_FAULTS`` if set (CLI startup calls
    this); returns the injector or None."""
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        return None
    try:
        return activate(parse_plan(spec))
    except Exception:
        # a malformed plan must not take the process down — but it must
        # be LOUD: silently serving without the chaos you asked for
        # invalidates the experiment
        log.exception("malformed %s ignored: %r", ENV_VAR, spec)
        return None


def fire(point: str, **ctx) -> None:
    """Module-level sync hook: no-op unless a plan is active."""
    inj = ACTIVE
    if inj is not None:
        inj.fire(point, **ctx)
