"""FaultPlan: the declarative description of which faults to inject where.

A plan is a seed plus an ordered list of rules. Sources (docs/
robustness.md):

- the ``DYN_FAULTS`` environment variable (compact string syntax),
- a JSON file (``DYN_FAULTS=@/path/plan.json``),
- a per-request ``X-Dyn-Fault`` header (parsed with the same syntax and
  scoped to one request id; only honored when the active plan allows
  it — see injector.arm_request).

Compact syntax — ``;``-separated elements, each either a plan-level
``key=value`` setting or a rule::

    DYN_FAULTS="seed=42;store.call:delay=0.05@p=0.5;engine.step:error@after=3@max=2"

Rule grammar: ``point:kind[=value][@mod=value]...``

kinds
    ``delay=S``   sleep S seconds at the point (async points await)
    ``stall=S``   alias of delay with a 30 s default — "hung peer"
    ``error[=E]`` raise (E: ``conn`` ConnectionError, ``os`` OSError,
                  ``timeout`` asyncio.TimeoutError, ``runtime``/default
                  FaultInjectedError)
    ``drop``      raise DroppedFrameError (a ConnectionError): at
                  transport points the existing connection-loss
                  handling turns this into a realistic peer-vanished
                  teardown
    ``kill``      terminate THIS process (one-shot worker death);
                  implies max=1 unless overridden

modifiers
    ``@p=0.3``      fire with probability 0.3 (seeded, per-rule stream)
    ``@after=N``    skip the first N passes through the point
    ``@max=M``      fire at most M times (kill defaults to 1)
    ``@match=S``    fire only when some string context value (e.g.
                    request_id, op name) contains S

Determinism: every rule draws from its own ``random.Random`` seeded
from ``(plan seed, point, rule index)``, so the fire pattern at one
point is a pure function of the seed and that point's call sequence —
independent of scheduling interleave across points.
"""

from __future__ import annotations

import asyncio
import json
import random
from dataclasses import dataclass, field
from typing import Optional


class FaultInjectedError(RuntimeError):
    """Default error raised by ``error`` rules."""


class DroppedFrameError(ConnectionError):
    """Raised by ``drop`` rules: call sites treat it as a lost peer."""


KINDS = ("delay", "stall", "error", "drop", "kill")

_ERROR_TYPES = {
    "": FaultInjectedError,
    "runtime": FaultInjectedError,
    "conn": ConnectionError,
    "connection": ConnectionError,
    "os": OSError,
    "timeout": asyncio.TimeoutError,
}


@dataclass
class FaultRule:
    point: str
    kind: str  # one of KINDS
    value: Optional[str] = None  # seconds for delay/stall, exc name for error
    p: float = 1.0
    after: int = 0
    max_fires: Optional[int] = None
    match: Optional[str] = None

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (known: {', '.join(KINDS)})"
            )
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"fault probability out of [0,1]: {self.p}")
        if self.kind in ("delay", "stall"):
            float(self.delay_s)  # validate at parse time, not at fire time
        if self.kind == "error" and (self.value or "") not in _ERROR_TYPES:
            raise ValueError(
                f"unknown error type {self.value!r} "
                f"(known: {', '.join(k for k in _ERROR_TYPES if k)})"
            )
        if self.kind == "kill" and self.max_fires is None:
            self.max_fires = 1

    @property
    def delay_s(self) -> float:
        if self.value is not None:
            return float(self.value)
        return 30.0 if self.kind == "stall" else 0.0

    def exc(self) -> BaseException:
        if self.kind == "drop":
            return DroppedFrameError(
                f"injected frame drop at {self.point}"
            )
        return _ERROR_TYPES[self.value or ""](
            f"injected fault at {self.point}"
        )

    def to_dict(self) -> dict:
        return {
            "point": self.point, "kind": self.kind, "value": self.value,
            "p": self.p, "after": self.after, "max": self.max_fires,
            "match": self.match,
        }


@dataclass
class FaultPlan:
    seed: int = 0
    rules: list[FaultRule] = field(default_factory=list)
    # whether per-request X-Dyn-Fault headers may append scoped rules
    allow_request_rules: bool = False

    def rule_rng(self, index: int) -> random.Random:
        rule = self.rules[index]
        return random.Random(f"{self.seed}:{rule.point}:{index}")

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "allow_request_rules": self.allow_request_rules,
            "rules": [r.to_dict() for r in self.rules],
        }


class RuleState:
    """Mutable evaluation state for one rule: counters plus the rule's
    seeded stream. ``step()`` is THE eligibility algorithm — match →
    after → max → p, in that order, one RNG draw per probabilistic
    pass. Both evaluation planes (the live ``FaultInjector`` and the
    simulator's ``SimFaultDriver``) run this exact method, so a chaos
    plan replayed as a what-if cannot drift from live behavior: any
    future mod (a new gate, a reordering) lands in both at once."""

    __slots__ = ("rule", "rng", "passes", "fires")

    def __init__(self, rule: FaultRule, rng: random.Random):
        self.rule = rule
        self.rng = rng
        self.passes = 0
        self.fires = 0

    @property
    def exhausted(self) -> bool:
        return (
            self.rule.max_fires is not None
            and self.fires >= self.rule.max_fires
        )

    def step(self, ctx: dict) -> bool:
        """One pass of this rule's point; True when the rule fires."""
        rule = self.rule
        if rule.match is not None and not any(
            rule.match in str(v) for v in ctx.values()
        ):
            return False
        self.passes += 1
        if self.passes <= rule.after:
            return False
        if self.exhausted:
            return False
        if rule.p < 1.0 and self.rng.random() >= rule.p:
            return False
        self.fires += 1
        return True


def parse_rule(text: str) -> FaultRule:
    """One ``point:kind[=value][@mod=value]...`` element."""
    text = text.strip()
    head, *mods = text.split("@")
    if ":" not in head:
        raise ValueError(
            f"fault rule {text!r} needs point:kind (e.g. store.call:error)"
        )
    point, _, kind_part = head.partition(":")
    kind, _, value = kind_part.partition("=")
    kw: dict = {}
    for mod in mods:
        key, eq, val = mod.strip().partition("=")
        if not eq:
            raise ValueError(f"fault modifier {mod!r} needs key=value")
        if key == "p":
            kw["p"] = float(val)
        elif key == "after":
            kw["after"] = int(val)
        elif key == "max":
            kw["max_fires"] = int(val)
        elif key == "match":
            kw["match"] = val
        else:
            raise ValueError(
                f"unknown fault modifier {key!r} (known: p, after, max, match)"
            )
    return FaultRule(
        point=point.strip(), kind=kind.strip(),
        value=value if value != "" else None, **kw,
    )


def parse_plan(spec: str) -> FaultPlan:
    """Parse the compact ``DYN_FAULTS`` syntax (or ``@path`` / JSON)."""
    spec = spec.strip()
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            spec = f.read().strip()
    if spec.startswith("{"):
        return plan_from_dict(json.loads(spec))
    plan = FaultPlan()
    for element in spec.split(";"):
        element = element.strip()
        if not element:
            continue
        if element.startswith("seed="):
            plan.seed = int(element[len("seed="):])
        elif element in ("header", "header=1"):
            plan.allow_request_rules = True
        else:
            plan.rules.append(parse_rule(element))
    return plan


def plan_from_dict(data: dict) -> FaultPlan:
    rules = []
    for r in data.get("rules", []):
        rules.append(
            FaultRule(
                point=r["point"], kind=r["kind"], value=r.get("value"),
                p=float(r.get("p", 1.0)), after=int(r.get("after", 0)),
                max_fires=(
                    int(r["max"]) if r.get("max") is not None else None
                ),
                match=r.get("match"),
            )
        )
    return FaultPlan(
        seed=int(data.get("seed", 0)),
        rules=rules,
        allow_request_rules=bool(data.get("allow_request_rules", False)),
    )
