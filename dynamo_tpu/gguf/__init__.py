"""GGUF model-file support (reference: lib/llm/src/gguf/*.rs — GGUF
metadata/content parsing + embedded-tokenizer extraction + model-card
creation from GGUF)."""

from dynamo_tpu.gguf.reader import (
    GGUFReader,
    GGUFTensorInfo,
    config_from_gguf,
    load_params_from_gguf,
    tokenizer_from_gguf,
    write_gguf,
)

__all__ = [
    "GGUFReader",
    "GGUFTensorInfo",
    "config_from_gguf",
    "load_params_from_gguf",
    "tokenizer_from_gguf",
    "write_gguf",
]
